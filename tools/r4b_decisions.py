"""Render the round-4b chip artifacts into playbook decisions.

Usage: python tools/r4b_decisions.py [tools/sweep_results/r4b]

Reads the staged collection's raw JSONs and evaluates each
pre-registered decision from docs/chip_playbook.md (round-4b table),
printing one line per decision: the measured numbers, the threshold,
and the action (default flip / keep / record-bound). Pure file
reading — safe to run any time; missing artifacts print as PENDING.
"""

import json
import os
import sys


def _load(d, name):
    p = os.path.join(d, f"{name}.json")
    try:
        if os.path.getsize(p) == 0:
            return None
        with open(p) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        return json.loads(lines[-1])
    except (OSError, ValueError, IndexError):
        return None


def _eps(doc):
    if doc is None:
        return None
    return doc.get("epochs_per_s") or doc.get("value")


def main() -> None:
    d = sys.argv[1] if len(sys.argv) > 1 else "tools/sweep_results/r4b"
    if not os.path.isdir(d):
        sys.exit(f"no such directory: {d}")

    # r4 reference numbers (tools/sweep_results/r4, BASELINE.md)
    R4 = {
        "block_ingest": 1.15e6,
        "regular_partial": 5.40e6,
        "train_step_raw_phase": 4.59e6,
        "train_step_block": 1.34e6,
        "train_step_131k": 24.14e6,
        "einsum_262k": 47.50e6,
        "einsum_roofline_pct": 69.6,
    }

    def line(name, verdict):
        print(f"{name:22s} {verdict}")

    def pending(name):
        line(name, "PENDING (no artifact)")

    b32 = _load(d, "bank128_32k")
    b131 = _load(d, "bank128_131k")
    bank = _eps(b131) or _eps(b32)
    if bank is None:
        pending("bank128")
    else:
        ratio = bank / R4["block_ingest"]
        act = (
            "FLIP default_fused_backend accelerator branch block->pallas"
            if ratio >= 2
            else "keep block default; record the bound"
        )
        line(
            "bank128",
            f"{bank/1e6:.2f}M eps = {ratio:.1f}x block(1.15M) -> {act}",
        )

    rb = _load(d, "regular_bank")
    if rb is None:
        pending("regular_bank")
    else:
        eps = _eps(rb)
        act = (
            "FLIP resolve_regular_formulation('auto') accelerator -> bank"
            if eps and eps > R4["regular_partial"]
            else "keep partial/phase; record why"
        )
        line("regular_bank", f"{(eps or 0)/1e6:.2f}M vs partial 5.40M -> {act}")

    e524 = _load(d, "einsum_524k")
    if e524 is None:
        pending("einsum_524k")
    else:
        eps = _eps(e524)
        act = (
            "raise BENCH_BATCH default to 524288"
            if eps and eps > R4["einsum_262k"] * 1.05
            else "keep 262144"
        )
        line("einsum_524k", f"{(eps or 0)/1e6:.2f}M vs 47.50M @262k -> {act}")

    for name, bytes_ok in (("einsum_sliced", False), ("einsum_512", True)):
        doc = _load(d, name)
        if doc is None:
            pending(name)
            continue
        pct = doc.get("pct_of_hbm_roofline")
        eps = _eps(doc)
        if name == "einsum_512":
            act = (
                "make compact-resident the headline row "
                "(fe=dwt-8-tpu-compact shipped); state 6144 B/epoch"
                if pct and pct >= 65
                else "full-width stands; write the accounting caveat"
            )
        else:
            act = (
                "subrange read fuses: report effective bytes"
                if pct and pct > 100
                else "XLA reads dead columns; compact is the honest win"
            )
        line(name, f"{(eps or 0)/1e6:.2f}M eps, {pct}% roofline -> {act}")

    eb = _load(d, "einsum_512_bf16")
    if eb is None:
        pending("einsum_512_bf16")
    else:
        pct = eb.get("pct_of_hbm_roofline")
        act = (
            "compact-bf16 is the absolute-throughput tier "
            "(fe=dwt-8-tpu-compact-bf16 shipped)"
            if pct and pct >= 65
            else "record which effect failed to compound"
        )
        line(
            "einsum_512_bf16",
            f"{(_eps(eb) or 0)/1e6:.2f}M eps, {pct}% roofline -> {act}",
        )

    r1 = _load(d, "rf_predict_retry")
    r2 = _load(d, "rf_predict_chunked")
    if r1 is None and r2 is None:
        pending("rf_predict")
    elif r1 is not None:
        line(
            "rf_predict",
            f"retry ok ({(_eps(r1) or 0)/1e3:.1f}k rows/s) -> r4 fault "
            f"was transient; keep full predict default",
        )
    else:
        line(
            "rf_predict",
            f"retry faulted, chunked "
            f"{'ok (' + format((_eps(r2) or 0)/1e3, '.1f') + 'k rows/s)' if r2 else 'ALSO faulted'}"
            f" -> {'make row-chunked the device predict default' if r2 else 'construct fault: bisect the walk'}",
        )

    t262 = _load(d, "train_step_262k")
    if t262 is None:
        pending("train_step_262k")
    else:
        eps = _eps(t262)
        recovered = eps and eps > R4["train_step_131k"] * 1.5
        line(
            "train_step_262k",
            f"{(eps or 0)/1e6:.2f}M vs 24.14M @131k -> "
            f"{'dispatch amortization confirmed; raise bench train batch' if recovered else 'not dispatch: read cost_train bytes_ratio'}",
        )

    t512 = _load(d, "train_step_512")
    if t512 is None:
        pending("train_step_512")
    else:
        line(
            "train_step_512",
            f"{(_eps(t512) or 0)/1e6:.2f}M at 6144 B/epoch (pair with "
            f"einsum_512's flip decision)",
        )

    tb = _load(d, "train_bank")
    if tb is None:
        pending("train_bank")
    else:
        eps = _eps(tb)
        line(
            "train_bank",
            f"{(eps or 0)/1e6:.2f}M vs train_step_block 1.34M -> "
            f"{'bank wins irregular training' if eps and eps > 1.34e6 else 'block stands'}",
        )

    trb = _load(d, "train_raw_bank")
    if trb is None:
        pending("train_raw_bank")
    else:
        eps = _eps(trb)
        line(
            "train_raw_bank",
            f"{(eps or 0)/1e6:.2f}M vs phase 4.59M -> "
            f"{'bank wins raw training' if eps and eps > 4.59e6 else 'phase stands'}",
        )

    be = _load(d, "bench_early") or _load(d, "bench_full")
    if be is None:
        pending("bench (driver format)")
    else:
        line(
            "driver bench",
            f"value {be.get('value', 0)/1e6:.2f}M, platform "
            f"{be.get('platform', 'tpu')} -> chip_evidence source for "
            f"every later bench line",
        )


if __name__ == "__main__":
    main()
