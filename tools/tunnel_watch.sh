#!/bin/bash
# Watch the axon TPU tunnel; when it recovers, immediately collect the
# measurements that are blocked on it, then stop. Safe by constraint:
# everything it runs is jit-only (never eager through the tunnel), the
# probe is kill-free (it returns on its own — tools/probe_tpu.py), and
# nothing is killed mid-compile (generous timeouts, sequential).
#
#   nohup setsid bash tools/tunnel_watch.sh /tmp/tunnel_watch > /dev/null 2>&1 &
#
# Status: $OUT/watch.log; results: $OUT/*.json
set -u
cd "$(dirname "$0")/.."
OUT=$(readlink -f "${1:-/tmp/tunnel_watch}")
mkdir -p "$OUT"
log() { echo "$(date +%H:%M:%S) $*" >> "$OUT/watch.log"; }

log "watch started (kill-free probe)"
while :; do
  # NO external timeout on the probe: SIGTERM on an axon-INITIALIZING
  # process is the known tunnel-wedging event. The probe returns by
  # itself — ok JSON on a healthy tunnel, an UNAVAILABLE error after
  # ~25 min on a down-but-failing-fast one; on a truly wedged tunnel
  # it hangs and this watcher waits with it.
  python tools/probe_tpu.py > "$OUT/probe.out" 2>> "$OUT/probe.err"
  if grep -q '"ok": true' "$OUT/probe.out" \
      && grep -Eq '"platform": "(axon|tpu)"' "$OUT/probe.out"; then
    log "tunnel recovered: $(cat "$OUT/probe.out")"
    break
  fi
  log "probe not-ok: $(tail -c 200 "$OUT/probe.out")"
  sleep 600
done

run() { # name timeout cmd...
  name=$1; t=$2; shift 2
  log "run $name"
  timeout "$t" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  log "done $name rc=$? $(tail -c 300 "$OUT/$name.json")"
}

# the collection list: $2 overrides for targeted re-runs (default is
# the single shared list, also used by real_chip_sweep.sh)
source "${2:-tools/collect_chip_runs.sh}"
log "collection complete"
