#!/bin/bash
# Watch the axon TPU tunnel; when it recovers, immediately collect the
# measurements that are blocked on it, then stop. Safe by constraint:
# everything it runs is jit-only (never eager through the tunnel), the
# probe is kill-free (it returns on its own — tools/probe_tpu.py), and
# nothing is killed mid-compile (generous timeouts, sequential).
#
#   nohup setsid bash tools/tunnel_watch.sh /tmp/tunnel_watch > /dev/null 2>&1 &
#
# Status: $OUT/watch.log; results: $OUT/*.json
set -u
cd "$(dirname "$0")/.."
OUT=$(readlink -f "${1:-/tmp/tunnel_watch}")
mkdir -p "$OUT"
log() { echo "$(date +%H:%M:%S) $*" >> "$OUT/watch.log"; }

log "watch started (kill-free probe)"
while :; do
  # NO external timeout on the probe: SIGTERM on an axon-INITIALIZING
  # process is the known tunnel-wedging event. The probe returns by
  # itself — ok JSON on a healthy tunnel, an UNAVAILABLE error after
  # ~25 min on a down-but-failing-fast one; on a truly wedged tunnel
  # it hangs and this watcher waits with it.
  python tools/probe_tpu.py > "$OUT/probe.out" 2>> "$OUT/probe.err"
  if grep -q '"ok": true' "$OUT/probe.out" \
      && grep -Eq '"platform": "(axon|tpu)"' "$OUT/probe.out"; then
    log "tunnel recovered: $(cat "$OUT/probe.out")"
    break
  fi
  log "probe not-ok: $(tail -c 200 "$OUT/probe.out")"
  sleep 600
done

run() { # name timeout cmd...
  name=$1; t=$2; shift 2
  log "run $name"
  timeout "$t" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  log "done $name rc=$? $(tail -c 300 "$OUT/$name.json")"
}

# Order = evidence priority (VERDICT r2): the irregular-ingest
# fast-path numbers and the chip-staged rows first, the driver bench
# artifact once the core numbers are safe, Pallas (whose kernel
# crashes the remote compile helper) after everything XLA-only, and
# the compiler bisect DEAD LAST because a helper crash may re-wedge.
run parity        900 python tools/tpu_parity_check.py
run einsum        600 python tools/ingest_bench.py einsum 262144 50
run xla_ingest    900 python tools/ingest_bench.py xla_ingest 32768 10
run block_ingest  900 python tools/ingest_bench.py block_ingest 32768 10
BENCH_FORMULATION=phase run regular_phase 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=conv run regular_conv 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=reshape run regular_reshape 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
run train_raw     900 python tools/ingest_bench.py train_step_raw 131072 20
run train_block   900 python tools/ingest_bench.py train_step_block 32768 10
run rf_train      900 python tools/ingest_bench.py rf_train 65536 3
run rf_predict    600 python tools/ingest_bench.py rf_predict 262144 10
run einsum_flat   600 python tools/ingest_bench.py einsum_flat 262144 50
run einsum_2d     600 python tools/ingest_bench.py einsum_2d 262144 50
run einsum_bf16   600 python tools/ingest_bench.py einsum_bf16 262144 50
# bf16 roofline-gap diagnostics (VERDICT r2 item 4): layout A/B at
# 2-byte elements, plus batch-size halving/doubling for dispatch
# amortization
run einsum_bf16_flat 600 python tools/ingest_bench.py einsum_bf16_flat 262144 50
run einsum_bf16_131k 600 python tools/ingest_bench.py einsum_bf16 131072 50
run einsum_bf16_524k 600 python tools/ingest_bench.py einsum_bf16 524288 50
run train_step    600 python tools/ingest_bench.py train_step 131072 20
# outer timeout must exceed bench.py's worst case (probe 420 +
# variant budget 1500 + one variant overrun 420) so the watcher never
# SIGTERMs bench mid-variant
BENCH_TOTAL_BUDGET=1500 run bench_full 3600 python bench.py
# compile-only: XLA cost model (bytes/epoch) for the TPU-compiled hot
# programs — answers "does the compiled program move more bytes than
# the design assumed" for every below-roofline number above. 3600s:
# ~6 fresh chip compiles in one process; a SIGTERM mid-remote-compile
# is the wedging event, so this gets the most generous budget of all
# (and the tool prints each program's line as it completes, so even a
# timeout preserves the finished ones)
run cost_report  3600 python tools/cost_report.py 32768
# pallas_dwt first: it compiled to Mosaic on chip in round 2, so it
# separates "remote compiler regressed globally" from "the ingest
# kernel's construct delta (scalar-prefetch index maps / int16 loads /
# aliased inputs / dynamic lane slices) is the crasher"
run pallas_dwt    900 python tools/ingest_bench.py pallas_dwt 131072 20
run pallas_ingest 900 python tools/ingest_bench.py pallas_ingest 131072 20
# the 8-aligned-slice variant-bank kernel: the fix path if the exact
# kernel's arbitrary-offset lane slice is what crashes the compiler
BENCH_PALLAS_MODE=aligned8 run pallas_aligned8 900 \
  python tools/ingest_bench.py pallas_ingest 131072 20
run pallas_bisect 900 python tools/pallas_compile_bisect.py
log "collection complete"
