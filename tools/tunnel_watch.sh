#!/bin/bash
# Watch the axon TPU tunnel; when it recovers, immediately collect the
# measurements that are blocked on it, then stop. Safe by constraint:
# everything it runs is jit-only (never eager through the tunnel) and
# nothing is killed mid-compile (generous timeouts, sequential).
#
#   nohup setsid bash tools/tunnel_watch.sh /tmp/tunnel_watch > /dev/null 2>&1 &
#
# Status: $OUT/watch.log; results: $OUT/*.json
set -u
cd "$(dirname "$0")/.."
OUT=$(readlink -f "${1:-/tmp/tunnel_watch}")
mkdir -p "$OUT"
log() { echo "$(date +%H:%M:%S) $*" >> "$OUT/watch.log"; }

log "watch started"
while :; do
  # 240s probe timeout: SIGTERM on an axon-INITIALIZING process is the
  # known tunnel-wedging event, and a recovered-but-cold tunnel can
  # take minutes to init — never kill a probe that might be mid-init
  # on a healthy tunnel (same budget as real_chip_sweep.sh)
  if timeout 240 python -c "import jax; print(jax.devices()[0].platform)" \
      > "$OUT/probe.out" 2>/dev/null; then
    plat=$(cat "$OUT/probe.out")
    if [ "$plat" = "axon" ] || [ "$plat" = "tpu" ]; then
      log "tunnel recovered (platform $plat); collecting"
      break
    fi
  fi
  log "still wedged"
  sleep 600
done

run() { # name timeout cmd...
  name=$1; t=$2; shift 2
  log "run $name"
  timeout "$t" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  log "done $name rc=$? $(tail -c 200 "$OUT/$name.json")"
}

BENCH_FORMULATION=phase run regular_phase 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=conv run regular_conv 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=reshape run regular_reshape 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
run einsum 600 python tools/ingest_bench.py einsum 262144 50
run bench_full 1800 python bench.py
# LAST, after every measurement is safely on disk: the bisect probes
# the construct that crashes the remote compiler, and a helper crash
# may re-wedge the tunnel — nothing of value runs after it
run pallas_bisect 900 python tools/pallas_compile_bisect.py
log "collection complete"
