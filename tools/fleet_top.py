"""One fleet view: scrape every replica's /metrics + /stats and the
shared lease directory, and render the whole fleet as one table.

Usage:
    python tools/fleet_top.py http://h:p1 http://h:p2 [--journal DIR]
    python tools/fleet_top.py ... --snapshot        # strict JSON out

Per replica: throughput counters (plans completed, serve requests),
held leases, takeover count, the latency histogram's p50/p99, and the
per-tenant SLO verdicts off the replica's own /stats block. Fleet-
wide: the replicas' fixed-bucket histograms merged by exact integer
addition (obs/metrics_export.py — the merged p99 IS the histogram-p99
of the union of observations, not an approximation), summed counters,
and, with ``--journal``, the lease table joined straight off the
shared directory (who holds what, what is stale, what is claimable).

A replica that cannot be scraped renders as DOWN with the error —
the fleet view must degrade per-replica, never refuse the whole
table because one member is mid-restart.

``--snapshot`` emits the same data as one strict-JSON object
(non-finite floats -> null) for CI and the gateway_fleet bench line
(tools/pipeline_bench.py embeds it in the bench artifact).

Stdlib only, like every tool in this repo.
"""

import argparse
import json
import os
import sys
import urllib.error
import urllib.request


def _get_text(url: str, timeout_s: float = 10.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode("utf-8", "replace")


def _get_json(url: str, timeout_s: float = 10.0):
    return json.loads(_get_text(url, timeout_s=timeout_s))


def replica_snapshot(url: str, timeout_s: float = 10.0) -> dict:
    """Scrape one replica: parsed /metrics series + the /stats
    payload, reduced to the fleet table's row (raising on any scrape
    failure — the caller degrades the row, not this function)."""
    from eeg_dataanalysispackage_tpu.obs import metrics_export

    base = url.rstrip("/")
    series = metrics_export.parse(_get_text(base + "/metrics", timeout_s))
    stats = _get_json(base + "/stats", timeout_s)

    def counter(name: str) -> int:
        rows = series.get(f"eeg_tpu_{name}_total", [])
        return int(rows[0][1]) if rows else 0

    def gauge(name: str) -> int:
        rows = series.get(f"eeg_tpu_{name}", [])
        return int(rows[0][1]) if rows else 0

    info = series.get("eeg_tpu_build_info", [])
    replica = info[0][0].get("replica", "?") if info else "?"
    # the service-wide histogram is the tenant-unlabeled series
    # (matching tenant=None keeps only rows WITHOUT the label);
    # per-tenant series carry tenant= labels
    hist = metrics_export.histogram_from_series(
        series, "eeg_tpu_serve_request_latency_ms",
        match={"tenant": None},
    )
    serve = stats.get("serve") or {}
    tenants = serve.get("tenants") or {}
    slo = {
        name: block.get("slo")
        for name, block in sorted(tenants.items())
        if block.get("slo") is not None
    }
    if not slo and serve.get("slo") is not None:
        slo = {"(service)": serve["slo"]}
    fleet_block = stats.get("fleet") or {}
    return {
        "url": base,
        "replica": replica,
        "draining": bool(fleet_block.get("draining")),
        "plans_completed": counter("scheduler_completed"),
        "serve_completed": counter("serve_completed"),
        "serve_shed": counter("serve_shed"),
        "held_leases": gauge("fleet_held_leases"),
        "takeovers": counter("lease_takeovers"),
        # device-pool columns (ISSUE 20): the ordinals this replica's
        # plans hold right now, straight off the replica's own stats
        # block (the gauge carries the count; the block, the list)
        "devices_held": fleet_block.get("devices_held") or [],
        "device_pool": fleet_block.get("device_pool"),
        "latency_hist": None if hist is None else hist.snapshot(),
        "slo": slo,
    }


def _device_pool_table(journal_dir: str):
    """The shared device pool, observed straight off the lease dir:
    per-ordinal holder rows, the claimable count, and the waiting
    plans with the footprint that blocks them (oldest first). None
    when no replica has ever run with a pool here (no marker)."""
    from eeg_dataanalysispackage_tpu.scheduler import (
        placement as placement_mod,
    )

    size = placement_mod.pool_size_marker(journal_dir)
    if size is None:
        return None
    devices = placement_mod.device_table(journal_dir)
    held = {row["ordinal"] for row in devices if not row["stale"]}
    waiting = placement_mod.waiting_entries(journal_dir)
    return {
        "size": size,
        "devices": devices,
        "free": max(0, size - len(held)),
        "waiting": [
            {
                "plan_id": w.get("plan_id"),
                "footprint": w.get("footprint"),
                "age_s": round(
                    max(0.0, _now() - float(w.get("since", 0.0))), 2
                ),
            }
            for w in waiting
        ],
    }


def _now() -> float:
    import time

    return time.time()


def _lease_table(journal_dir: str) -> list:
    """The shared lease directory's rows (offline — same join as
    plan_admin's ``fleet`` view, reduced to what the top table
    needs)."""
    from eeg_dataanalysispackage_tpu.scheduler import lease as lease_mod

    leases = lease_mod.LeaseDir(journal_dir, holder="fleet-top")
    return [
        {
            "plan_id": info["plan_id"],
            "holder": info["holder"],
            "age_s": round(info["age_s"], 2),
            "stale": bool(info["stale"]),
        }
        for info in leases.scan()
    ]


def snapshot(urls, journal_dir=None, timeout_s: float = 10.0) -> dict:
    """The whole fleet as one JSON-safe dict: per-replica rows
    (DOWN rows carry ``error``), the exactly-merged fleet histogram,
    summed counters, the worst per-tenant SLO across replicas, and
    (with ``journal_dir``) the lease table."""
    from eeg_dataanalysispackage_tpu.obs import metrics_export

    replicas = []
    for url in urls:
        try:
            replicas.append(replica_snapshot(url, timeout_s=timeout_s))
        except (urllib.error.URLError, OSError, ValueError) as e:
            replicas.append({
                "url": url.rstrip("/"),
                "replica": None,
                "error": f"{type(e).__name__}: {e}",
            })
    up = [r for r in replicas if "error" not in r]
    merged = metrics_export.merge_all(
        metrics_export.LatencyHistogram.from_snapshot(r["latency_hist"])
        for r in up
        if r.get("latency_hist")
    )
    # per-tenant worst-case across replicas: a tenant is only as
    # healthy as its worst replica says it is
    tenant_slo = {}
    for r in up:
        for tenant, block in (r.get("slo") or {}).items():
            prior = tenant_slo.get(tenant)
            if prior is None or (
                block.get("error_budget_burn", 0)
                > prior.get("error_budget_burn", 0)
            ):
                tenant_slo[tenant] = block
    fleet = {
        "replicas_total": len(replicas),
        "replicas_up": len(up),
        "plans_completed": sum(r["plans_completed"] for r in up),
        "serve_completed": sum(r["serve_completed"] for r in up),
        "serve_shed": sum(r["serve_shed"] for r in up),
        "held_leases": sum(r["held_leases"] for r in up),
        "takeovers": sum(r["takeovers"] for r in up),
        "latency_hist": None if merged is None else merged.snapshot(),
        "latency_p50_ms": None if merged is None else merged.quantile(50.0),
        "latency_p99_ms": None if merged is None else merged.quantile(99.0),
        "tenant_slo": tenant_slo,
    }
    fleet["devices_held"] = sum(
        len(r.get("devices_held") or []) for r in up
    )
    snap = {"replicas": replicas, "fleet": fleet}
    if journal_dir:
        try:
            snap["leases"] = _lease_table(journal_dir)
        except OSError as e:
            snap["leases_error"] = f"{type(e).__name__}: {e}"
        try:
            pool = _device_pool_table(journal_dir)
            if pool is not None:
                snap["device_pool"] = pool
        except OSError as e:
            snap["device_pool_error"] = f"{type(e).__name__}: {e}"
    return snap


def render(snap: dict) -> None:
    """The human table over one :func:`snapshot`."""
    from eeg_dataanalysispackage_tpu.obs import metrics_export

    cols = ("replica", "state", "plans", "serve", "shed", "leases",
            "devices", "takeovers", "p50ms", "p99ms")
    rows = []
    for r in snap["replicas"]:
        if "error" in r:
            rows.append({
                "replica": r["url"], "state": "DOWN",
                "plans": "-", "serve": "-", "shed": "-", "leases": "-",
                "devices": "-", "takeovers": "-",
                "p50ms": "-", "p99ms": "-",
                "_error": r["error"],
            })
            continue
        hist = (
            metrics_export.LatencyHistogram.from_snapshot(
                r["latency_hist"]
            )
            if r.get("latency_hist") else None
        )
        p50 = hist.quantile(50.0) if hist else None
        p99 = hist.quantile(99.0) if hist else None
        rows.append({
            "replica": r["replica"],
            "state": "draining" if r["draining"] else "up",
            "plans": r["plans_completed"],
            "serve": r["serve_completed"],
            "shed": r["serve_shed"],
            "leases": r["held_leases"],
            "devices": (
                ",".join(str(o) for o in r.get("devices_held") or [])
                or "-"
            ),
            "takeovers": r["takeovers"],
            "p50ms": "-" if p50 is None else f"{p50:g}",
            "p99ms": "-" if p99 is None else f"{p99:g}",
        })
    widths = {
        c: max(len(c), *(len(str(row[c])) for row in rows))
        for c in cols
    } if rows else {c: len(c) for c in cols}
    print("  ".join(f"{c:<{widths[c]}}" for c in cols))
    for row in rows:
        print("  ".join(f"{str(row[c]):<{widths[c]}}" for c in cols))
        if row.get("_error"):
            print(f"    ({row['_error']})")
    fleet = snap["fleet"]
    p99 = fleet.get("latency_p99_ms")
    print(
        f"\nfleet: {fleet['replicas_up']}/{fleet['replicas_total']} up, "
        f"{fleet['plans_completed']} plans, "
        f"{fleet['serve_completed']} serve requests "
        f"({fleet['serve_shed']} shed), "
        f"{fleet['held_leases']} leases held, "
        f"{fleet['takeovers']} takeovers"
        + (f", merged p99 {p99:g}ms" if p99 is not None else "")
    )
    for tenant, slo in sorted((fleet.get("tenant_slo") or {}).items()):
        verdict = "OK" if slo.get("ok") else "BURNING"
        print(
            f"  slo {tenant}: {verdict}  "
            f"avail={slo.get('availability')} "
            f"attain={slo.get('latency_attainment')} "
            f"burn={slo.get('error_budget_burn')} "
            f"(objective {slo.get('objective_ms')}ms, "
            f"target {slo.get('availability_target')})"
        )
    leases = snap.get("leases")
    if leases is not None:
        stale = sum(1 for row in leases if row["stale"])
        print(f"\nleases on disk: {len(leases)} ({stale} stale)")
        for row in leases:
            mark = "STALE" if row["stale"] else "held"
            print(
                f"  {row['plan_id']:<12} {row['holder'] or '?':<16} "
                f"{row['age_s']:>7.2f}s  {mark}"
            )
    pool = snap.get("device_pool")
    if pool is not None:
        print(
            f"\ndevice pool: {pool['size']} ordinals, "
            f"{pool['free']} free, "
            f"{len(pool['waiting'])} plan(s) waiting"
        )
        for row in pool["devices"]:
            mark = "STALE" if row["stale"] else "held"
            print(
                f"  device {row['ordinal']:<3} "
                f"{row['holder'] or '?':<16} "
                f"{row['age_s']:>7.2f}s  {mark}"
            )
        for w in pool["waiting"]:
            fp = w.get("footprint") or {}
            print(
                f"  waiting {w['plan_id'] or '?':<10} "
                f"needs devices={fp.get('devices')} "
                f"hosts={fp.get('hosts')} "
                f"class={fp.get('memory_class')} "
                f"({w['age_s']:.2f}s)"
            )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleet_top", description=__doc__.split("\n\n")[0],
    )
    parser.add_argument(
        "urls", nargs="+", help="replica base URLs (http://host:port)",
    )
    parser.add_argument(
        "--journal", help="shared journal dir (adds the lease table)",
    )
    parser.add_argument(
        "--snapshot", action="store_true",
        help="emit one strict-JSON object instead of the table",
    )
    parser.add_argument("--timeout", type=float, default=10.0)
    args = parser.parse_args(argv)
    snap = snapshot(
        args.urls, journal_dir=args.journal, timeout_s=args.timeout
    )
    if args.snapshot:
        from eeg_dataanalysispackage_tpu.utils import strict_json

        print(strict_json.dumps(snap, sort_keys=True))
    else:
        render(snap)
    return 0 if snap["fleet"]["replicas_up"] == len(args.urls) else 1


if __name__ == "__main__":
    # the repo root, so the package imports without installation
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(130)
