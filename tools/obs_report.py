"""Render / diff the pipeline's run-report artifacts.

Usage:
    python tools/obs_report.py show <run_report.json | crash_report.json>
    python tools/obs_report.py diff <a.json> <b.json>

``show`` renders one artifact (obs/report.py schemas) as an aligned
human-readable summary: stage table (total/count/mean/min/max), cache
attribution, XLA compilation accounting, degradation history, top
metrics counters, span aggregates — and for crash reports the error
plus the flight-recorder event tail.

``diff`` compares two run reports side by side — the cold-vs-warm and
degraded-vs-clean questions: per-stage seconds with the ratio, cache
attribution deltas, backend rung drift, compilation count/seconds
deltas, and metrics counters that changed. Exit code 0 always (it is
a lens, not a gate; gates live in tools/e2e_smoke.py).

Stdlib only, like every tool in this repo.
"""

import json
import os
import sys

_STAGE_COLS = ("seconds", "count", "mean_s", "min_s", "max_s")


def _load(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    schema = data.get("schema", "")
    if not schema.startswith(("eeg-tpu-run-report/", "eeg-tpu-crash-report/")):
        raise SystemExit(
            f"{path}: not a run/crash report (schema={schema!r})"
        )
    return data


def _fmt_stage_table(stages: dict) -> list:
    if not stages:
        return ["  (no stages recorded)"]
    rows = sorted(
        stages.items(), key=lambda kv: (-kv[1]["seconds"], kv[0])
    )
    width = max(len(n) for n, _ in rows)
    out = [
        f"  {'stage':<{width}}  {'total':>9}  {'count':>5}  "
        f"{'mean':>9}  {'min':>9}  {'max':>9}"
    ]
    for name, v in rows:
        out.append(
            f"  {name:<{width}}  {v['seconds']:9.4f}  {v['count']:>5}  "
            f"{v.get('mean_s', v['seconds'] / max(1, v['count'])):9.4f}  "
            f"{v.get('min_s', 0.0):9.4f}  {v.get('max_s', 0.0):9.4f}"
        )
    return out


def _fmt_population(block: dict, leg: str = "") -> list:
    """One population block (obs/report.py ``population`` field):
    the axes line, the cross-member summary, and the per-member
    accuracy table sorted best-first."""
    shape = block.get("shape", {})
    summary = block.get("summary", {})
    tag = f"{leg or block.get('classifier', '?')}"
    out = [
        f"  {tag}: {block.get('members')} members  "
        f"(folds={shape.get('folds')} {shape.get('cv_mode')} "
        f"seeds={shape.get('seeds')} grid={shape.get('grid_points')})  "
        f"mode={block.get('mode')}"
        + (
            f" (requested {block['requested_mode']})"
            if block.get("requested_mode") not in (None, block.get("mode"))
            else ""
        )
        + (
            f"  compiles={block['compiles']}"
            if block.get("compiles") is not None
            else ""
        )
    ]
    if summary:
        out.append(
            f"    best {summary.get('best')} "
            f"acc={summary.get('best_accuracy')}  "
            f"mean={summary.get('mean_accuracy')}  "
            f"std={summary.get('std_accuracy')}"
        )
    accuracy = block.get("accuracy") or {}
    if accuracy:
        width = max(len(k) for k in accuracy)
        ranked = sorted(accuracy.items(), key=lambda kv: (-kv[1], kv[0]))
        for member, acc in ranked:
            out.append(f"    {member:<{width}}  {acc}")
    return out


def _top_counters(metrics: dict, n: int = 12) -> list:
    counters = (metrics or {}).get("counters", {})
    rows = sorted(counters.items(), key=lambda kv: (-kv[1], kv[0]))[:n]
    if not rows:
        return ["  (no counters)"]
    width = max(len(k) for k, _ in rows)
    return [f"  {k:<{width}}  {v:g}" for k, v in rows]


def show(path: str) -> None:
    data = _load(path)
    crash = data["schema"].startswith("eeg-tpu-crash-report/")
    print(f"{'CRASH' if crash else 'RUN'} report  {path}")
    print(f"  schema   {data['schema']}")
    print(f"  outcome  {data.get('outcome')}")
    if "wall_s" in data:
        print(f"  wall     {data['wall_s']:.3f}s")
    print(f"  query    {data.get('query', '')}")
    dev = data.get("device", {})
    print(
        f"  device   {dev.get('platform')} x{dev.get('device_count', '?')}"
    )
    backend = data.get("backend") or {}
    if backend:
        print(
            f"  backend  requested={backend.get('requested')} "
            f"landed={backend.get('landed')}"
        )
    precision = data.get("precision")
    if precision:
        gate = precision.get("gate") or {}
        print(
            f"  precision requested={precision.get('requested')} "
            f"used={precision.get('used')} "
            f"gate_dev={gate.get('max_abs_dev')} "
            f"tol={gate.get('tolerance')}"
        )
    if data.get("overlap") is not None:
        print(f"  overlap  {data.get('overlap')}")
    dedup = data.get("dedup")
    if dedup:
        line = (
            f"  dedup    role={dedup.get('role')} "
            f"prefix={str(dedup.get('prefix_key'))[:16]}… "
            f"rows={dedup.get('rows')}"
        )
        if dedup.get("role") == "leader":
            line += f" build_s={dedup.get('build_seconds')}"
            if dedup.get("promoted_after_leader_failure"):
                line += " (promoted after leader failure)"
        else:
            line += (
                f" leader={dedup.get('leader_plan')} "
                f"bytes_saved={dedup.get('bytes_saved')} "
                f"seconds_saved={dedup.get('seconds_saved')}"
            )
        print(line)
    gateway = data.get("gateway")
    if gateway:
        print(
            f"  gateway  via={gateway.get('via')} "
            f"idempotency_key={gateway.get('idempotency_key')} "
            f"client={gateway.get('client')}"
        )
    trace = data.get("trace")
    if trace:
        print(
            f"  trace    id={trace.get('trace_id')} "
            f"segment={trace.get('segment')}"
            "  (stitch: plan_admin trace <plan-id>)"
        )
    mesh = data.get("mesh")
    if mesh:
        req = mesh.get("requested") or {}
        line = (
            f"  mesh     rung={mesh.get('rung')} "
            f"shape={mesh.get('shape')} "
            f"requested={req.get('devices')} "
            f"axes={','.join(req.get('axes') or [])}"
        )
        if mesh.get("error"):
            line += f"  error={mesh['error']}"
        print(line)
        # pod coordinates: live (top-level fields on the pod rung) or
        # requested-but-degraded (the pod sub-block with its evidence)
        pod = mesh.get("pod") or {}
        if mesh.get("rung") == "pod" or pod:
            src = pod or mesh
            pod_line = (
                f"           pod processes={src.get('processes')} "
                f"process_id={src.get('process_id')} "
                f"coordinator={src.get('coordinator')} "
                f"dcn_shape={mesh.get('dcn_shape')}"
            )
            if pod.get("error"):
                pod_line += f"  error={pod['error']}"
            print(pod_line)
        pop_mesh = mesh.get("population") or {}
        if pop_mesh:
            print(
                f"           population rung={pop_mesh.get('rung')} "
                f"members/device={pop_mesh.get('members_per_device')} "
                f"padded={pop_mesh.get('padded_members')}"
            )
    if crash:
        err = data.get("error", {})
        print(f"\nerror: {err.get('type')}: {err.get('message')}")
    workload = data.get("workload")
    if workload:
        print("\nworkload:")
        print(
            f"  task={workload.get('task')}  window="
            f"{workload.get('window')}  stride={workload.get('stride')}"
            f"  label_overlap={workload.get('label_overlap')}"
        )
        print(
            f"  windows={workload.get('windows')}  positives="
            f"{workload.get('positives')}  class_ratio="
            f"{workload.get('class_ratio')}"
        )
        print(
            f"  weight_pos={workload.get('weight_pos')}  weight_neg="
            f"{workload.get('weight_neg')}  cost_fp="
            f"{workload.get('cost_fp')}  cost_fn={workload.get('cost_fn')}"
        )
    classification = data.get("classification")
    if classification:
        print("\nclassification (extended metrics):")
        blocks = (
            classification
            if all(isinstance(v, dict) for v in classification.values())
            else {"": classification}
        )
        for member, block in blocks.items():
            if block is None:
                continue
            prefix = f"  {member}: " if member else "  "
            print(
                f"{prefix}precision={block.get('precision')} "
                f"recall={block.get('recall')} f1={block.get('f1')} "
                f"balanced_acc={block.get('balanced_accuracy')} "
                f"expected_cost={block.get('expected_cost')} "
                f"(fp={block.get('cost_fp')}, fn={block.get('cost_fn')})"
            )
    pop = data.get("population")
    if pop:
        print("\npopulation:")
        # train_clf= runs carry one block; fan-out runs one per leg
        blocks = pop.get("legs", {"": pop}) if isinstance(pop, dict) else {}
        for leg, block in blocks.items():
            for line in _fmt_population(block, leg):
                print(line)
    serve = data.get("serve")
    if serve:
        print("\nserve:")
        req = serve.get("requests", {})
        lat = serve.get("latency_ms", {})
        print(
            f"  mode={serve.get('mode')}  batches="
            f"{serve.get('batches')}  mean_batch="
            f"{serve.get('mean_batch_size')}"
        )
        print(
            f"  completed={req.get('completed')}  shed="
            f"{req.get('shed')}  deadline_exceeded="
            f"{req.get('deadline_exceeded')}  failed="
            f"{req.get('failed')}  retries={req.get('retries')}"
        )
        print(
            f"  latency p50={lat.get('p50')}ms p99={lat.get('p99')}ms "
            f"max={lat.get('max')}ms  drained="
            f"{serve.get('drained_cleanly')}  wedged="
            f"{serve.get('wedged')}"
        )
        slo = serve.get("slo")
        if slo:
            print(
                f"  slo {'OK' if slo.get('ok') else 'BURNING'}  "
                f"avail={slo.get('availability')} "
                f"attain={slo.get('latency_attainment')} "
                f"burn={slo.get('error_budget_burn')}  "
                f"(objective {slo.get('objective_ms')}ms, target "
                f"{slo.get('availability_target')})"
            )
        tenants = serve.get("tenants") or {}
        if tenants:
            print(
                f"  tenants={len(tenants)}  quota="
                f"{serve.get('tenant_quota')}  resident_bytes="
                f"{serve.get('resident_weight_bytes')}"
            )
            width = max(len(name) for name in tenants)
            for name in sorted(tenants):
                t = tenants[name]
                treq = t.get("requests", {})
                tlat = t.get("latency_ms", {})
                tslo = t.get("slo") or {}
                slo_tail = (
                    f"  slo={'OK' if tslo.get('ok') else 'BURN'}"
                    f"(burn={tslo.get('error_budget_burn')})"
                    if tslo else ""
                )
                print(
                    f"    {name:<{width}}  lane={t.get('lane')} "
                    f"gen={t.get('generation')}  completed="
                    f"{treq.get('completed')} shed={treq.get('shed')} "
                    f"deadline={treq.get('deadline_exceeded')} "
                    f"failed={treq.get('failed')}  p50="
                    f"{tlat.get('p50')}ms p99={tlat.get('p99')}ms"
                    f"{slo_tail}"
                )
    lifecycle = data.get("lifecycle")
    if lifecycle:
        print("\nlifecycle:")
        fb = lifecycle.get("feedback") or {}
        print(
            f"  state={lifecycle.get('state')}  generation="
            f"{lifecycle.get('generation')}  swaps="
            f"{lifecycle.get('swaps')}  rollbacks="
            f"{lifecycle.get('rollbacks')}  drift="
            f"{lifecycle.get('drift_events')}  wedged="
            f"{lifecycle.get('wedged')}"
        )
        print(
            f"  feedback received={fb.get('received')} dropped="
            f"{fb.get('dropped')}  batches={fb.get('batches')} "
            f"chunks={fb.get('chunks')} failures={fb.get('failures')}"
        )
        lw = lifecycle.get("live_window") or {}
        print(
            f"  live window n={lw.get('n')}/{lw.get('window')}  "
            f"expected_cost={lw.get('expected_cost')}  recall="
            f"{lw.get('recall')}  baseline_cost="
            f"{lifecycle.get('baseline_cost')}"
        )
        cand = lifecycle.get("candidate")
        if cand:
            cw = cand.get("window") or {}
            print(
                f"  candidate g{cand.get('generation')} "
                f"batches={cand.get('batches')} t={cand.get('t')} "
                f"rows={cand.get('rows')}  shadow cost="
                f"{cw.get('expected_cost')} recall={cw.get('recall')}"
            )
        gate = lifecycle.get("gate")
        if gate:
            print(
                f"  gate {lifecycle.get('config', {}).get('swap_gate')}:"
                f" candidate_cost={gate.get('candidate_cost')} "
                f"live_cost={gate.get('live_cost')} "
                f"promote={gate.get('promote')}"
            )
        if lifecycle.get("promoted_path"):
            print(f"  promoted  {lifecycle.get('promoted_path')}")
        ckpt = lifecycle.get("checkpoint")
        if ckpt:
            print(
                f"  checkpoint {ckpt.get('dir')} "
                f"(steps retained: {ckpt.get('steps')})"
            )
    deg = data.get("degradation") or []
    if deg:
        print("\ndegradation history:")
        for step in deg:
            print(f"  {step}")
    print("\nstages:")
    for line in _fmt_stage_table(data.get("stages", {})):
        print(line)
    caches = data.get("caches", {})
    print(
        f"\ncaches: feature={caches.get('feature_cache')} "
        f"plan={caches.get('plan_cache')} "
        f"compile_dir={caches.get('compile_cache_dir')}"
    )
    xla = data.get("xla", {})
    print(
        f"xla: compilations={xla.get('compilations')} "
        f"backend_compile_s={xla.get('backend_compile_s')}"
    )
    chaos = data.get("chaos")
    if chaos:
        print(f"chaos: spec={chaos.get('spec')!r} seed={chaos.get('seed')}")
        for point, rule in (chaos.get("rules") or {}).items():
            print(
                f"  {point}: calls={rule['calls']} fired={rule['fired']}"
            )
    spans = data.get("spans", {})
    by_name = spans.get("by_name", {})
    if by_name:
        print(
            f"\nspans ({spans.get('span_count')} total, "
            f"{spans.get('dropped_spans', 0)} dropped):"
        )
        width = max(len(k) for k in by_name)
        for name, agg in by_name.items():
            print(
                f"  {name:<{width}}  x{agg['count']:<5} "
                f"{agg['seconds']:9.4f}s  "
                f"[{agg['min_s']:.4f} .. {agg['max_s']:.4f}]"
            )
    print("\ntop metrics counters:")
    for line in _top_counters(data.get("metrics", {})):
        print(line)
    if crash:
        events = data.get("events") or []
        print(f"\nflight recorder (last {len(events)} events):")
        for ev in events[-20:]:
            print(
                f"  t={ev['t']:9.4f}  {ev['name']:<28} "
                f"span={ev.get('span_name')}  {ev.get('attrs') or ''}"
            )
        fleet_ctx = data.get("fleet_context")
        if fleet_ctx:
            counters = fleet_ctx.get("lease_counters") or {}
            print(
                f"\nfleet context: replica={fleet_ctx.get('replica')} "
                f"takeover={fleet_ctx.get('takeover')} "
                f"held_leases={fleet_ctx.get('held_leases')}"
            )
            if counters:
                print(
                    "  lease counters: "
                    + "  ".join(
                        f"{k}={v}" for k, v in sorted(counters.items())
                    )
                )


def diff(path_a: str, path_b: str) -> None:
    a, b = _load(path_a), _load(path_b)
    print(f"A: {path_a}")
    print(f"B: {path_b}")
    wall_a, wall_b = a.get("wall_s"), b.get("wall_s")
    if wall_a and wall_b:
        print(
            f"\nwall: A {wall_a:.3f}s  B {wall_b:.3f}s  "
            f"(B/A = {wall_b / wall_a:.2f}x)"
        )
    ba, bb = a.get("backend") or {}, b.get("backend") or {}
    if ba != bb:
        print(f"backend: A {ba}  B {bb}")

    def _mesh_digest(report):
        mesh = report.get("mesh")
        if not mesh:
            return None
        pop = mesh.get("population") or {}
        return {
            "rung": mesh.get("rung"),
            "shape": mesh.get("shape"),
            "members_per_device": pop.get("members_per_device"),
            "processes": mesh.get("processes")
            or (mesh.get("pod") or {}).get("processes"),
        }

    ma, mb = _mesh_digest(a), _mesh_digest(b)
    if (ma or mb) and ma != mb:
        print(f"mesh (rung, shape, members/device): A {ma}  B {mb}")

    def _dedup_digest(report):
        dedup = report.get("dedup")
        if not dedup:
            return None
        return {
            "role": dedup.get("role"),
            "prefix": str(dedup.get("prefix_key"))[:16],
            "leader": dedup.get("leader_plan"),
            "bytes_saved": dedup.get("bytes_saved"),
            "seconds_saved": dedup.get(
                "seconds_saved", dedup.get("build_seconds")
            ),
        }

    dda, ddb = _dedup_digest(a), _dedup_digest(b)
    if (dda or ddb) and dda != ddb:
        print(f"dedup (role, prefix, leader, saved): A {dda}  B {ddb}")

    def _lifecycle_digest(report):
        lc = report.get("lifecycle")
        if not lc:
            return None
        return {
            "state": lc.get("state"),
            "generation": lc.get("generation"),
            "swaps": lc.get("swaps"),
            "rollbacks": lc.get("rollbacks"),
            "drift": lc.get("drift_events"),
            "batches": (lc.get("feedback") or {}).get("batches"),
            "live_cost": (lc.get("live_window") or {}).get(
                "expected_cost"
            ),
        }

    la, lb = _lifecycle_digest(a), _lifecycle_digest(b)
    if (la or lb) and la != lb:
        print(
            f"lifecycle (state, gen, swaps, rollbacks, drift): "
            f"A {la}  B {lb}"
        )
    ga, gb = a.get("gateway") or {}, b.get("gateway") or {}
    if (ga or gb) and ga != gb:
        print(f"gateway: A {ga}  B {gb}")

    def _tenant_digest(report):
        tenants = (report.get("serve") or {}).get("tenants")
        if not tenants:
            return None
        return {
            name: (
                t.get("lane"), t.get("generation"),
                (t.get("requests") or {}).get("completed"),
                (t.get("requests") or {}).get("shed"),
                (t.get("slo") or {}).get("ok"),
            )
            for name, t in tenants.items()
        }

    ta, tb = _tenant_digest(a), _tenant_digest(b)
    if (ta or tb) and ta != tb:
        print(
            f"serve tenants (lane, gen, completed, shed, slo_ok): "
            f"A {ta}  B {tb}"
        )

    def _slo_digest(report):
        slo = (report.get("serve") or {}).get("slo")
        if not slo:
            return None
        return {
            "ok": slo.get("ok"),
            "availability": slo.get("availability"),
            "attainment": slo.get("latency_attainment"),
            "burn": slo.get("error_budget_burn"),
        }

    slo_a, slo_b = _slo_digest(a), _slo_digest(b)
    if (slo_a or slo_b) and slo_a != slo_b:
        print(f"serve slo (ok, avail, attain, burn): A {slo_a}  B {slo_b}")
    tr_a = (a.get("trace") or {}).get("trace_id")
    tr_b = (b.get("trace") or {}).get("trace_id")
    if (tr_a or tr_b) and tr_a != tr_b:
        print(f"trace: A {tr_a}  B {tr_b}")

    def _pop_digest(report):
        pop = report.get("population")
        if not pop:
            return None
        blocks = pop.get("legs", {"": pop})
        return {
            leg or blk.get("classifier", "?"): (
                blk.get("members"), blk.get("mode"),
                (blk.get("summary") or {}).get("best_accuracy"),
            )
            for leg, blk in blocks.items()
        }

    pa, pb = _pop_digest(a), _pop_digest(b)
    if (pa or pb) and pa != pb:
        print(f"population (members, mode, best acc): A {pa}  B {pb}")
    da, db = a.get("degradation") or [], b.get("degradation") or []
    if len(da) != len(db):
        print(f"degradation steps: A {len(da)}  B {len(db)}")

    print("\nstages (A vs B):")
    stages_a, stages_b = a.get("stages", {}), b.get("stages", {})
    names = sorted(set(stages_a) | set(stages_b))
    if names:
        width = max(len(n) for n in names)
        for name in names:
            sa = stages_a.get(name, {}).get("seconds", 0.0)
            sb = stages_b.get(name, {}).get("seconds", 0.0)
            ratio = f"{sb / sa:7.2f}x" if sa > 0 else "      --"
            print(
                f"  {name:<{width}}  A {sa:9.4f}s  B {sb:9.4f}s  {ratio}"
            )

    print("\ncaches:")
    for kind in ("feature_cache", "plan_cache"):
        ca = (a.get("caches") or {}).get(kind)
        cb = (b.get("caches") or {}).get(kind)
        marker = " " if ca == cb else "*"
        print(f" {marker} {kind}: A {ca}  B {cb}")
    xa, xb = a.get("xla", {}), b.get("xla", {})
    print(
        f"\nxla: A compilations={xa.get('compilations')} "
        f"({xa.get('backend_compile_s')}s)  "
        f"B compilations={xb.get('compilations')} "
        f"({xb.get('backend_compile_s')}s)"
    )

    ca = (a.get("metrics") or {}).get("counters", {})
    cb = (b.get("metrics") or {}).get("counters", {})
    changed = {
        k for k in set(ca) | set(cb) if ca.get(k, 0) != cb.get(k, 0)
    }
    if changed:
        print("\nmetrics counters that differ:")
        width = max(len(k) for k in changed)
        for k in sorted(changed):
            print(
                f"  {k:<{width}}  A {ca.get(k, 0):g}  B {cb.get(k, 0):g}"
            )
    sa = a.get("statistics_sha256")
    sb = b.get("statistics_sha256")
    if sa and sb:
        verdict = "IDENTICAL" if sa == sb else "DIFFER"
        print(f"\nstatistics: {verdict} (A {sa[:12]}… B {sb[:12]}…)")


def main(argv) -> int:
    if len(argv) >= 2 and argv[0] == "show":
        show(argv[1])
        return 0
    if len(argv) >= 3 and argv[0] == "diff":
        diff(argv[1], argv[2])
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    try:
        sys.exit(main(sys.argv[1:]))
    except BrokenPipeError:
        # `obs_report.py show ... | head` closing the pipe early is
        # fine — exit quietly like any well-behaved filter
        os_devnull = open(os.devnull, "w")
        sys.stdout = os_devnull
        sys.exit(0)
