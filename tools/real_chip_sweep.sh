#!/bin/bash
# Real-chip validation sweep: parity + all bench variants (+ a Pallas
# tile-geometry sweep). Run in background with a generous timeout and
# NEVER kill it mid-compile (axon tunnel wedges). Results land in
# /tmp/sweep/*.json, one JSON line each.
set -u
OUT=${1:-/tmp/sweep}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

# Generous probe timeout: SIGTERM on an axon-INITIALIZING process is
# the known tunnel-wedging event; 240s comfortably covers cold init.
probe() {
  timeout 240 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null
}

plat=$(probe)
if [ "$plat" != "axon" ] && [ "$plat" != "tpu" ]; then
  echo "real TPU not reachable (got '${plat:-none}'); aborting sweep" >&2
  exit 1
fi
echo "platform: $plat"

run() { # name, timeout, cmd...
  name=$1; t=$2; shift 2
  echo "== $name =="
  timeout "$t" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  echo "rc=$? $(tail -c 400 "$OUT/$name.json")"
}

# Timeouts are generous (first Mosaic/XLA compiles can take minutes);
# a kill mid-compile wedges the tunnel, so prefer waiting.
run parity        600 python tools/tpu_parity_check.py
run einsum        600 python tools/ingest_bench.py einsum 262144 50
run einsum_2d     600 python tools/ingest_bench.py einsum_2d 262144 50
run einsum_bf16   600 python tools/ingest_bench.py einsum_bf16 262144 50
run regular       600 python tools/ingest_bench.py regular_ingest 262144 20
run pallas_64k32  900 python tools/ingest_bench.py pallas_ingest 131072 20
BENCH_CHUNK=131072 BENCH_TILE_B=64 \
run pallas_128k64 900 python tools/ingest_bench.py pallas_ingest 131072 20
BENCH_CHUNK=32768 BENCH_TILE_B=16 \
run pallas_32k16  900 python tools/ingest_bench.py pallas_ingest 131072 20
run xla_ingest    900 python tools/ingest_bench.py xla_ingest 32768 10
run block_ingest  900 python tools/ingest_bench.py block_ingest 32768 10
run einsum_flat   600 python tools/ingest_bench.py einsum_flat 262144 50
run train_step    600 python tools/ingest_bench.py train_step 131072 20
BENCH_FORMULATION=phase \
run regular_phase 900 python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=conv \
run regular_conv  900 python tools/ingest_bench.py regular_ingest 262144 20
run rf_train      900 python tools/ingest_bench.py rf_train 65536 3
run rf_predict    600 python tools/ingest_bench.py rf_predict 262144 10
run train_raw     900 python tools/ingest_bench.py train_step_raw 131072 20
echo "sweep done"
