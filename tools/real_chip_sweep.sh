#!/bin/bash
# Real-chip validation sweep: parity + all bench variants + the Pallas
# compile canary/bisect. Run in background with a generous timeout and
# NEVER kill it mid-compile (axon tunnel wedges). Results land in
# /tmp/sweep/*.json, one JSON line each. This is the manual
# reproduction of tools/tunnel_watch.sh's collection (same list, same
# order); tools/summarize_sweep.py renders either directory.
set -u
OUT=${1:-/tmp/sweep}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

# Kill-free probe: returns on its own (tools/probe_tpu.py) — ok JSON
# on a healthy tunnel, UNAVAILABLE after ~25 min on a down one.
plat=$(python tools/probe_tpu.py 2>/dev/null)
if ! echo "$plat" | grep -q '"ok": true' \
    || ! echo "$plat" | grep -Eq '"platform": "(axon|tpu)"'; then
  echo "real TPU not reachable ($plat); aborting sweep" >&2
  exit 1
fi
echo "platform probe: $plat"

run() { # name, timeout, cmd...
  name=$1; t=$2; shift 2
  echo "== $name =="
  timeout "$t" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  echo "rc=$? $(tail -c 400 "$OUT/$name.json")"
}

# the single shared collection list (also used by tunnel_watch.sh)
source tools/collect_chip_runs.sh
echo "sweep done"
