#!/bin/bash
# Real-chip validation sweep: parity + all bench variants (+ a Pallas
# tile-geometry sweep). Run in background with a generous timeout and
# NEVER kill it mid-compile (axon tunnel wedges). Results land in
# /tmp/sweep/*.json, one JSON line each.
set -u
OUT=${1:-/tmp/sweep}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null
}

plat=$(probe)
if [ "$plat" != "axon" ] && [ -z "$plat" ]; then
  echo "TPU not reachable; aborting sweep" >&2
  exit 1
fi
echo "platform: $plat"

run() { # name, timeout, cmd...
  name=$1; t=$2; shift 2
  echo "== $name =="
  timeout "$t" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  echo "rc=$? $(tail -c 400 "$OUT/$name.json")"
}

run parity        420 python tools/tpu_parity_check.py
run einsum        420 python tools/ingest_bench.py einsum 262144 50
run regular       420 python tools/ingest_bench.py regular_ingest 262144 20
run pallas_64k32  420 python tools/ingest_bench.py pallas_ingest 131072 20
BENCH_CHUNK=131072 BENCH_TILE_B=64 \
run pallas_128k64 420 python tools/ingest_bench.py pallas_ingest 131072 20
BENCH_CHUNK=32768 BENCH_TILE_B=16 \
run pallas_32k16  420 python tools/ingest_bench.py pallas_ingest 131072 20
run xla_ingest    420 python tools/ingest_bench.py xla_ingest 32768 10
run train_step    420 python tools/ingest_bench.py train_step 131072 20
echo "sweep done"
