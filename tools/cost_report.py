"""XLA cost-model report for the hot programs (bytes/flops per epoch).

For each key jitted program, compile it and print XLA's own
``cost_analysis()`` — bytes accessed and flops — normalized per epoch,
next to the hand-derived bytes from ``docs/ingest_kernel.md``. The
point: when a real-chip number comes in below roofline, the first
question is whether the *compiled program* moves more bytes than the
design assumed (relayout copies, materialized intermediates) or
whether the bytes are right and the gap is elsewhere (dispatch,
bandwidth ceiling, tiling). The cost model answers that without a
device: it is computed from the optimized HLO.

Usage: python tools/cost_report.py [n_epochs]  (default 32768; runs on
whatever backend is default — use the env-level CPU recipe for a
hermetic run, or the real chip for the deployed layout).

Prints one JSON line per program:
  {"program", "bytes_accessed_per_epoch", "design_bytes_per_epoch",
   "flops_per_epoch", "bytes_ratio", ...}
``bytes_ratio`` > ~1.5 means the compiled program moves materially
more than the design — look for relayouts/materializations in the HLO.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cost(jitted, *args) -> dict:
    compiled = jitted.lower(*args).compile()
    ca = compiled.cost_analysis()
    # cost_analysis returns a dict (or list of dicts on older jax)
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return dict(ca or {})


def main() -> None:
    import jax
    import jax.numpy as jnp

    from eeg_dataanalysispackage_tpu.ops import device_ingest, dwt as dwt_xla

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    platform = jax.devices()[0].platform

    def report(name, jitted, args, design_bytes):
        # one line per program, printed AS PRODUCED: a later program's
        # compile failure (remote-compile crash, missing cost keys)
        # must not discard minutes of already-spent chip compiles
        try:
            c = _cost(jitted, *args)
        except Exception as e:  # noqa: BLE001 — tool must keep going
            print(json.dumps({"program": name, "error": str(e)[:300]}))
            sys.stdout.flush()
            return
        bytes_acc = c.get("bytes accessed")
        flops = c.get("flops")
        line = {
            "program": name,
            "platform": platform,
            "n_epochs": n,
            "bytes_accessed_per_epoch": (
                round(float(bytes_acc) / n, 1)
                if bytes_acc is not None
                else None
            ),
            "design_bytes_per_epoch": design_bytes,
            "bytes_ratio": (
                round(float(bytes_acc) / n / design_bytes, 3)
                if bytes_acc is not None and design_bytes
                else None
            ),
            "flops_per_epoch": (
                round(float(flops) / n, 1) if flops is not None else None
            ),
        }
        print(json.dumps(line))
        sys.stdout.flush()

    # headline: f32 epochs resident -> features (12 KB/epoch design)
    extract = dwt_xla.make_batched_extractor()
    epochs = jax.ShapeDtypeStruct((n, 3, 1000), jnp.float32)
    report("einsum", extract, (epochs,), 3 * 1000 * 4)

    # bf16 twin (6 KB/epoch design)
    extract_bf16 = dwt_xla.make_batched_extractor(dtype=jnp.bfloat16)
    epochs_bf16 = jax.ShapeDtypeStruct((n, 3, 1000), jnp.bfloat16)
    report("einsum_bf16", extract_bf16, (epochs_bf16,), 3 * 1000 * 2)

    # train step: epochs -> features -> MLP fwd/bwd/update, one jitted
    # program. Design is epochs-read dominated (12 KB/epoch) + the
    # (B, 48) f32 features materialized once and touched by fwd + bwd
    # (~0.6 KB): the r4 chip run reached only 35.4% of roofline vs the
    # feature-only 69.6% (VERDICT r4 weakness 6) — bytes_ratio >> 1
    # here localizes the gap to program traffic (optimizer-state /
    # loss-tail materializations); ratio ~1 means it's dispatch or
    # tiling, not bytes.
    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    # AOT-lower the raw jitted step (the factory returns a host-side
    # chaos-injection wrapper; __wrapped__ is the jit object)
    init_state, tstep = ptrain.make_train_step()
    tstep = ptrain._raw_step(tstep)
    state0 = init_state(jax.random.PRNGKey(0))
    vec_f = jax.ShapeDtypeStruct((n,), jnp.float32)
    report(
        "train_step",
        tstep,
        (state0, epochs, vec_f, vec_f),
        3 * 1000 * 4 + 3 * 48 * 4,
    )

    # the MLP half alone on precomputed (B, 48) features: subtracting
    # this row from train_step's separates extraction traffic from
    # optimizer/loss traffic
    _, fstep = ptrain.make_feature_train_step()
    fstep = ptrain._raw_step(fstep)
    feats = jax.ShapeDtypeStruct((n, 48), jnp.float32)
    report("feature_step", fstep, (state0, feats, vec_f, vec_f), 3 * 48 * 4)

    # regular int16 ingest, each formulation (4.8 KB/epoch design)
    stride = 800
    S = 200 + n * stride + 2 * 3200
    raw = jax.ShapeDtypeStruct((3, S), jnp.int16)
    res = jax.ShapeDtypeStruct((3,), jnp.float32)
    for formulation in ("reshape", "conv", "phase", "partial"):
        ing = device_ingest.make_regular_ingest_featurizer(
            stride, n, formulation=formulation
        )
        if formulation in ("phase", "partial"):
            # the public wrapper plans the aligned slab host-side;
            # cost the inner jitted program exactly as the wrapper
            # calls it (phase-0 tables, slab start 0). The raw length
            # must cover the aligned slab, whose geometry the
            # featurizer itself exports.
            m_groups, row = ing._phase_geometry
            raw_phase = jax.ShapeDtypeStruct(
                (3, max(S, (m_groups + 1) * row)), jnp.int16
            )
            if formulation == "phase":
                inner, targs = ing._phase_jit, ing._phase_tables(0)
            else:
                inner, targs = ing._partial_jit, (ing._partial_tables(0),)
            report(
                f"regular_{formulation}",
                inner,
                (raw_phase, res, 0, *targs),
                3 * stride * 2,
            )
        else:
            report(
                f"regular_{formulation}",
                ing._jit,
                (raw, res, 200),
                3 * stride * 2,
            )

    # block irregular ingest. Design bytes are the formulation's OWN
    # budget from docs/ingest_kernel.md (~61 KB/epoch: slab write+read
    # ~12 KB + the (C, n, BLK, K) variant tensor ~49 KB) — the
    # intermediates are inherent to the variant-bank design, so a
    # ratio near 1 is healthy and >1.5 still means unexpected copies.
    cap = ((n + 63) // 64) * 64
    block = device_ingest.make_block_ingest_featurizer()
    args = (
        jax.ShapeDtypeStruct((3, 200 + n * stride + 1000), jnp.int16),
        res,
        jax.ShapeDtypeStruct((cap,), jnp.int32),
        jax.ShapeDtypeStruct((cap,), jnp.bool_),
    )
    report("block_ingest", block, args, 61_000)


if __name__ == "__main__":
    main()
