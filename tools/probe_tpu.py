"""Kill-free axon tunnel probe: prints one JSON line and exits on its own.

Killing an axon process mid device-init or mid-compile is the known
tunnel-wedging event, so this probe carries NO external timeout
contract — it initializes the backend, jits one trivial op (never
eager through the tunnel), and returns by itself:

- healthy tunnel: ``{"ok": true, "platform": "axon", ...}`` in ~1 min
  cold / seconds warm,
- down-but-failing-fast tunnel: ``{"ok": false, "err": "...
  UNAVAILABLE ..."}`` (observed ~25 min to surface),
- truly wedged tunnel: hangs — the caller waits with it rather than
  killing it.

Used by tools/tunnel_watch.sh; fine standalone.
"""

import json
import time


def main() -> None:
    t0 = time.time()
    try:
        import jax
        import jax.numpy as jnp

        devs = jax.devices()
        r = jax.jit(lambda x: x * 2 + 1)(jnp.ones((8, 128), jnp.float32))
        r.block_until_ready()
        out = {
            "ok": True,
            "platform": devs[0].platform,
            "n_devices": len(devs),
            "t_s": round(time.time() - t0, 1),
        }
    except Exception as e:  # noqa: BLE001 — probe must always print
        out = {"ok": False, "err": str(e)[:300], "t_s": round(time.time() - t0, 1)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
