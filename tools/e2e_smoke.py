"""End-to-end pipeline smoke gate: cold -> warm -> fan-out.

Runs the pipeline_e2e trio (tools/pipeline_bench.py children, one
fresh process each — the same process discipline bench.py uses) over
one shared hermetic synthetic session and FAILS unless the
performance contract holds:

- the warm-cache run is faster than the cold run (the feature cache
  must actually buy something);
- the warm run hits the cache (hits > 0, and the cold run stored the
  entries it missed);
- cold and warm produce byte-identical ClassificationStatistics
  (``report_sha256`` equality — a cache that changes results is a
  correctness bug, not a speedup);
- the 5-classifier fan-out's logreg statistics match the
  single-classifier run's exactly (shared features must not perturb
  any individual classifier);
- fan-out wall time beats running its five classifiers as five
  single-classifier pipelines (the five singles are measured, not
  proxied — the old 3x-logreg-cold heuristic got flakier the warmer
  the machine, because the nn leg's fixed compile cost doesn't
  shrink with the page cache the way ingest does);
- the fan-out run compiles FEWER XLA programs than running its five
  classifiers as five single-classifier pipelines (the run reports'
  compile counters: fanout < sum of the five singles — the shared
  feature buffer / one-ingest contract, ISSUE-5 satellite);
- the 16-member population pair (population_vmap vs
  population_looped, tools/pipeline_bench.py): the vmapped engine's
  train stage is FASTER than the looped twin's, the two runs'
  ClassificationStatistics are byte-identical (report_sha256
  equality — per-member parity), and both trained all 16 members;
- the mesh gate (population_sharded, tools/pipeline_bench.py): the
  devices=1 degenerate-mesh run is report_sha256-IDENTICAL to the
  unmeshed vmapped run (the single-device mesh is byte-for-byte
  today's path), the forced-8-device CPU run is statistics-identical
  too with the mesh block present (rung=mesh, shape data:8,
  per-device member counts) in both the bench line and its
  run_report.json, and tools/obs_report.py renders + diffs the mesh
  block from the artifacts;
- every timed run wrote a well-formed ``run_report.json``
  (obs/report.py schema): nonzero stage spans for ingest/train/test,
  a span summary that actually recorded the stage spans, and
  feature-cache attribution identical to the bench line's
  ``feature_cache`` field (the report and the bench artifact must
  tell the same story);
- the serving layer (serve_smoke, tools/serve_bench.py): every
  concurrency level recorded p50/p99 latency and sustained
  predictions/sec, shed requests are COUNTED (the depth-1 burst
  probe shed and its counter matches), served predictions are
  bit-identical to the batch pipeline's on the same epochs, the
  chaos-injected soak (serve.request/serve.batch faults) terminated
  cleanly with every request resolved and a completed drain, and the
  ``serve=true`` pipeline run's ``run_report.json`` carries the
  ``serve`` block;
- the seizure workload (seizure_e2e, tools/pipeline_bench.py): one
  cost-swept population run (sweep=cost_fn:1,8 — the unit-weight
  member IS the unweighted baseline, trained in the same vmapped
  program); the synthetic continuous set is genuinely imbalanced,
  and the cost-sensitive member BEATS its unweighted twin on
  expected cost at the configured asymmetric costs (higher recall
  too) — the cost-sensitive knobs must buy what they claim on the
  workload they exist for; the run's ``run_report.json`` carries the
  ``workload`` and per-member ``classification`` blocks.

- the multi-tenant plan executor (scheduler_multi,
  tools/pipeline_bench.py — ISSUE 10): 4 plans run concurrently are
  no slower than the same 4 sequential (>= within a 5% scheduling
  -noise floor) with byte-identical statistics across the phases;
  every plan wrote its OWN intact run_report.json (plan_id +
  statistics sha cross-checked); the shared feature cache kept
  exactly one rebuild under concurrency (the single-flight guard);
  and a SIGKILLed child's journal recovers every unfinished plan to
  statistics identical to uninterrupted twins without re-running the
  completed one;

- the networked plan service (plan_service,
  tools/pipeline_bench.py — ISSUE 11): a shared-prefix pair of tenant
  plans submitted over loopback HTTP computes the ingest+featurize
  prefix exactly once (one feature-cache store; the follower a dedup
  hit with leader/bytes-saved attribution in its own run report) with
  BOTH plans' statistics byte-identical to their solo dedup=false
  runs; an idempotency-keyed re-submit of the completed leader
  replays the ORIGINAL plan id over HTTP 200 without re-executing;
  and a many-client chaos soak (clean + faults=scheduler.plan
  clients interleaved) resolves every plan with clean-twin
  statistics and a recorded submits/sec;

- the replicated gateway fleet (gateway_fleet,
  tools/pipeline_bench.py — ISSUE 17): three real replica processes
  over ONE shared journal directory; the replica executing the heavy
  plan is SIGKILLed mid-run and a SURVIVOR completes the plan under
  its original id with statistics byte-identical to an uninterrupted
  fresh-process twin, exactly once (one terminal record per plan,
  zero corrupt quarantines, zero leftover leases, and the survivors'
  ``scheduler.completed`` sum equals the expected execution count);
  a keyed re-submit after the takeover replays the original id; a
  live ``fleet_top`` /metrics sweep taken after the takeover sees
  exactly the survivors up (the victim a DOWN row) with scraped
  completion/takeover counters agreeing with the journal audit; and
  the surviving replicas drain to exit 0 on a real SIGTERM;

- device-aware fleet placement (fleet_placement,
  tools/pipeline_bench.py — ISSUE 20): the same 3-replica fleet run
  twice over a forced-8-virtual-device host — shared device pool on
  vs off — driving one whole-pool gang plan plus four single-device
  plans; the placed fleet must finish at a makespan no worse than the
  placement-disabled twin, every plan byte-identical to its
  fresh-process twin, the gang granted all 8 leased ordinals (named
  in its journal meta), the live lease audit observing zero
  double-held ordinals and nothing beyond the pool, zero device
  leases after the drain;

- the observability plane (ISSUE 19): a telemetry-off cold twin (no
  report dir) produces statistics byte-identical to the instrumented
  cold run (observation never steers) and the instrumented wall stays
  inside the shared-box noise floor of the unobserved twin's;

- the PR 8 ingest gates: the overlap=true cold twin produces
  byte-identical statistics to the serial cold run (double-buffered
  ingest reschedules work, never changes it); the precision=bf16 twin
  records its accuracy-gate decision (now carrying ``gate_seconds`` —
  the gate's double-featurize cost, attributed instead of hidden in
  the wall) and, when the gate passed, ran inside the documented
  tolerance; a forced-gate-off bf16 run (EEG_TPU_BF16_GATE_TOL=0)
  auto-disables AND produces statistics byte-identical to the f32
  cold run; and pipeline_e2e_cold beats the BENCH_pr5 plateau in
  machine-normalized form (cold eps / einsum eps measured now vs the
  same ratio from the committed artifact — raw eps would gate on this
  box's 2x load swings, not on the code).

- the serve megakernel (serve_mega, tools/serve_bench.py — the PR 12
  tentpole): the mega rung actually promoted (warmup parity gate
  passed against the fused program), served predictions bit-identical
  to the fused twin AND the batch pipeline, one window's margin
  bit-identical whatever batch it rides in (the within-bucket pin),
  and at concurrency 16 the mega rung's predictions/sec and p99 are
  no worse than the same-process fused twin's (a small scheduling-
  noise floor applied — the rungs are measured back-to-back seconds
  apart, but this is still a shared box);

- the int8 precision rung (pipeline_e2e_int8 + the serve_mega line's
  int8_gate): the gate decision is recorded (used=int8 inside the
  documented tolerance, or the auto-disable), a forced-gate-off int8
  run (EEG_TPU_INT8_GATE_TOL=0) auto-disables AND produces statistics
  byte-identical to the f32 cold run, and the serving engine's int8
  warmup gate decision rides the serve_mega line.

- the model lifecycle manager (serve_lifecycle, tools/serve_bench.py
  — the ISSUE 15 tentpole): a gate-off lifecycle service's served
  predictions bit-identical to the batch pipeline (staging + shadow-
  scoring a candidate never touches the live path), at least one
  promotion landing DURING closed-loop load with the across-promotion
  p99 inside the noise floor of the steady-state pass, the promoted
  candidate served online bit-identical to its ``promoted.npz``
  checkpoint's batch predictions, the serve.swap/serve.adapt p=0.2
  chaos soak resolving every request with a failed swap leaving the
  live model untouched, and the ``lifecycle`` block present in the
  adapt run's run_report.json.

- the multiplexed multi-tenant engine (serve_multitenant,
  tools/serve_bench.py — the ISSUE 16 tentpole): every tenant's
  predictions out of the mixed-tenant stream bit-identical to that
  tenant's solo engine, tenant scaling 1→16 and a hot tenant swap at
  0 XLA compiles (the one resident program serves any tenant mix),
  and the 16-tenant multiplexed throughput at concurrency 16 no
  worse than the 16-engine solo fleet it replaces (0.9x noise
  floor, back-to-back on a shared box).

- the int4 precision rung (pipeline_e2e_int4 — the ISSUE 18
  tentpole's feature half): the bottom of the ladder rides the SAME
  gate contract as bf16/int8 — a decision recorded on every run,
  measured deviation inside the int4 envelope when it served, and
  the forced-gate-off twin (EEG_TPU_INT4_GATE_TOL=0) auto-disabled
  AND byte-identical to the f32 cold run.

- the quantized tenant weight stack (serve_multitenant_quant,
  tools/serve_bench.py — the ISSUE 18 tentpole's serving half): the
  warmup gate decision recorded, 16-tenant margins within the
  derived weights tolerance of the f32 multiplexed twin, >=4x
  resident-weight-bytes reduction, tenant add/swap/remove at 0 XLA
  compiles on the LIVE quantized stack, quant throughput >=0.95x
  the f32 twin (noise floor applied — shared box), and the
  forced-gate-off twin (EEG_TPU_WEIGHTS_GATE_TOL=0) serving the f32
  stack with margins bit-identical to the twin.

Usage: python tools/e2e_smoke.py [n_markers_per_file] [n_files]

Prints a JSON summary line; exit 0 iff every gate passed. Wired into
the suite as a ``slow``-marked pytest (tests/test_e2e_smoke.py), so
tier-1 stays fast while CI can still run the whole ladder.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PIPELINE_BENCH = os.path.join(_REPO, "tools", "pipeline_bench.py")
_SERVE_BENCH = os.path.join(_REPO, "tools", "serve_bench.py")

#: the run-report gates :func:`run` drives through ``_check_report``,
#: in call order. The summary's ``reports_checked`` count and the
#: suite's pin (tests/test_e2e_smoke.py) are BOTH derived from this
#: registry, so growing the checked set is one edit here — never a
#: hand-maintained integer chase across files.
REPORT_CHECKS = (
    "cold", "warm", "fanout", "pop_vmap", "pop_looped", "pop_sharded",
)


def _run_serve_bench(n_markers: int, n_files: int,
                     report_dir: str = None,
                     variant: str = "serve_bench",
                     env_extra: dict = None) -> dict:
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(
        [
            sys.executable, _SERVE_BENCH, variant,
            str(n_markers), str(n_files),
            *([f"--report-dir={report_dir}"] if report_dir else []),
        ],
        capture_output=True,
        text=True,
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{variant} child failed rc={proc.returncode}\n"
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _check_serve(line: dict, report_dir: str, failures: list) -> None:
    """The serve_smoke gate: latency/throughput recorded per level,
    sheds counted, parity pinned, chaos soak clean, serve block in
    the run report."""
    serve = line.get("serve") or {}
    sweep = serve.get("sweep") or []
    if not sweep:
        failures.append("serve: no concurrency sweep recorded")
    for level in sweep:
        for key in ("p50_ms", "p99_ms", "preds_per_s"):
            if not level.get(key, 0.0) > 0.0:
                failures.append(
                    f"serve: concurrency {level.get('concurrency')} "
                    f"did not record {key}: {level}"
                )
    probe = serve.get("shed_probe") or {}
    if not probe.get("ok"):
        failures.append(
            f"serve: shed probe failed (sheds must happen AND be "
            f"counted): {probe}"
        )
    parity = serve.get("parity") or {}
    if not parity.get("bit_identical"):
        failures.append(
            f"serve: served predictions drifted from the batch "
            f"pipeline: {parity}"
        )
    chaos_block = serve.get("chaos") or {}
    if not chaos_block.get("chaos_clean"):
        failures.append(
            f"serve: chaos soak did not terminate cleanly: "
            f"{chaos_block}"
        )
    report_path = os.path.join(report_dir, "run_report.json")
    try:
        with open(report_path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        failures.append(f"serve: no readable run_report.json: {e}")
        return
    block = report.get("serve")
    if not block or "latency_ms" not in block:
        failures.append(
            f"serve: run_report.json has no serve block: {block}"
        )
    elif block.get("drained_cleanly") is not True:
        failures.append(
            f"serve: report says the drain did not complete: "
            f"{block.get('drained_cleanly')}"
        )


def _check_serve_mega(line: dict, failures: list) -> None:
    """The megakernel gate (the PR 12 tentpole's acceptance): the
    mega rung promoted behind its warmup parity pin, served
    predictions bit-identical to the fused twin and the batch path,
    the within-bucket margin bit-identity, and the concurrency-16
    throughput/latency no worse than the same-process fused twin
    (0.9x preds / 1.25x p99 noise floors — the pair is measured
    back-to-back, but the box is shared)."""
    mv = (line.get("serve") or {}).get("mega_vs_fused") or {}
    if not mv:
        failures.append("serve_mega: no mega_vs_fused block on the line")
        return
    if mv.get("mega_rung") != "mega":
        failures.append(
            f"serve_mega: the mega rung did not serve (rung "
            f"{mv.get('mega_rung')}; engine record "
            f"{(line.get('serve') or {}).get('engine')})"
        )
    parity = mv.get("parity") or {}
    if not (
        parity.get("bit_identical")
        and parity.get("vs_batch_bit_identical")
    ):
        failures.append(
            f"serve_mega: served predictions drifted (vs fused/batch): "
            f"{parity}"
        )
    if mv.get("bucket_identical") is not True:
        failures.append(
            "serve_mega: a window's margin changed with its batch "
            "(within-bucket bit-identity broken)"
        )
    level16 = next(
        (lv for lv in mv.get("sweep") or []
         if lv.get("concurrency") == 16),
        None,
    )
    if level16 is None:
        failures.append("serve_mega: no concurrency-16 sweep level")
    else:
        mega, fused = level16.get("mega") or {}, level16.get("fused") or {}
        if not mega.get("preds_per_s", 0.0) >= 0.9 * fused.get(
            "preds_per_s", 0.0
        ):
            failures.append(
                f"serve_mega: mega preds/sec worse than the fused twin "
                f"at concurrency 16: {mega.get('preds_per_s')} vs "
                f"{fused.get('preds_per_s')}"
            )
        if not mega.get("p99_ms", 1e9) <= 1.25 * fused.get(
            "p99_ms", 0.0
        ):
            failures.append(
                f"serve_mega: mega p99 worse than the fused twin at "
                f"concurrency 16: {mega.get('p99_ms')}ms vs "
                f"{fused.get('p99_ms')}ms"
            )
    int8_gate = (line.get("serve") or {}).get("int8_gate") or {}
    if int8_gate.get("requested") != "int8" or "used" not in int8_gate:
        failures.append(
            f"serve_mega: no int8 gate decision recorded: {int8_gate}"
        )


def _check_lifecycle(line: dict, report_dir: str,
                     failures: list) -> None:
    """The model-lifecycle gate (the ISSUE 15 acceptance): the
    no-swap byte-identity pin (a lifecycle-enabled gate-off service
    serves exactly the batch predictions), the promoted==batch parity
    pin (the swapped-in candidate served online equals its checkpoint
    run over the batch features), the p99 across a promotion within
    the noise floor of steady state (10x — promotions race full
    closed-loop load on a shared box), the serve.swap/serve.adapt
    chaos soak clean with a failed swap provably leaving the live
    model untouched, and the ``lifecycle`` block in the adapt run's
    run_report.json."""
    serve = line.get("serve") or {}
    no_swap = serve.get("no_swap_parity") or {}
    if not no_swap.get("bit_identical") or no_swap.get("swaps") != 0:
        failures.append(
            f"lifecycle: the no-swap byte-identity pin broke (a "
            f"gate-off lifecycle must not touch the live path): "
            f"{no_swap}"
        )
    promoted = serve.get("promoted_parity") or {}
    if not promoted.get("swapped"):
        failures.append(
            "lifecycle: no promotion happened under the permissive "
            f"gate: {serve.get('lifecycle')}"
        )
    elif not promoted.get("bit_identical"):
        failures.append(
            f"lifecycle: promoted-candidate served predictions "
            f"drifted from its checkpoint's batch run: {promoted}"
        )
    swaps_seen = 0
    for level in serve.get("sweep") or []:
        swaps_seen += level.get("swaps_during", 0)
        if not level.get("p99_ratio", 0.0) > 0.0:
            failures.append(
                f"lifecycle: concurrency {level.get('concurrency')} "
                f"recorded no p99 ratio: {level}"
            )
        elif level.get("swaps_during", 0) and level["p99_ratio"] > 10.0:
            failures.append(
                f"lifecycle: p99 across a promotion left the noise "
                f"floor at concurrency {level.get('concurrency')}: "
                f"{level['p99_ratio']}x steady state"
            )
    if swaps_seen < 1:
        failures.append(
            "lifecycle: no swap landed during any load level "
            "(swap-under-load unmeasured)"
        )
    chaos_block = serve.get("chaos") or {}
    if not chaos_block.get("chaos_clean"):
        failures.append(
            f"lifecycle: serve.swap/serve.adapt soak did not "
            f"terminate cleanly: {chaos_block}"
        )
    if not chaos_block.get("live_untouched_on_failed_swap"):
        failures.append(
            f"lifecycle: a failed swap touched the live model: "
            f"{chaos_block}"
        )
    report_path = os.path.join(report_dir, "run_report.json")
    try:
        with open(report_path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        failures.append(f"lifecycle: no readable run_report.json: {e}")
        return
    block = report.get("lifecycle")
    if not block or not block.get("enabled"):
        failures.append(
            f"lifecycle: run_report.json has no lifecycle block: "
            f"{block}"
        )
    elif block.get("feedback", {}).get("received", 0) < 1:
        failures.append(
            f"lifecycle: the adapt run's report recorded no feedback: "
            f"{block.get('feedback')}"
        )


def _check_multitenant(line: dict, failures: list) -> None:
    """The multiplexed multi-tenant gate (the ISSUE 16 acceptance):
    every tenant's multiplexed predictions bit-identical to its solo
    engine, tenant scaling 1→16 and a hot swap at 0 XLA compiles
    (one compile serves any tenant mix), and the 16-tenant
    multiplexed throughput at concurrency 16 no worse than the
    16-engine solo fleet (0.9x noise floor — the pair is measured
    back-to-back, but the box is shared)."""
    mt = (line.get("serve") or {}).get("multitenant") or {}
    if not mt:
        failures.append(
            "serve_multitenant: no multitenant block on the line"
        )
        return
    parity = mt.get("parity") or {}
    if not parity.get("bit_identical"):
        failures.append(
            f"serve_multitenant: a tenant's served predictions "
            f"drifted from its solo engine: {parity}"
        )
    compiles = mt.get("compiles") or {}
    if compiles.get("available") and compiles.get("scaling", 0) != 0:
        failures.append(
            f"serve_multitenant: scaling 1→16 tenants recompiled "
            f"({compiles.get('scaling')} compiles; the resident "
            f"program must serve any tenant mix)"
        )
    swap = mt.get("swap") or {}
    if compiles.get("available") and swap.get("compiles", 0) != 0:
        failures.append(
            f"serve_multitenant: a hot tenant swap recompiled: {swap}"
        )
    level16 = next(
        (lv for lv in mt.get("levels") or []
         if lv.get("tenants") == 16),
        None,
    )
    if level16 is None:
        failures.append("serve_multitenant: no 16-tenant level")
    else:
        mult = (level16.get("multiplexed") or {}).get(
            "preds_per_s", 0.0
        )
        fleet = (level16.get("solo_fleet") or {}).get(
            "preds_per_s", 0.0
        )
        if not mult >= 0.9 * fleet:
            failures.append(
                f"serve_multitenant: multiplexed worse than the solo "
                f"fleet at 16 tenants: {mult} vs {fleet} preds/s"
            )
        if (level16.get("multiplexed") or {}).get("unresolved"):
            failures.append(
                f"serve_multitenant: unresolved requests at the "
                f"16-tenant level: {level16.get('multiplexed')}"
            )


def _check_multitenant_quant(line: dict, off_line: dict,
                             failures: list) -> None:
    """The quantized weight stack gate (the ISSUE 18 serving-half
    acceptance): warmup gate decision recorded; when the int4 stack
    served, its measured deviation inside the derived tolerance and
    every 16-tenant margin within that tolerance of the f32
    multiplexed twin; >=4x resident-weight-bytes reduction; tenant
    add/swap/remove at 0 XLA compiles on the live quantized stack;
    quant conc-16 throughput >=0.95x the f32 twin (with the same
    shared-box noise allowance the serve_multitenant gate applies);
    and the forced-gate-off twin (EEG_TPU_WEIGHTS_GATE_TOL=0) serving
    the f32 stack with margins bit-identical to the twin's."""
    mq = (line.get("serve") or {}).get("multitenant_quant") or {}
    if not mq:
        failures.append(
            "serve_multitenant_quant: no multitenant_quant block on "
            "the line"
        )
        return
    weights = mq.get("weights") or {}
    gate = weights.get("gate") or {}
    if weights.get("requested") != "int4" or "used" not in weights:
        failures.append(
            f"serve_multitenant_quant: no weights gate decision "
            f"recorded: {weights}"
        )
    elif weights["used"] == "int4":
        if not (
            gate.get("ok")
            and gate.get("max_abs_dev", 1.0)
            <= gate.get("tolerance", 0.0)
        ):
            failures.append(
                f"serve_multitenant_quant: int4 stack served outside "
                f"its gate: {gate}"
            )
        admin = mq.get("admin") or {}
        if not admin.get("compiles_zero_ok"):
            failures.append(
                f"serve_multitenant_quant: tenant admin on the "
                f"quantized stack recompiled: {admin}"
            )
        if not admin.get("still_quantized"):
            failures.append(
                f"serve_multitenant_quant: tenant admin degraded the "
                f"stack to f32: {admin}"
            )
        parity = mq.get("parity") or {}
        if not parity.get("within_tolerance"):
            failures.append(
                f"serve_multitenant_quant: 16-tenant margins drifted "
                f"past the weights tolerance of the f32 twin: {parity}"
            )
        resident = mq.get("resident") or {}
        if not resident.get("reduction", 0.0) >= 4.0:
            failures.append(
                f"serve_multitenant_quant: resident-weight-bytes "
                f"reduction below the 4x bar: {resident}"
            )
        qps = (mq.get("quant") or {}).get("preds_per_s", 0.0)
        fps = (mq.get("f32") or {}).get("preds_per_s", 0.0)
        # nominal pin 0.95x (the dequant toll must stay in the noise);
        # measured with the same 0.9x-style shared-box allowance the
        # serve_multitenant fleet gate applies, so 0.9 * 0.95
        if not qps >= 0.9 * 0.95 * fps:
            failures.append(
                f"serve_multitenant_quant: quantized stack slower "
                f"than 0.95x the f32 twin at conc 16 (noise floor "
                f"applied): {qps} vs {fps} preds/s"
            )
        if (mq.get("quant") or {}).get("unresolved"):
            failures.append(
                f"serve_multitenant_quant: unresolved requests on "
                f"the quantized stack: {mq.get('quant')}"
            )
    if not mq.get("drained_cleanly"):
        failures.append(
            "serve_multitenant_quant: a service did not drain cleanly"
        )
    # the forced-gate-off drill: the gate must refuse (recorded), the
    # run serves the f32 stack, and — both sides then running the SAME
    # f32 program over the SAME host mirror — margins are bit-identical
    off = (off_line.get("serve") or {}).get("multitenant_quant") or {}
    off_weights = off.get("weights") or {}
    if off_weights.get("used") != "f32" or (
        off_weights.get("gate") or {}
    ).get("ok") is not False:
        failures.append(
            f"serve_multitenant_quant: forced gate-off did not refuse "
            f"the quantized stack: {off_weights}"
        )
    off_parity = off.get("parity") or {}
    if not (
        off_parity.get("max_abs_margin_dev") == 0.0
        and off_parity.get("prediction_mismatches") == 0
    ):
        failures.append(
            f"serve_multitenant_quant: gated-off stack's margins not "
            f"bit-identical to the f32 twin: {off_parity}"
        )


def _run_variant(variant: str, n_markers: int, n_files: int,
                 data_dir: str, cache_dir: str,
                 report_dir: str, extra: list = (),
                 env_extra: dict = None) -> dict:
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    # report_dir=None: the child manages its own report layout (the
    # scheduler_multi variant writes one run_report.json PER PLAN
    # under its executor's report root — a single shared dir would
    # make the tenants clobber each other's artifact)
    report_args = (
        [] if report_dir is None else [f"--report-dir={report_dir}"]
    )
    proc = subprocess.run(
        [
            sys.executable, _PIPELINE_BENCH, variant,
            str(n_markers), str(n_files),
            f"--data-dir={data_dir}", f"--cache-dir={cache_dir}",
            *report_args, *extra,
        ],
        capture_output=True,
        text=True,
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{variant} child failed rc={proc.returncode}\n"
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _einsum_eps_now() -> float:
    """A quick same-machine compute probe (the einsum headline at a
    small batch) — the denominator that makes cross-artifact e2e
    comparisons machine-speed-normalized (this box's load swings 2x
    between runs; raw eps comparisons would gate on the weather)."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "tools", "ingest_bench.py"),
            "einsum", "8192", "3",
        ],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"einsum probe failed rc={proc.returncode}\n"
            f"{proc.stderr[-1000:]}"
        )
    return float(
        json.loads(proc.stdout.strip().splitlines()[-1])["epochs_per_s"]
    )


def _check_plateau(cold: dict, failures: list) -> dict:
    """The ISSUE 8 acceptance gate: the pipeline_e2e_cold number must
    move past the BENCH_pr5 plateau, machine-normalized (cold eps /
    einsum eps vs the same ratio from the committed BENCH_pr5.json).
    The authoritative ratio is the one the cold CHILD embedded —
    its einsum probe ran in-process immediately after the timed query
    (tools/pipeline_bench._einsum_probe_eps), and this box's load
    swings 2-4x between smoke variants, so a probe run HERE (after
    the fan-out/population/serve/seizure children) would re-import
    exactly the noise normalization removes. The subprocess probe is
    only the fallback for a cold line that carries no normalized
    ratio (e.g. a BENCH_pr5.json without an einsum value)."""
    plateau = cold.get("plateau") or {}
    pr5_cold = plateau.get("pr5_cold_eps")
    pr5_einsum = plateau.get("pr5_einsum_eps")
    if not pr5_cold or not pr5_einsum:
        failures.append(
            f"plateau: BENCH_pr5 reference missing from the cold "
            f"line: {plateau}"
        )
        return {}
    ratio_pr5 = pr5_cold / pr5_einsum
    if "normalized_ratio" in plateau:
        einsum_now = plateau.get("einsum_probe_eps")
        ratio_now = plateau["normalized_ratio"]
    else:
        einsum_now = _einsum_eps_now()
        ratio_now = cold["epochs_per_s"] / einsum_now
    if not ratio_now > ratio_pr5:
        failures.append(
            f"plateau: cold e2e did not beat the BENCH_pr5 plateau "
            f"(machine-normalized {ratio_now:.5f} vs pr5 "
            f"{ratio_pr5:.5f}; cold {cold['epochs_per_s']} eps, "
            f"einsum probe {einsum_now})"
        )
    return {
        "cold_eps": cold["epochs_per_s"],
        "einsum_eps_now": einsum_now,
        "normalized_ratio": round(ratio_now, 5),
        "pr5_normalized_ratio": round(ratio_pr5, 5),
        "beats_pr5_plateau": ratio_now > ratio_pr5,
    }


#: stages a timed pipeline run must have spent real time in
_REQUIRED_STAGES = ("ingest", "train", "test")


def _check_mesh(sharded: dict, sharded1: dict, vmap_line: dict,
                sharded_report_dir: str, vmap_report_dir: str,
                failures: list) -> None:
    """The multi-device mesh gate: devices=1 report_sha256-identical
    to the unmeshed run, the forced-8-device run statistics-identical
    with the mesh block present (bench line AND run report), and
    tools/obs_report.py rendering/diffing the block."""
    if sharded1["report_sha256"] != vmap_line["report_sha256"]:
        failures.append(
            "mesh: devices=1 degenerate run drifted from the unmeshed "
            f"run: {sharded1['report_sha256']} vs "
            f"{vmap_line['report_sha256']}"
        )
    if sharded["report_sha256"] != vmap_line["report_sha256"]:
        failures.append(
            "mesh: 8-device sharded statistics drifted from the "
            f"single-device run: {sharded['report_sha256']} vs "
            f"{vmap_line['report_sha256']}"
        )
    mesh = sharded.get("mesh") or {}
    pop_mesh = mesh.get("population") or {}
    if mesh.get("rung") != "mesh" or mesh.get("shape") != {"data": 8}:
        failures.append(
            f"mesh: 8-device line did not land on the mesh rung: {mesh}"
        )
    if pop_mesh.get("rung") != "mesh" or not pop_mesh.get(
        "members_per_device"
    ):
        failures.append(
            f"mesh: per-device member counts missing from the line: "
            f"{pop_mesh}"
        )
    report_path = os.path.join(sharded_report_dir, "run_report.json")
    try:
        with open(report_path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        failures.append(f"mesh: no readable run_report.json: {e}")
        return
    if (report.get("mesh") or {}).get("rung") != "mesh":
        failures.append(
            f"mesh: run_report.json mesh block missing/degraded: "
            f"{report.get('mesh')}"
        )
    # the artifacts must be renderable + diffable with the mesh block
    # visible (tools/obs_report.py is the operator's lens)
    obs_report = os.path.join(_REPO, "tools", "obs_report.py")
    show = subprocess.run(
        [sys.executable, obs_report, "show", report_path],
        capture_output=True, text=True,
    )
    if show.returncode != 0 or "mesh" not in show.stdout:
        failures.append(
            f"mesh: obs_report.py show did not render the mesh block "
            f"(rc={show.returncode})"
        )
    diff = subprocess.run(
        [
            sys.executable, obs_report, "diff", report_path,
            os.path.join(vmap_report_dir, "run_report.json"),
        ],
        capture_output=True, text=True,
    )
    if diff.returncode != 0 or "mesh" not in diff.stdout:
        failures.append(
            f"mesh: obs_report.py diff did not surface the mesh drift "
            f"(rc={diff.returncode})"
        )


def _check_multiproc(line: dict, failures: list) -> None:
    """The pod-scale gate (ISSUE 14): the 2-process loopback run is
    byte-identical to its single-process twin (parity sha), the mesh
    block carries the pod coordinates and the SHARDED population
    rung, and the degraded-coordinator run lands the single-host rung
    with its evidence — without failing."""
    block = line.get("multiproc") or {}
    if not block.get("parity_sha_ok"):
        failures.append(
            f"multiproc: 2-process statistics drifted from the "
            f"single-process twin: {block}"
        )
    mesh = block.get("mesh") or {}
    if mesh.get("rung") != "pod":
        failures.append(
            f"multiproc: run did not land the pod rung: {mesh}"
        )
    if mesh.get("dcn_shape") != {"hosts": 2} or mesh.get(
        "processes"
    ) != 2 or not mesh.get("coordinator"):
        failures.append(
            f"multiproc: pod coordinates missing from the mesh "
            f"block: {mesh}"
        )
    pop = mesh.get("population") or {}
    if pop.get("rung") != "mesh" or not pop.get("members_per_device"):
        failures.append(
            f"multiproc: population did not shard over the pod: {pop}"
        )
    if not block.get("members_per_s"):
        failures.append(f"multiproc: no members/sec recorded: {block}")
    degraded = block.get("degraded_coordinator") or {}
    if (
        degraded.get("rung") != "single_host"
        or not degraded.get("error_present")
        or not degraded.get("parity_ok")
    ):
        failures.append(
            f"multiproc: degraded-coordinator run did not land the "
            f"single-host rung with evidence + parity: {degraded}"
        )


def _check_seizure(line: dict, report_dir: str,
                   failures: list) -> None:
    """The seizure-workload gate: an imbalanced synthetic set, the
    cost-swept population's weighted member beating its unweighted
    twin (same vmapped program, same rows) on expected cost AND
    recall at the same asymmetric costs, and a run report carrying
    the workload + per-member classification blocks."""
    block = line.get("seizure") or {}
    w = block.get("weighted") or {}
    u = block.get("unweighted") or {}
    if not w or not u:
        failures.append(
            f"seizure: missing weighted/unweighted members: {block}"
        )
        return
    ratio = block.get("class_ratio", 1.0)
    if not 0.0 < ratio < 0.35:
        failures.append(
            f"seizure: synthetic set not imbalanced (class_ratio="
            f"{ratio})"
        )
    if not w.get("expected_cost", 1e9) < u.get("expected_cost", 0.0):
        failures.append(
            f"seizure: cost-sensitive member did not beat the "
            f"unweighted twin on expected cost: "
            f"{w.get('expected_cost')} vs {u.get('expected_cost')}"
        )
    if not w.get("recall", 0.0) > (u.get("recall") or 0.0):
        failures.append(
            f"seizure: cost-sensitive member did not raise recall: "
            f"{w.get('recall')} vs {u.get('recall')}"
        )
    if not block.get("windows_per_s", 0.0) > 0.0:
        failures.append(
            f"seizure: no windows/sec recorded: {block}"
        )
    report_path = os.path.join(report_dir, "run_report.json")
    try:
        with open(report_path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        failures.append(f"seizure: no readable run_report.json: {e}")
        return
    workload = report.get("workload") or {}
    if workload.get("task") != "seizure" or not workload.get("windows"):
        failures.append(
            f"seizure: run_report.json workload block missing/empty: "
            f"{workload}"
        )
    classification = report.get("classification") or {}
    # a population run's classification block is per-member
    if not any(
        isinstance(v, dict) and "expected_cost" in v
        for v in classification.values()
    ):
        failures.append(
            f"seizure: run_report.json classification block missing "
            f"per-member expected_cost: {classification}"
        )


def _check_scheduler(line: dict, failures: list) -> None:
    """The multi-tenant executor gate (ISSUE 10): N concurrent plans
    must not run slower than the same N sequential (>= within a 5%
    scheduling-noise floor), both phases must produce identical
    statistics, every plan must have written its own intact
    run_report.json, the shared feature cache must have kept exactly
    ONE rebuild under concurrency (single-flight), and the
    kill-and-resume scenario must have recovered every unfinished
    plan to twin-identical statistics without re-running the
    completed one."""
    sched = line.get("scheduler") or {}
    if not sched:
        failures.append("scheduler: no scheduler block on the line")
        return
    speedup = sched.get("concurrent_speedup", 0.0)
    if not speedup >= 0.95:
        failures.append(
            f"scheduler: concurrent throughput below sequential "
            f"(speedup {speedup}; walls "
            f"{sched.get('wall_concurrent_s')}s vs "
            f"{sched.get('wall_sequential_s')}s)"
        )
    if not sched.get("parity_sequential_vs_concurrent"):
        failures.append(
            "scheduler: concurrent statistics drifted from the "
            "sequential twins"
        )
    for phase in ("sequential", "concurrent"):
        block = sched.get(phase) or {}
        if not block.get("reports_ok"):
            failures.append(
                f"scheduler: {phase} per-plan run_report.json "
                f"integrity failed"
            )
        if block.get("stores") != 1:
            failures.append(
                f"scheduler: {phase} phase kept {block.get('stores')} "
                f"feature rebuilds, not exactly 1 (single-flight)"
            )
    crash = sched.get("crash_recovery") or {}
    if not (
        crash.get("killed")
        and crash.get("completed_kept") == 1
        and crash.get("resumed", 0) >= 1
        and crash.get("identical")
    ):
        failures.append(
            f"scheduler: kill-and-resume pin failed: {crash}"
        )


def _check_plan_service(line: dict, failures: list) -> None:
    """The networked plan service gate (ISSUE 11): the shared-prefix
    tenant pair over loopback HTTP computed its ingest+featurize
    prefix exactly once (one feature-cache store, the follower a
    dedup hit with leader attribution), BOTH deduped statistics are
    byte-identical to the solo dedup=false twins, an idempotency-keyed
    re-submit of the completed leader replayed the ORIGINAL plan id
    without re-executing, and the many-client chaos soak resolved
    every plan with clean-twin statistics while recording a nonzero
    submits/sec at the front door."""
    ps = line.get("plan_service") or {}
    if not ps:
        failures.append("plan_service: no plan_service block on the line")
        return
    pair = ps.get("pair") or {}
    dedup = pair.get("dedup") or {}
    if not dedup.get("hit_ratio", 0) > 0 or dedup.get("hits", 0) < 1:
        failures.append(
            f"plan_service: shared-prefix pair recorded no dedup hit: "
            f"{dedup}"
        )
    if pair.get("stores") != 1:
        failures.append(
            f"plan_service: pair kept {pair.get('stores')} prefix "
            f"builds, not exactly 1"
        )
    if not pair.get("statistics_identical_to_solo"):
        failures.append(
            "plan_service: deduped statistics drifted from the solo "
            "unshared runs"
        )
    attribution = pair.get("follower_attribution") or {}
    if not (
        attribution.get("role") == "follower"
        and attribution.get("leader_plan")
        and attribution.get("bytes_saved", 0) > 0
    ):
        failures.append(
            f"plan_service: follower attribution missing from the "
            f"follower's run report: {attribution}"
        )
    resubmit = pair.get("idempotent_resubmit") or {}
    if not (
        resubmit.get("http") == 200
        and resubmit.get("same_plan_id")
        and resubmit.get("replayed")
    ):
        failures.append(
            f"plan_service: idempotent re-submit did not replay the "
            f"original plan id: {resubmit}"
        )
    soak = ps.get("soak") or {}
    if not (soak.get("all_resolved") and soak.get("statistics_identical")):
        failures.append(
            f"plan_service: chaos soak not clean: resolved="
            f"{soak.get('all_resolved')} identical="
            f"{soak.get('statistics_identical')}"
        )
    if not soak.get("submits_per_s", 0) > 0:
        failures.append(
            f"plan_service: no submits/sec recorded: {soak}"
        )


def _check_fleet(line: dict, failures: list) -> None:
    """The replicated-fleet gate (ISSUE 17): three real replica
    processes over one shared journal; the replica executing the heavy
    plan is SIGKILLed mid-run and a survivor must complete it under
    the ORIGINAL plan id with statistics byte-identical to an
    uninterrupted twin — exactly once (journal audit + the survivors'
    completion-counter sum), with the keyed re-submit replaying the
    takeover's outcome and the surviving replicas draining to exit 0
    on a real SIGTERM."""
    fleet = line.get("fleet") or {}
    if not fleet:
        failures.append("fleet: no fleet block on the line")
        return
    if not (fleet.get("all_terminal") and fleet.get("all_completed")):
        failures.append(
            f"fleet: not every plan completed after the kill: "
            f"{(fleet.get('plans') or {}).get('states')}"
        )
    takeover = fleet.get("takeover") or {}
    if not (
        takeover.get("sha_identical_to_twin")
        and takeover.get("takeover_recorded")
        and takeover.get("not_victim")
    ):
        failures.append(
            f"fleet: takeover did not reproduce the victim's plan "
            f"byte-identically on a surviving peer: {takeover}"
        )
    if not fleet.get("quick_sha_identical"):
        failures.append(
            "fleet: quick plans' statistics drifted from the "
            "fresh-process twin"
        )
    resubmit = fleet.get("resubmit_after_takeover") or {}
    if not (
        resubmit.get("http") == 200
        and resubmit.get("same_plan_id")
        and resubmit.get("replayed")
    ):
        failures.append(
            f"fleet: keyed re-submit after the takeover did not "
            f"replay the original plan id: {resubmit}"
        )
    audit = fleet.get("journal_audit") or {}
    if not (
        audit.get("corrupt_quarantined") == 0
        and audit.get("leftover_leases") == 0
        and audit.get("terminal_records") == audit.get("expected_records")
    ):
        failures.append(f"fleet: journal audit failed: {audit}")
    if not fleet.get("zero_double_executions"):
        failures.append(
            f"fleet: double execution detected: survivor completed "
            f"counts {fleet.get('survivor_completed_counts')}"
        )
    if not fleet.get("drained_cleanly"):
        failures.append(
            f"fleet: SIGTERM drain exit codes "
            f"{fleet.get('drain_exit_codes')} (expected all 0)"
        )
    # the scraped fleet view (ISSUE 19): fleet_top's /metrics sweep,
    # taken live after the takeover — the dead victim a DOWN row, the
    # survivors' own exposition counters agreeing with the journal
    # about completions and the takeover
    metrics = fleet.get("metrics") or {}
    m_fleet = metrics.get("fleet") or {}
    down = [
        r for r in metrics.get("replicas") or [] if "error" in r
    ]
    if m_fleet.get("replicas_up") != fleet.get("replicas", 0) - 1 or (
        len(down) != 1
    ):
        failures.append(
            f"fleet: /metrics scrape did not see exactly the "
            f"survivors up and the victim DOWN: {m_fleet} "
            f"(down rows: {down})"
        )
    if m_fleet.get("plans_completed") != audit.get("expected_records"):
        failures.append(
            f"fleet: scraped completion counters disagree with the "
            f"journal: {m_fleet.get('plans_completed')} vs "
            f"{audit.get('expected_records')}"
        )
    if not m_fleet.get("takeovers", 0) >= 1:
        failures.append(
            f"fleet: the takeover never reached the survivors' "
            f"/metrics exposition: {m_fleet}"
        )


def _check_placement(line: dict, failures: list) -> None:
    """The device-aware placement gate (ISSUE 20): the same 3-replica
    fleet workload — one whole-pool gang plan plus 4 single-device
    plans over a forced-8-virtual-device host — run with the shared
    device pool on and off. The placed fleet must complete every plan
    byte-identically to fresh-process twins, at a makespan no worse
    than the placement-disabled twin, with the gang granted all 8
    leased ordinals, no ordinal ever held twice, never more held than
    the pool, zero device leases left after the drain, and both
    phases draining to exit 0 on a real SIGTERM."""
    block = line.get("placement") or {}
    if not block:
        failures.append("placement: no placement block on the line")
        return
    for tag in ("placed", "disabled"):
        phase = block.get(tag) or {}
        if not phase.get("all_completed"):
            failures.append(
                f"placement: {tag} phase left plans unfinished: "
                f"{phase.get('states')}"
            )
        if not phase.get("drained_cleanly"):
            failures.append(
                f"placement: {tag} phase drain exit codes "
                f"{phase.get('drain_exit_codes')} (expected all 0)"
            )
    if not block.get("sha_parity"):
        failures.append(
            "placement: statistics drifted from the fresh-process "
            f"twins: placed {block.get('placed', {}).get('sha_identical')} "
            f"disabled {block.get('disabled', {}).get('sha_identical')}"
        )
    if not block.get("placement_no_slower"):
        failures.append(
            f"placement: placed makespan slower than the disabled "
            f"twin (ratio {block.get('makespan_ratio')}): "
            f"{(block.get('placed') or {}).get('makespan_s')}s vs "
            f"{(block.get('disabled') or {}).get('makespan_s')}s"
        )
    if not block.get("zero_double_held"):
        failures.append(
            f"placement: device-lease audit failed: "
            f"{(block.get('placed') or {}).get('device_audit')}"
        )
    if not block.get("gang_fully_leased"):
        failures.append(
            f"placement: the gang never held its full footprint: "
            f"leased {(block.get('placed') or {}).get('device_audit', {}).get('gang_leased_ordinals')}"
        )


def _check_report(tag: str, bench_line: dict, report_dir: str,
                  failures: list, checked: list) -> dict:
    """The run-report half of the gate: the artifact exists, parses,
    matches the schema, recorded nonzero stage spans, and agrees with
    the bench line's cache attribution. Returns the parsed report (or
    {}) so cross-run gates (the fan-out compile counter) can read it."""
    checked.append(tag)
    path = os.path.join(report_dir, "run_report.json")
    if not os.path.exists(path):
        failures.append(f"{tag}: no run_report.json in {report_dir}")
        return {}
    try:
        with open(path) as f:
            report = json.load(f)
    except ValueError as e:
        failures.append(f"{tag}: run_report.json unparseable: {e}")
        return {}
    if report.get("schema") != "eeg-tpu-run-report/v1":
        failures.append(
            f"{tag}: bad report schema {report.get('schema')!r}"
        )
        return {}
    stages = report.get("stages", {})
    for stage in _REQUIRED_STAGES:
        if stages.get(stage, {}).get("seconds", 0.0) <= 0.0:
            failures.append(
                f"{tag}: stage {stage!r} has no recorded time: "
                f"{stages.get(stage)}"
            )
    by_name = (report.get("spans") or {}).get("by_name", {})
    for stage in _REQUIRED_STAGES:
        if by_name.get(f"stage.{stage}", {}).get("count", 0) < 1:
            failures.append(
                f"{tag}: span stage.{stage} missing from the report's "
                f"span summary: {sorted(by_name)}"
            )
    report_fc = (report.get("caches") or {}).get("feature_cache")
    if report_fc != bench_line["feature_cache"]:
        failures.append(
            f"{tag}: report cache attribution {report_fc} != bench "
            f"line {bench_line['feature_cache']}"
        )
    # both come from the same StageTimer, so the report's stage totals
    # must match the bench line's breakdown exactly (modulo rounding)
    for stage, entry in bench_line.get("stages", {}).items():
        got = round(stages.get(stage, {}).get("seconds", -1.0), 6)
        if abs(got - entry["seconds"]) > 1e-6:
            failures.append(
                f"{tag}: stage {stage!r} drifted between report "
                f"({got}) and bench line ({entry['seconds']})"
            )
    if report.get("outcome") != "ok":
        failures.append(f"{tag}: outcome {report.get('outcome')!r}")
    return report


def run(n_markers: int = 2000, n_files: int = 4) -> dict:
    failures = []
    reports_checked = []
    with tempfile.TemporaryDirectory(prefix="eeg_tpu_smoke_") as tmp:
        data_dir = os.path.join(tmp, "data")
        report_dirs = {
            v: os.path.join(tmp, f"report_{v}")
            for v in ("cold", "warm", "fanout", "pop_vmap", "pop_looped",
                      "pop_sharded", "pop_sharded1")
        }
        cold = _run_variant(
            "pipeline_e2e_cold", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_cold"),
            report_dirs["cold"],
        )
        warm = _run_variant(
            "pipeline_e2e_warm", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_warm"),
            report_dirs["warm"],
        )
        fanout = _run_variant(
            "pipeline_e2e_fanout5", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_fanout"),
            report_dirs["fanout"],
        )
        # the observability-plane twin (ISSUE 19): the same cold query
        # with telemetry fully OFF (no report dir, env override
        # cleared) — the plane observes, never steers, so its
        # statistics must be byte-identical to the instrumented cold
        # run's, and instrumenting must cost no more than the
        # shared-box noise floor
        obs_off = _run_variant(
            "pipeline_e2e_cold", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_obs_off"), None,
            env_extra={"EEG_TPU_RUN_REPORT_DIR": ""},
        )
        # PR 8 gates: the overlap twin (bit-identical statistics), the
        # bf16 twin (gate decision recorded, statistics within the
        # documented envelope), and a forced-gate-off bf16 run (pinned
        # statistics-identical to the f32 cold run)
        overlap_line = _run_variant(
            "pipeline_e2e_overlap", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_overlap"),
            os.path.join(tmp, "report_overlap"),
        )
        bf16_line = _run_variant(
            "pipeline_e2e_bf16", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_bf16"),
            os.path.join(tmp, "report_bf16"),
        )
        bf16_off_line = _run_variant(
            "pipeline_e2e_bf16", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_bf16_off"),
            os.path.join(tmp, "report_bf16_off"),
            # an impossible tolerance forces the auto-disable path:
            # the gated-off run must compute (and report) f32
            env_extra={"EEG_TPU_BF16_GATE_TOL": "0"},
        )
        # the int8 precision rung (PR 12): gate decision recorded, and
        # the forced-gate-off twin pinned byte-identical to f32
        int8_line = _run_variant(
            "pipeline_e2e_int8", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_int8"),
            os.path.join(tmp, "report_int8"),
        )
        int8_off_line = _run_variant(
            "pipeline_e2e_int8", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_int8_off"),
            os.path.join(tmp, "report_int8_off"),
            env_extra={"EEG_TPU_INT8_GATE_TOL": "0"},
        )
        # the int4 rung (ISSUE 18): same contract, bottom of the
        # ladder — gate decision recorded, and the forced-gate-off
        # twin pinned byte-identical to f32
        int4_line = _run_variant(
            "pipeline_e2e_int4", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_int4"),
            os.path.join(tmp, "report_int4"),
        )
        int4_off_line = _run_variant(
            "pipeline_e2e_int4", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_int4_off"),
            os.path.join(tmp, "report_int4_off"),
            env_extra={"EEG_TPU_INT4_GATE_TOL": "0"},
        )
        # the other four legs as their OWN single-classifier cold
        # runs (fresh process, fresh cache): their reports' compile
        # counters are the honest "5x single" side of the fan-out
        # compile-sharing gate — legs are heterogeneous, so 5x the
        # logreg count would understate what five full runs cost
        single_compiles = {}
        single_walls = {}
        for leg in ("svm", "dt", "rf", "nn"):
            leg_report_dir = os.path.join(tmp, f"report_single_{leg}")
            leg_line = _run_variant(
                "pipeline_e2e_cold", n_markers, n_files,
                data_dir, os.path.join(tmp, f"cache_single_{leg}"),
                leg_report_dir, extra=[f"--train-clf={leg}"],
            )
            single_walls[leg] = leg_line["wall_s"]
            try:
                with open(
                    os.path.join(leg_report_dir, "run_report.json")
                ) as f:
                    single_compiles[leg] = (
                        json.load(f).get("xla") or {}
                    ).get("compilations", 0)
            except (OSError, ValueError):
                single_compiles[leg] = 0
        pop_vmap = _run_variant(
            "population_vmap", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_pop"),
            report_dirs["pop_vmap"],
        )
        pop_looped = _run_variant(
            "population_looped", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_pop"),
            report_dirs["pop_looped"],
        )
        # the mesh gate: the same member set over a forced-8-device
        # CPU mesh, and the devices=1 degenerate mesh
        pop_sharded = _run_variant(
            "population_sharded", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_pop"),
            report_dirs["pop_sharded"],
        )
        pop_sharded1 = _run_variant(
            "population_sharded", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_pop"),
            report_dirs["pop_sharded1"], extra=["--devices=1"],
        )
        # the pod gate (ISSUE 14): 2-process loopback pod vs its
        # single-process twin + the degraded-coordinator run, all
        # spawned inside the child (report_dir=None — the workers are
        # their own processes)
        multiproc_line = _run_variant(
            "population_multiproc", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_multiproc"), None,
        )
        _check_multiproc(multiproc_line, failures)
        serve_report_dir = os.path.join(tmp, "report_serve")
        serve_line = _run_serve_bench(
            min(n_markers, 400), n_files, serve_report_dir
        )
        _check_serve(serve_line, serve_report_dir, failures)
        # the serve megakernel (PR 12 tentpole): mega vs fused
        # back-to-back in one child process, parity + rung + int8-gate
        # attribution all on one line
        serve_mega_line = _run_serve_bench(
            min(n_markers, 400), n_files, variant="serve_mega"
        )
        _check_serve_mega(serve_mega_line, failures)
        # the model lifecycle manager (ISSUE 15 tentpole): no-swap
        # byte-identity, swap-under-load, promoted==batch parity,
        # serve.swap/serve.adapt chaos soak, and the lifecycle block
        # in the adapt run's report — all on one line
        lifecycle_report_dir = os.path.join(tmp, "report_lifecycle")
        lifecycle_line = _run_serve_bench(
            min(n_markers, 400), n_files, lifecycle_report_dir,
            variant="serve_lifecycle",
        )
        _check_lifecycle(lifecycle_line, lifecycle_report_dir, failures)
        # the multiplexed multi-tenant engine (ISSUE 16 tentpole):
        # per-tenant parity, the 0-compile scaling + hot-swap pins,
        # and multiplexed >= solo-fleet at 16 tenants — all on one
        # line
        multitenant_line = _run_serve_bench(
            min(n_markers, 400), n_files, variant="serve_multitenant"
        )
        _check_multitenant(multitenant_line, failures)
        # the quantized tenant weight stack (ISSUE 18 tentpole): the
        # int4 run plus its forced-gate-off drill, gated together
        multitenant_quant_line = _run_serve_bench(
            min(n_markers, 400), n_files,
            variant="serve_multitenant_quant",
        )
        multitenant_quant_off_line = _run_serve_bench(
            min(n_markers, 400), n_files,
            variant="serve_multitenant_quant",
            env_extra={"EEG_TPU_WEIGHTS_GATE_TOL": "0"},
        )
        _check_multitenant_quant(
            multitenant_quant_line, multitenant_quant_off_line,
            failures,
        )
        # the seizure workload: one cost-swept population run over a
        # continuous annotated session (its own data dir — the
        # manifest points at continuous recordings); the swept member
        # set contains BOTH the cost-sensitive model and its
        # unweighted twin, trained in one vmapped program
        seizure_data = os.path.join(tmp, "seizure_data")
        seizure_report_dir = os.path.join(tmp, "report_seizure")
        seizure_line = _run_variant(
            "seizure_e2e", 40000, 2, seizure_data,
            os.path.join(tmp, "cache_seizure"), seizure_report_dir,
        )
        _check_seizure(seizure_line, seizure_report_dir, failures)
        # the multi-tenant executor (ISSUE 10): concurrent >=
        # sequential, per-plan report integrity, the single-flight
        # store pin, and the SIGKILL kill-and-resume scenario — all
        # measured inside the scheduler_multi child over its own
        # per-phase caches and per-plan report tree
        scheduler_line = _run_variant(
            "scheduler_multi", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_scheduler"), None,
        )
        _check_scheduler(scheduler_line, failures)
        # the networked plan service (ISSUE 11): the HTTP dedup pair,
        # the idempotent-resubmit replay, and the many-client chaos
        # soak — all measured inside the plan_service child over its
        # own per-phase caches (report_dir=None: the child's gateway
        # owns a per-plan report tree)
        plan_service_line = _run_variant(
            "plan_service", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_plan_service"), None,
        )
        _check_plan_service(plan_service_line, failures)
        # the replicated fleet (ISSUE 17): 3 real replica processes
        # over one shared journal, SIGKILL the in-flight holder, a
        # survivor completes the plan byte-identically exactly once,
        # survivors drain to exit 0 on real SIGTERM. Own small
        # session (not the ladder's): the heavy plan's kill window
        # is sized in iterations whose unit cost scales with the
        # session — failover pins don't sharpen with data size
        fleet_line = _run_variant(
            "gateway_fleet", 400, 2,
            os.path.join(tmp, "data_fleet"),
            os.path.join(tmp, "cache_fleet"), None,
        )
        _check_fleet(fleet_line, failures)
        # device-aware placement (ISSUE 20): the same fleet run with
        # the shared device pool on vs off — makespan no worse, shas
        # byte-identical, the gang fully leased, zero double-held
        # ordinals, zero leftover device leases. Same small-session
        # reasoning as gateway_fleet: the pins are scheduling pins
        placement_line = _run_variant(
            "fleet_placement", 400, 2,
            os.path.join(tmp, "data_placement"),
            os.path.join(tmp, "cache_placement"), None,
        )
        _check_placement(placement_line, failures)
        cold_report = _check_report(
            "cold", cold, report_dirs["cold"], failures, reports_checked
        )
        _check_report(
            "warm", warm, report_dirs["warm"], failures, reports_checked
        )
        fanout_report = _check_report(
            "fanout", fanout, report_dirs["fanout"], failures,
            reports_checked,
        )
        _check_report(
            "pop_vmap", pop_vmap, report_dirs["pop_vmap"], failures,
            reports_checked,
        )
        _check_report(
            "pop_looped", pop_looped, report_dirs["pop_looped"],
            failures, reports_checked,
        )
        _check_report(
            "pop_sharded", pop_sharded, report_dirs["pop_sharded"],
            failures, reports_checked,
        )
        _check_mesh(
            pop_sharded, pop_sharded1, pop_vmap,
            report_dirs["pop_sharded"], report_dirs["pop_vmap"],
            failures,
        )
        # the checked set IS the registry: a report gate added (or
        # dropped) without updating REPORT_CHECKS fails here, and the
        # suite's reports_checked pin derives from the same tuple
        if tuple(reports_checked) != REPORT_CHECKS:
            failures.append(
                f"report checks drifted from the REPORT_CHECKS "
                f"registry: ran {tuple(reports_checked)}, registered "
                f"{REPORT_CHECKS}"
            )

    if not warm["wall_s"] < cold["wall_s"]:
        failures.append(
            f"warm run not faster than cold: {warm['wall_s']}s vs "
            f"{cold['wall_s']}s"
        )
    if not warm["feature_cache"]["hits"] > 0:
        failures.append(
            f"warm run never hit the cache: {warm['feature_cache']}"
        )
    if not (
        cold["feature_cache"]["misses"] > 0
        and cold["feature_cache"]["hits"] == 0
    ):
        failures.append(
            f"cold run was not cold: {cold['feature_cache']}"
        )
    if cold["report_sha256"] != warm["report_sha256"]:
        failures.append(
            "cached vs uncached statistics drifted: "
            f"{cold['report_sha256']} vs {warm['report_sha256']}"
        )
    # the observability plane observes, never steers (ISSUE 19): the
    # telemetry-off twin is byte-identical to the instrumented cold
    # run, and instrumentation stays inside the noise floor (1.5x —
    # the pair runs minutes apart on a shared box)
    if obs_off["report_sha256"] != cold["report_sha256"]:
        failures.append(
            "obs: instrumented statistics drifted from the "
            f"telemetry-off twin: {cold['report_sha256']} vs "
            f"{obs_off['report_sha256']}"
        )
    if not cold["wall_s"] <= 1.5 * obs_off["wall_s"]:
        failures.append(
            f"obs: telemetry overhead left the noise floor: "
            f"{cold['wall_s']}s instrumented vs {obs_off['wall_s']}s off"
        )
    # overlap-on vs overlap-off: scheduling only, never results
    if overlap_line.get("overlap") is not True:
        failures.append(
            f"overlap line did not run overlapped: "
            f"{overlap_line.get('overlap')}"
        )
    if overlap_line["report_sha256"] != cold["report_sha256"]:
        failures.append(
            "overlap-on statistics drifted from the serial cold run: "
            f"{overlap_line['report_sha256']} vs "
            f"{cold['report_sha256']}"
        )
    # the bf16 twin: a decision must be recorded, and when the gate
    # passed (used=bf16) its measured deviation must sit inside the
    # documented tolerance; statistics stay within the decision
    # envelope (integer confusion counts — in practice identical)
    prec = bf16_line.get("precision") or {}
    gate = prec.get("gate") or {}
    if prec.get("requested") != "bf16" or "used" not in prec:
        failures.append(f"bf16 line recorded no gate decision: {prec}")
    elif prec["used"] == "bf16":
        if not (gate.get("ok") and
                gate.get("max_abs_dev", 1.0) <= gate.get("tolerance", 0.0)):
            failures.append(
                f"bf16 ran outside its gate: {gate}"
            )
        if abs(bf16_line["accuracy"] - cold["accuracy"]) > 0.02:
            failures.append(
                f"bf16 statistics outside the envelope: accuracy "
                f"{bf16_line['accuracy']} vs f32 {cold['accuracy']}"
            )
    # the gate's double-featurize cost is attributed, not hidden: the
    # bf16 line's gate record must carry gate_seconds (satellite of
    # the bf16-slower-than-f32 investigation)
    if prec.get("used") == "bf16" and "gate_seconds" not in gate:
        failures.append(
            f"bf16 gate record carries no gate_seconds: {gate}"
        )
    # the forced-gate-off run: auto-disable recorded AND the run's
    # statistics byte-identical to the f32 cold run
    prec_off = bf16_off_line.get("precision") or {}
    if prec_off.get("used") != "f32":
        failures.append(
            f"forced bf16 gate-off did not auto-disable: {prec_off}"
        )
    if bf16_off_line["report_sha256"] != cold["report_sha256"]:
        failures.append(
            "gated-off bf16 run drifted from the f32 cold run: "
            f"{bf16_off_line['report_sha256']} vs "
            f"{cold['report_sha256']}"
        )
    # the int8 rung: a decision recorded, inside the documented
    # tolerance when it ran, and the forced-gate-off twin byte-
    # identical to the f32 cold run
    prec_i8 = int8_line.get("precision") or {}
    gate_i8 = prec_i8.get("gate") or {}
    if prec_i8.get("requested") != "int8" or "used" not in prec_i8:
        failures.append(
            f"int8 line recorded no gate decision: {prec_i8}"
        )
    elif prec_i8["used"] == "int8" and not (
        gate_i8.get("ok")
        and gate_i8.get("max_abs_dev", 1.0)
        <= gate_i8.get("tolerance", 0.0)
    ):
        failures.append(f"int8 ran outside its gate: {gate_i8}")
    prec_i8_off = int8_off_line.get("precision") or {}
    if prec_i8_off.get("used") != "f32":
        failures.append(
            f"forced int8 gate-off did not auto-disable: {prec_i8_off}"
        )
    if int8_off_line["report_sha256"] != cold["report_sha256"]:
        failures.append(
            "gated-off int8 run drifted from the f32 cold run: "
            f"{int8_off_line['report_sha256']} vs "
            f"{cold['report_sha256']}"
        )
    # the int4 rung: the same contract at the bottom of the ladder
    prec_i4 = int4_line.get("precision") or {}
    gate_i4 = prec_i4.get("gate") or {}
    if prec_i4.get("requested") != "int4" or "used" not in prec_i4:
        failures.append(
            f"int4 line recorded no gate decision: {prec_i4}"
        )
    elif prec_i4["used"] == "int4" and not (
        gate_i4.get("ok")
        and gate_i4.get("max_abs_dev", 1.0)
        <= gate_i4.get("tolerance", 0.0)
    ):
        failures.append(f"int4 ran outside its gate: {gate_i4}")
    prec_i4_off = int4_off_line.get("precision") or {}
    if prec_i4_off.get("used") != "f32":
        failures.append(
            f"forced int4 gate-off did not auto-disable: {prec_i4_off}"
        )
    if int4_off_line["report_sha256"] != cold["report_sha256"]:
        failures.append(
            "gated-off int4 run drifted from the f32 cold run: "
            f"{int4_off_line['report_sha256']} vs "
            f"{cold['report_sha256']}"
        )
    plateau_summary = _check_plateau(cold, failures)
    if fanout["accuracy"].get("logreg") != cold["accuracy"]:
        failures.append(
            "fan-out logreg accuracy drifted from the single-"
            f"classifier run: {fanout['accuracy'].get('logreg')} vs "
            f"{cold['accuracy']}"
        )
    if len(fanout.get("accuracy", {})) != 5:
        failures.append(
            f"fan-out did not report 5 classifiers: {fanout.get('accuracy')}"
        )
    # fan-out amortization, measured against the real alternative:
    # the five classifiers run as five single-classifier pipelines
    # (each its own fresh cold process, like the fan-out's)
    single_walls["logreg"] = cold["wall_s"]
    singles_wall_sum = round(sum(single_walls.values()), 3)
    if not fanout["wall_s"] < singles_wall_sum:
        failures.append(
            f"fan-out not amortized: {fanout['wall_s']}s vs its five "
            f"singles combined {singles_wall_sum}s ({single_walls})"
        )

    # compile sharing (ISSUE-5 satellite): the fan-out run — five
    # classifiers against ONE staged feature buffer and one ingest
    # pass — must compile fewer XLA programs than running its five
    # classifiers as five single-classifier pipelines
    single_compiles["logreg"] = (
        cold_report.get("xla") or {}
    ).get("compilations", 0)
    c_singles_sum = sum(single_compiles.values())
    c_fanout = (fanout_report.get("xla") or {}).get("compilations", 0)
    if all(single_compiles.values()) and c_fanout:
        if not c_fanout < c_singles_sum:
            failures.append(
                f"fan-out compiled {c_fanout} programs, not fewer than "
                f"its five singles combined ({c_singles_sum}: "
                f"{single_compiles})"
            )
    else:
        failures.append(
            f"compile counters missing from reports: "
            f"singles={single_compiles} fanout={c_fanout}"
        )

    # population engine gates: the vmapped 16-member program must beat
    # the looped twin's train stage, with byte-identical statistics
    pv_train = pop_vmap.get("stages", {}).get("train", {}).get(
        "seconds", 0.0
    )
    pl_train = pop_looped.get("stages", {}).get("train", {}).get(
        "seconds", 0.0
    )
    if not (pv_train > 0.0 and pv_train < pl_train):
        failures.append(
            f"vmapped population train stage not faster than looped: "
            f"{pv_train}s vs {pl_train}s"
        )
    if pop_vmap["report_sha256"] != pop_looped["report_sha256"]:
        failures.append(
            "vmapped vs looped population statistics drifted: "
            f"{pop_vmap['report_sha256']} vs {pop_looped['report_sha256']}"
        )
    for tag, line in (("vmap", pop_vmap), ("looped", pop_looped)):
        members = (line.get("population") or {}).get("members")
        if members != 16:
            failures.append(
                f"population_{tag} trained {members} members, not 16"
            )

    multiproc_block = multiproc_line.get("multiproc") or {}
    return {
        "ok": not failures,
        "failures": failures,
        "cold_wall_s": cold["wall_s"],
        "warm_wall_s": warm["wall_s"],
        "fanout5_wall_s": fanout["wall_s"],
        "warm_speedup": round(cold["wall_s"] / warm["wall_s"], 2),
        "fanout_vs_cold": round(fanout["wall_s"] / cold["wall_s"], 2),
        "singles_wall_sum_s": singles_wall_sum,
        "fanout_vs_singles": round(
            fanout["wall_s"] / singles_wall_sum, 2
        ),
        "warm_feature_cache": warm["feature_cache"],
        "cold_feature_cache": cold["feature_cache"],
        "population_vmap_train_s": pv_train,
        "population_looped_train_s": pl_train,
        "population_train_speedup": (
            round(pl_train / pv_train, 2) if pv_train > 0 else None
        ),
        "mesh_devices1_identical": (
            pop_sharded1["report_sha256"] == pop_vmap["report_sha256"]
        ),
        "mesh_sharded_identical": (
            pop_sharded["report_sha256"] == pop_vmap["report_sha256"]
        ),
        "mesh_rung": (pop_sharded.get("mesh") or {}).get("rung"),
        "mesh_members_per_device": (
            (pop_sharded.get("mesh") or {}).get("population") or {}
        ).get("members_per_device"),
        "population_sharded_members_per_s": pop_sharded.get(
            "members_per_s"
        ),
        "population_vmap_members_per_s": pop_vmap.get("members_per_s"),
        "compilations_singles": single_compiles,
        "compilations_singles_sum": c_singles_sum,
        "compilations_fanout5": c_fanout,
        "serve_preds_per_s": (serve_line.get("serve") or {}).get(
            "sweep", [{}]
        )[-1].get("preds_per_s"),
        "serve_shed_counted": (serve_line.get("serve") or {}).get(
            "shed_probe", {}
        ).get("counted_shed"),
        "serve_chaos_clean": (serve_line.get("serve") or {}).get(
            "chaos", {}
        ).get("chaos_clean"),
        "seizure_class_ratio": (seizure_line.get("seizure") or {}).get(
            "class_ratio"
        ),
        "seizure_weighted_cost": (
            (seizure_line.get("seizure") or {}).get("weighted") or {}
        ).get("expected_cost"),
        "seizure_unweighted_cost": (
            (seizure_line.get("seizure") or {}).get("unweighted") or {}
        ).get("expected_cost"),
        "seizure_weighted_recall": (
            (seizure_line.get("seizure") or {}).get("weighted") or {}
        ).get("recall"),
        "seizure_windows_per_s": (seizure_line.get("seizure") or {}).get(
            "windows_per_s"
        ),
        "overlap_wall_s": overlap_line["wall_s"],
        "overlap_statistics_identical": (
            overlap_line["report_sha256"] == cold["report_sha256"]
        ),
        "bf16_precision": bf16_line.get("precision"),
        "bf16_gate_off_identical_to_f32": (
            bf16_off_line["report_sha256"] == cold["report_sha256"]
        ),
        "int8_precision": int8_line.get("precision"),
        "int8_gate_off_identical_to_f32": (
            int8_off_line["report_sha256"] == cold["report_sha256"]
        ),
        "int4_precision": int4_line.get("precision"),
        "int4_gate_off_identical_to_f32": (
            int4_off_line["report_sha256"] == cold["report_sha256"]
        ),
        "serve_lifecycle": {
            "no_swap_parity": (
                (lifecycle_line.get("serve") or {})
                .get("no_swap_parity")
            ),
            "promoted_parity": (
                (lifecycle_line.get("serve") or {})
                .get("promoted_parity")
            ),
            "swaps": (
                (lifecycle_line.get("serve") or {})
                .get("lifecycle") or {}
            ).get("swaps"),
            "rollbacks": (
                (lifecycle_line.get("serve") or {})
                .get("lifecycle") or {}
            ).get("rollbacks"),
            "drift_events": (
                (lifecycle_line.get("serve") or {})
                .get("lifecycle") or {}
            ).get("drift_events"),
            "chaos": (lifecycle_line.get("serve") or {}).get("chaos"),
        },
        "serve_multitenant": {
            "parity": (
                (multitenant_line.get("serve") or {})
                .get("multitenant") or {}
            ).get("parity"),
            "compiles": (
                (multitenant_line.get("serve") or {})
                .get("multitenant") or {}
            ).get("compiles"),
            "swap": (
                (multitenant_line.get("serve") or {})
                .get("multitenant") or {}
            ).get("swap"),
            "levels": (
                (multitenant_line.get("serve") or {})
                .get("multitenant") or {}
            ).get("levels"),
        },
        "serve_multitenant_quant": {
            "weights": (
                (multitenant_quant_line.get("serve") or {})
                .get("multitenant_quant") or {}
            ).get("weights"),
            "parity": (
                (multitenant_quant_line.get("serve") or {})
                .get("multitenant_quant") or {}
            ).get("parity"),
            "ratio": (
                (multitenant_quant_line.get("serve") or {})
                .get("multitenant_quant") or {}
            ).get("ratio"),
            "resident": (
                (multitenant_quant_line.get("serve") or {})
                .get("multitenant_quant") or {}
            ).get("resident"),
            "admin": (
                (multitenant_quant_line.get("serve") or {})
                .get("multitenant_quant") or {}
            ).get("admin"),
            "gate_off_used": (
                ((multitenant_quant_off_line.get("serve") or {})
                 .get("multitenant_quant") or {}).get("weights") or {}
            ).get("used"),
        },
        "serve_mega": {
            "mega_rung": (
                (serve_mega_line.get("serve") or {})
                .get("mega_vs_fused") or {}
            ).get("mega_rung"),
            "parity": (
                (serve_mega_line.get("serve") or {})
                .get("mega_vs_fused") or {}
            ).get("parity"),
            "sweep": (
                (serve_mega_line.get("serve") or {})
                .get("mega_vs_fused") or {}
            ).get("sweep"),
            "int8_gate": (serve_mega_line.get("serve") or {}).get(
                "int8_gate"
            ),
        },
        "plateau": plateau_summary,
        "multiproc_parity_ok": multiproc_block.get("parity_sha_ok"),
        "multiproc_members_per_s": multiproc_block.get("members_per_s"),
        "multiproc_twin_members_per_s": multiproc_block.get(
            "twin_members_per_s"
        ),
        "multiproc_degraded_rung": (
            multiproc_block.get("degraded_coordinator") or {}
        ).get("rung"),
        "scheduler_concurrent_speedup": (
            scheduler_line.get("scheduler") or {}
        ).get("concurrent_speedup"),
        "scheduler_parity": (
            scheduler_line.get("scheduler") or {}
        ).get("parity_sequential_vs_concurrent"),
        "scheduler_crash_recovery": (
            scheduler_line.get("scheduler") or {}
        ).get("crash_recovery"),
        "plan_service_dedup_hit_ratio": (
            ((plan_service_line.get("plan_service") or {}).get("pair")
             or {}).get("dedup") or {}
        ).get("hit_ratio"),
        "plan_service_submits_per_s": (
            (plan_service_line.get("plan_service") or {}).get("soak")
            or {}
        ).get("submits_per_s"),
        "plan_service_soak_clean": bool(
            ((plan_service_line.get("plan_service") or {}).get("soak")
             or {}).get("all_resolved")
            and ((plan_service_line.get("plan_service") or {}).get(
                "soak") or {}).get("statistics_identical")
        ),
        "fleet_takeover_sha_ok": bool(
            ((fleet_line.get("fleet") or {}).get("takeover") or {})
            .get("sha_identical_to_twin")
        ),
        "fleet_takeover_wall_s": (
            (fleet_line.get("fleet") or {}).get("takeover") or {}
        ).get("wall_s"),
        "fleet_zero_double_executions": bool(
            (fleet_line.get("fleet") or {}).get("zero_double_executions")
        ),
        "fleet_drained_cleanly": bool(
            (fleet_line.get("fleet") or {}).get("drained_cleanly")
        ),
        "fleet_metrics_scrape": (
            ((fleet_line.get("fleet") or {}).get("metrics") or {})
            .get("fleet")
        ),
        "obs_overhead": {
            "obs_on_wall_s": cold["wall_s"],
            "obs_off_wall_s": obs_off["wall_s"],
            "ratio": (
                round(cold["wall_s"] / obs_off["wall_s"], 2)
                if obs_off["wall_s"] > 0 else None
            ),
            "statistics_identical": (
                obs_off["report_sha256"] == cold["report_sha256"]
            ),
        },
        "reports_checked": len(reports_checked),
        "cold_stages": {
            k: v["seconds"] for k, v in cold.get("stages", {}).items()
        },
        "warm_stages": {
            k: v["seconds"] for k, v in warm.get("stages", {}).items()
        },
    }


def main(argv) -> int:
    sys.path.insert(0, _REPO)
    from eeg_dataanalysispackage_tpu.utils import strict_json

    n_markers = int(argv[0]) if argv else 2000
    n_files = int(argv[1]) if len(argv) > 1 else 4
    summary = run(n_markers, n_files)
    print(strict_json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
