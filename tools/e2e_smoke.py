"""End-to-end pipeline smoke gate: cold -> warm -> fan-out.

Runs the pipeline_e2e trio (tools/pipeline_bench.py children, one
fresh process each — the same process discipline bench.py uses) over
one shared hermetic synthetic session and FAILS unless the
performance contract holds:

- the warm-cache run is faster than the cold run (the feature cache
  must actually buy something);
- the warm run hits the cache (hits > 0, and the cold run stored the
  entries it missed);
- cold and warm produce byte-identical ClassificationStatistics
  (``report_sha256`` equality — a cache that changes results is a
  correctness bug, not a speedup);
- the 5-classifier fan-out's logreg statistics match the
  single-classifier run's exactly (shared features must not perturb
  any individual classifier);
- fan-out wall time stays under 3x the single-classifier cold run
  (ingest+featurization amortized across the five classifiers).

Usage: python tools/e2e_smoke.py [n_markers_per_file] [n_files]

Prints a JSON summary line; exit 0 iff every gate passed. Wired into
the suite as a ``slow``-marked pytest (tests/test_e2e_smoke.py), so
tier-1 stays fast while CI can still run the whole ladder.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PIPELINE_BENCH = os.path.join(_REPO, "tools", "pipeline_bench.py")


def _run_variant(variant: str, n_markers: int, n_files: int,
                 data_dir: str, cache_dir: str) -> dict:
    proc = subprocess.run(
        [
            sys.executable, _PIPELINE_BENCH, variant,
            str(n_markers), str(n_files),
            f"--data-dir={data_dir}", f"--cache-dir={cache_dir}",
        ],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{variant} child failed rc={proc.returncode}\n"
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run(n_markers: int = 2000, n_files: int = 4) -> dict:
    failures = []
    with tempfile.TemporaryDirectory(prefix="eeg_tpu_smoke_") as tmp:
        data_dir = os.path.join(tmp, "data")
        cold = _run_variant(
            "pipeline_e2e_cold", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_cold"),
        )
        warm = _run_variant(
            "pipeline_e2e_warm", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_warm"),
        )
        fanout = _run_variant(
            "pipeline_e2e_fanout5", n_markers, n_files,
            data_dir, os.path.join(tmp, "cache_fanout"),
        )

    if not warm["wall_s"] < cold["wall_s"]:
        failures.append(
            f"warm run not faster than cold: {warm['wall_s']}s vs "
            f"{cold['wall_s']}s"
        )
    if not warm["feature_cache"]["hits"] > 0:
        failures.append(
            f"warm run never hit the cache: {warm['feature_cache']}"
        )
    if not (
        cold["feature_cache"]["misses"] > 0
        and cold["feature_cache"]["hits"] == 0
    ):
        failures.append(
            f"cold run was not cold: {cold['feature_cache']}"
        )
    if cold["report_sha256"] != warm["report_sha256"]:
        failures.append(
            "cached vs uncached statistics drifted: "
            f"{cold['report_sha256']} vs {warm['report_sha256']}"
        )
    if fanout["accuracy"].get("logreg") != cold["accuracy"]:
        failures.append(
            "fan-out logreg accuracy drifted from the single-"
            f"classifier run: {fanout['accuracy'].get('logreg')} vs "
            f"{cold['accuracy']}"
        )
    if len(fanout.get("accuracy", {})) != 5:
        failures.append(
            f"fan-out did not report 5 classifiers: {fanout.get('accuracy')}"
        )
    if not fanout["wall_s"] < 3 * cold["wall_s"]:
        failures.append(
            f"fan-out not amortized: {fanout['wall_s']}s vs 3x cold "
            f"{cold['wall_s']}s"
        )

    return {
        "ok": not failures,
        "failures": failures,
        "cold_wall_s": cold["wall_s"],
        "warm_wall_s": warm["wall_s"],
        "fanout5_wall_s": fanout["wall_s"],
        "warm_speedup": round(cold["wall_s"] / warm["wall_s"], 2),
        "fanout_vs_cold": round(fanout["wall_s"] / cold["wall_s"], 2),
        "warm_feature_cache": warm["feature_cache"],
        "cold_feature_cache": cold["feature_cache"],
    }


def main(argv) -> int:
    n_markers = int(argv[0]) if argv else 2000
    n_files = int(argv[1]) if len(argv) > 1 else 4
    summary = run(n_markers, n_files)
    print(json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
