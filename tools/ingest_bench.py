"""Per-variant ingest/feature benchmark (real chip or CPU).

Usage: python tools/ingest_bench.py <variant> [n_epochs] [iters]

Variants:
  einsum          f32 epochs resident in HBM -> dwt-8 features
                  (the round-1 headline path, ops/dwt.py)
  einsum_2d       A/B formulation of the headline: same geometry, but
                  (B, C, T) flattened to (B*C, T) and contracted as
                  one explicit 2-D matmul instead of the bct,tk einsum
  einsum_flat     A/B formulation of the headline: epochs stored
                  channel-flat (B, C*T) and contracted against a
                  block-diagonal (C*T, C*K) operator — no C dimension
                  exists for XLA to lay out or relayout
  einsum_bf16     the headline with bfloat16 epochs resident (half the
                  HBM bytes; ~2e-3 feature deviation, classification
                  unchanged on the fixture — fe=dwt-8-tpu-bf16)
  einsum_sliced   A/B of the headline: rank-preserving static slice
                  to the live [skip, skip+size) columns + the same
                  einsum — reads 51% of the headline's bytes IF XLA
                  fuses the subrange read into the dot
  einsum_512_bf16 the compact layout in bf16 residency (3072
                  B/epoch) — compact x bf16 compound headline candidate
  einsum_512      epochs resident as (B, C, 512) — the compact
                  feature-only layout — at the honest 6144 B/epoch
  einsum_bf16_flat  bf16-resident epochs in the channel-flat (B, C*T)
                  layout against the block-diagonal operator: isolates
                  whether the bf16 twin's roofline shortfall (55.2% vs
                  f32's 68.6%, VERDICT r2) is (B, C, T) tiling at 2-byte
                  elements or inherent to bf16 HBM streams
  xla_ingest      int16 raw + irregular markers -> features via the
                  XLA gather formulation (ops/device_ingest.py)
  block_ingest    int16 raw + irregular markers -> features via the
                  tile-row-gather formulation with windows batched by
                  alignment class (make_classed_block_ingest_featurizer
                  — one matmul per shift class instead of the
                  128-variant bank; host plan cached in ops/plan_cache)
                  — the XLA-only replacement for the element gather
  decode_ingest   int16 raw + irregular markers -> features via the
                  decode rung (ops/decode_ingest.py): windows cut by
                  dynamic slices in a tiled scan (CPU) or the bank128
                  VMEM kernel (accelerators) — NO XLA gather. The
                  line additionally times the element-gather rung on
                  the same data in the same process and records the
                  ratio (``gather_baseline``), so the
                  vs-gather-baseline claim is auditable from the
                  artifact alone
  pallas_ingest   int16 raw + irregular markers -> features via the
                  fused Pallas kernel (ops/ingest_pallas.py)
  pallas_dwt      f32 epochs resident -> features via the Pallas
                  epochs-resident kernel (ops/dwt_pallas.py) — the
                  Mosaic compile-health canary for the Pallas stack
  sharded_ingest  int16 raw + irregular markers -> features with the
                  recording TIME-SHARDED over a device mesh
                  (parallel/sharded_ingest.py): each device cuts +
                  featurizes the windows starting in its block, ring
                  halo for boundary straddlers. Runs on a virtual
                  8-device host mesh when the process is CPU-pinned
                  (the forced-host-platform flag is set before jax
                  initializes), on the real devices otherwise; the
                  line's ``mesh`` block records the mesh size, the
                  compiled program's collective-permute count, the
                  same-machine SINGLE-DEVICE twin's eps (the identical
                  block featurizer, unsharded, same data, back to
                  back) and the sharded/single ratio
  regular_ingest  int16 raw + regular stimulus train -> features, no
                  gather (static window formation); the formulation
                  (reshape | conv | phase, see device_ingest) defaults
                  to auto and can be forced with BENCH_FORMULATION;
                  the JSON line records which one ran
  train_step      f32 epochs -> features -> logreg forward/backward/
                  update (parallel/train.py one-step)
  train_step_512  the train step over compact-resident (B, C, 512)
                  epochs (honest 6144 B/epoch read;
                  parallel/train.make_compact_train_step)
  train_step_raw  int16 raw stream -> fused regular ingest ->
                  features -> logreg fwd/bwd/update: the full
                  training loop at int16 bytes/epoch
                  (parallel/train.make_raw_train_step)
  train_step_block  int16 raw + IRREGULAR markers -> block-gather
                  fused ingest -> features -> logreg fwd/bwd/update
                  (parallel/train.make_irregular_train_step)
  train_step_bank int16 raw + IRREGULAR markers -> bank128 Pallas
                  fused ingest -> features -> logreg fwd/bwd/update
                  (parallel/train.make_irregular_bank_train_step;
                  BENCH_PALLAS_MODE selects the bank twin)
  rf_train        rf-tpu whole-forest growth as one XLA program
                  (models/trees_device.py): 100 trees, depth 5,
                  32 bins over n rows x 48 binned features;
                  epochs_per_s = rows through the full forest growth
  rf_predict      whole-forest device inference
                  (predict_linked_forest): rows/s through 100 trees

Prints one JSON line: {"variant", "epochs_per_s", "bytes_per_epoch",
"pct_of_hbm_roofline", ...}. Run each variant in its own process (the
driver-facing bench.py orchestrates that with timeouts/fallbacks).

Timing: the axon tunnel does not synchronize on block_until_ready, so
the loop runs inside one jitted lax.scan whose per-iteration input is
perturbed (prevents hoisting) and the clock closes on fetching a
scalar that depends on every iteration.
"""

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Persistent compilation cache (primed into env before jax import):
# the chip-side fresh compiles of regular_ingest / train_step_raw run
# 10-14 min (r4 sweep), which is what times bench.py variants out at
# 420 s — a warm cache turns the second process's compile into a
# read. Harmless if the backend can't serialize executables (cache
# misses degrade to a plain compile). The wiring lives in
# utils/compile_cache (shared with the pipeline builder and run.sh);
# BENCH_NO_COMPILE_CACHE opts out, like EEG_TPU_NO_COMPILE_CACHE.
if os.environ.get("BENCH_NO_COMPILE_CACHE"):
    os.environ.setdefault("EEG_TPU_NO_COMPILE_CACHE", "1")
from eeg_dataanalysispackage_tpu.utils import compile_cache as _compile_cache

_compile_cache.prime_env(os.path.join(_REPO, ".jax_compile_cache"))

# v5e HBM bandwidth (GB/s) for roofline context; override for other gens.
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", 819.0))

STRIDE = 750  # irregular-marker mean spacing (samples at 1 kHz)
REGULAR_STRIDE = 800  # fixed-SOA paradigm


def _check_parity(got, want, tol: float, label: str) -> float:
    """max-abs-dev gate shared by the parity-checked variants: a
    miscompiled/miswired fast path must never publish a number."""
    import numpy as np

    dev = float(np.max(np.abs(got - want)))
    if not (dev <= tol):
        raise RuntimeError(
            f"{label} ingest parity failed on device: max abs dev "
            f"{dev} — refusing to publish a throughput number"
        )
    return dev


def _gather_reference_rows(raw_spot, res, spot):
    """Reference feature rows for a parity spot check: the first
    ``len(spot)`` markers through the gather featurizer. Returns
    (want (len(spot), 48), pos_pad, mask) — handles len(spot) < 64.
    """
    import jax.numpy as jnp
    import numpy as np

    from eeg_dataanalysispackage_tpu.ops import device_ingest

    cap = max(64, len(spot))
    pos_pad = np.zeros(cap, np.int32)
    pos_pad[: len(spot)] = spot
    mask = np.zeros(cap, bool)
    mask[: len(spot)] = True
    ref = device_ingest.make_device_ingest_featurizer()
    want = np.asarray(
        ref(
            jnp.asarray(raw_spot), jnp.asarray(res),
            jnp.asarray(pos_pad), jnp.asarray(mask),
        )
    )[: len(spot)]
    return want, pos_pad, mask


def _best_of_eps(fn, n: int, iters: int, reps: int = 2) -> float:
    """Best-of-``reps`` epochs/sec for one already-compiled timed
    pass: warmup call, then the minimum wall time of ``reps`` runs.
    ONE helper shared by every variant that publishes a same-machine
    ratio (decode vs gather, sharded vs single-device) — the
    back-to-back best-of-2 discipline those ratio blocks document is
    load-bearing, so the two sides of a ratio must never drift onto
    different timing rules."""
    fn()  # warmup (everything is compiled by the caller)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return n * iters / best


def run(variant: str, n: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    rng = np.random.RandomState(0)
    res = np.array([0.1, 0.1, 0.2], np.float32)

    if variant in (
        "einsum", "einsum_2d", "einsum_bf16", "einsum_flat",
        "einsum_bf16_flat", "einsum_sliced", "einsum_512",
        "einsum_512_bf16", "pallas_dwt",
    ):
        from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla

        # A/B variants derive geometry from the extractor's own
        # defaults so every twin benchmarks the identical computation
        import inspect

        defaults = {
            k: p.default
            for k, p in inspect.signature(
                dwt_xla.epoch_features
            ).parameters.items()
            if p.default is not inspect.Parameter.empty
        }
        skip = defaults["skip_samples"]
        esize = defaults["epoch_size"]
        fsize = defaults["feature_size"]
        widx = defaults["wavelet_index"]
        T, C = 1000, 3

        if variant == "einsum":
            extract = dwt_xla.make_batched_extractor()
        elif variant in ("einsum_sliced", "einsum_512", "einsum_512_bf16"):
            # einsum_sliced: rank-preserving slice + same einsum over
            # the FULL (B, C, 1000) resident array — the operator's
            # rows outside [skip, skip+size) are zero, so the
            # headline reads 1000 columns to use 512; if XLA fuses
            # the subrange read into the dot (no relayout — unlike
            # the 16x-slower slice-RESHAPE-matmul the docstring of
            # epoch_features measured) this reads 51% of the bytes
            # and shows as >100%-of-roofline at the counted 12000.
            # einsum_512: epochs RESIDENT as (B, C, 512) — the
            # compact layout a feature-only pipeline could store —
            # at the honest 6144 B/epoch.
            k512 = jnp.asarray(
                np.asarray(
                    dwt_xla.cascade_matrix(widx, esize, fsize),
                    np.float32,
                )
            )

            @jax.jit
            def extract(x, kern):
                z = (
                    jax.lax.slice_in_dim(x, skip, skip + esize, axis=2)
                    if x.shape[2] != esize
                    else x
                )
                y = jnp.einsum(
                    # operator follows the stream dtype (the
                    # epoch_features twin-parity rule): bf16 x bf16
                    # for the bf16-resident variant, f32 otherwise
                    "bct,tk->bck", z, kern.astype(z.dtype),
                    precision=jax.lax.Precision.HIGHEST,
                )
                return dwt_xla.safe_l2_normalize(
                    y.reshape(x.shape[0], C * fsize)
                )
        elif variant == "pallas_dwt":
            # epochs-resident Pallas extractor: compiled to Mosaic on
            # chip in round 2 (~9.8M eps at tile_b=128) — serves as
            # the remote-compile health canary for the Pallas stack
            # (its construct profile lacks the ingest kernel's scalar-
            # prefetch index maps / int16 loads / aliased inputs)
            from eeg_dataanalysispackage_tpu.ops import dwt_pallas

            extract = dwt_pallas.make_batched_extractor_pallas()
        elif variant == "einsum_bf16":
            extract = dwt_xla.make_batched_extractor(dtype=jnp.bfloat16)
        elif variant in ("einsum_flat", "einsum_bf16_flat"):
            # channel-flat layout: (B, C*T) against a block-diagonal
            # operator; 3x the MACs (zeros) but zero layout questions
            blk = np.zeros((T, fsize), np.float32)
            blk[skip : skip + esize] = np.asarray(
                dwt_xla.cascade_matrix(widx, esize, fsize), np.float32
            )
            bd = np.zeros((C * T, C * fsize), np.float32)
            for c in range(C):
                bd[c * T : (c + 1) * T, c * fsize : (c + 1) * fsize] = blk
            # the bf16 twin must be bf16 x bf16 like einsum_bf16
            # (epoch_features casts its kernel to the epoch dtype) —
            # an f32 operator would promote the batch and confound
            # the layout A/B with a dtype-regime change
            op_dtype = (
                jnp.bfloat16
                if variant == "einsum_bf16_flat"
                else jnp.float32
            )
            bd_dev = jnp.asarray(bd, dtype=op_dtype)

            @jax.jit
            def extract(xflat):
                y = jax.lax.dot_general(
                    xflat, bd_dev, (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                )
                return dwt_xla.safe_l2_normalize(y)

        else:
            # A/B formulation: flatten (B, C, T) -> (B*C, T) and run
            # one explicit 2-D matmul instead of the bct,tk einsum
            kernel_np = np.zeros((T, fsize), np.float32)
            kernel_np[skip : skip + esize] = np.asarray(
                dwt_xla.cascade_matrix(widx, esize, fsize), np.float32
            )

            @jax.jit
            def extract(x):
                K = jnp.asarray(kernel_np)
                B = x.shape[0]
                flat = x.reshape(B * C, T)
                y = jax.lax.dot_general(
                    flat, K, (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                )
                return dwt_xla.safe_l2_normalize(y.reshape(B, C * fsize))

        if variant in ("einsum_flat", "einsum_bf16_flat"):
            shape = (n, 3 * 1000)
        elif variant in ("einsum_512", "einsum_512_bf16"):
            shape = (n, 3, esize)
        else:
            shape = (n, 3, 1000)
        epochs = jax.random.normal(
            jax.random.PRNGKey(0), shape, dtype=jnp.float32
        ) * 50.0
        if variant in ("einsum_bf16", "einsum_bf16_flat"):
            # bf16-RESIDENT epochs: the HBM bytes halve only if the
            # array in memory is bf16, not merely cast inside the jit
            epochs = epochs.astype(jnp.bfloat16)
            bytes_per_epoch = 3 * 1000 * 2
        elif variant == "einsum_512_bf16":
            epochs = epochs.astype(jnp.bfloat16)
            bytes_per_epoch = 3 * esize * 2
        elif variant == "einsum_512":
            bytes_per_epoch = 3 * esize * 4
        else:
            bytes_per_epoch = 3 * 1000 * 4

        if variant in ("einsum_sliced", "einsum_512", "einsum_512_bf16"):
            # perturb the SMALL operator, not the stream: an x + i
            # perturbation would materialize a full-width copy per
            # iteration and confound the byte-traffic A/B these
            # variants exist to measure (review finding; same hazard
            # the regular variant documents)
            @jax.jit
            def loop(x):
                def body(acc, i):
                    y = extract(x, k512 + i.astype(jnp.float32) * 1e-12)
                    return acc + jnp.float32(y.sum()), None

                acc, _ = jax.lax.scan(
                    body, jnp.float32(0), jnp.arange(iters)
                )
                return acc
        else:
            @jax.jit
            def loop(x):
                def body(acc, i):
                    y = extract(x + i.astype(x.dtype))
                    return acc + jnp.float32(y.sum()), None

                acc, _ = jax.lax.scan(
                    body, jnp.float32(0), jnp.arange(iters)
                )
                return acc

        arg = epochs

    elif variant in ("xla_ingest", "block_ingest", "pallas_ingest"):
        S = 200 + n * STRIDE + 1000
        raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
        base = np.arange(n, dtype=np.int64) * STRIDE + 200
        jitter = rng.randint(-200, 200, size=n)
        positions = np.clip(base + jitter, 100, S - 800)
        bytes_per_epoch = 3 * STRIDE * 2

        if variant in ("xla_ingest", "block_ingest"):
            from eeg_dataanalysispackage_tpu.ops import device_ingest

            feat = (
                device_ingest.make_device_ingest_featurizer()
                if variant == "xla_ingest"
                # the host-planned alignment-classed formulation (one
                # matmul per shift class instead of the 128-variant
                # bank) — what the pipeline's fe=...-fused-block mode
                # ships, so the bench times the shipped path
                else device_ingest.make_classed_block_ingest_featurizer()
            )
            if variant == "block_ingest":
                # on-device parity spot check before timing (same
                # contract as the pallas variant): the first markers
                # must match the gather formulation
                spot = positions[:64]
                raw_spot = np.pad(
                    raw[:, : int(spot.max()) + 2048], ((0, 0), (0, 2048))
                )
                want, pos_pad, spot_mask = _gather_reference_rows(
                    raw_spot, res, spot
                )
                got = np.asarray(
                    feat(
                        jnp.asarray(raw_spot), jnp.asarray(res),
                        pos_pad, spot_mask,
                    )
                )[: len(spot)]
                block_parity = _check_parity(got, want, 5e-5, "block/gather")
            cap = ((n + 63) // 64) * 64
            pos_pad = np.zeros(cap, np.int32)
            pos_pad[:n] = positions
            mask = np.zeros(cap, bool)
            mask[:n] = True
            raw_p = np.pad(raw, ((0, 0), (0, 900)))
            args = (
                jnp.asarray(raw_p), jnp.asarray(res),
                jnp.asarray(pos_pad), jnp.asarray(mask),
            )

            if variant == "block_ingest":
                # host gather plan once (cached in ops/plan_cache);
                # the timed loop drives the inner jitted program with
                # the plan arrays closed over — planning is metadata
                # work per layout, not per step, so the steady state
                # being measured is plan-free by design
                plan = feat.plan(pos_pad, mask, raw_p.shape[1])
                plan_args = (
                    jnp.asarray(plan.class_b0), jnp.asarray(plan.Wc),
                    jnp.asarray(plan.Mc), jnp.asarray(plan.colsum),
                    jnp.asarray(plan.row_of),
                )

                def step(raw_a, res_a, pos_a, mask_a):
                    return feat._run(raw_a, res_a, *plan_args, mask_a)

            else:
                step = feat

            @jax.jit
            def loop(raw_a, res_a, pos_a, mask_a):
                def body(acc, i):
                    y = step(
                        raw_a, res_a + i.astype(jnp.float32) * 1e-12,
                        pos_a, mask_a,
                    )
                    return acc + y.sum(), None

                acc, _ = jax.lax.scan(body, jnp.float32(0),
                                      jnp.arange(iters))
                return acc

            arg = args
        else:
            from eeg_dataanalysispackage_tpu.ops import ingest_pallas

            # BENCH_PALLAS_MODE forces a kernel formulation; the
            # default follows the library's platform-aware choice
            # (bank128 on compiled Mosaic — the only chip-compiling
            # formulation, r4 probe — exact on interpreter platforms)
            from eeg_dataanalysispackage_tpu.ops import pallas_support

            mode = (
                os.environ.get("BENCH_PALLAS_MODE")
                or pallas_support.default_ingest_mode()
            )
            # single source for the kernel geometry: the library's own
            # window/bank constructors — the timed loop can never
            # drift from the shipped kernel shape
            window = ingest_pallas.kernel_window(mode)
            chunk = int(os.environ.get("BENCH_CHUNK", 65536))
            tile_b = int(os.environ.get("BENCH_TILE_B", 32))
            plan = ingest_pallas.plan_pallas_tiles(
                positions, window=window, chunk=chunk, tile_b=tile_b
            )
            from eeg_dataanalysispackage_tpu.ops import device_ingest

            bank_modes = ingest_pallas.BANK_MODES
            if mode in bank_modes:
                Wvm_np, fold_np, slab_rows = ingest_pallas.bank128_banks()
                # the offset -> row-block + in-row-shift encoding has
                # exactly one home (bank_plan_arrays); the bench must
                # time the shipped layout, never a re-derived one
                blocks, shifts_rows, _ = ingest_pallas.bank_plan_arrays(
                    plan, 3
                )
                bank_bf16 = mode == "bank128_bf16"
                bank_extra = (
                    jnp.asarray(blocks), jnp.asarray(shifts_rows),
                    jnp.asarray(Wvm_np, ingest_pallas.bank_wvm_dtype(mode)),
                    jnp.asarray(fold_np),
                )
            elif mode == "aligned8":
                Wv_np, Mv_np, colsum_np, _ = ingest_pallas.aligned8_banks()
                aligned_extra = (
                    jnp.asarray(plan.offsets & ~7),
                    jnp.asarray(plan.offsets & 7),
                    jnp.asarray(Wv_np), jnp.asarray(Mv_np),
                    jnp.asarray(colsum_np)[None, :],
                )
            else:
                E = jnp.asarray(
                    device_ingest.ingest_matrix(
                        window_len=window, fold_baseline=False
                    )
                )
            half = chunk // 2
            needed = (int(plan.half_idx.max(initial=0)) + 2) * half
            if raw.shape[1] < needed:
                raw = np.pad(raw, ((0, 0), (0, needed - raw.shape[1])))
            elif raw.shape[1] % half:
                raw = np.pad(
                    raw, ((0, 0), (0, half - raw.shape[1] % half))
                )
            fill = float((plan.src_rows >= 0).mean())
            if mode in bank_modes:
                # the bank kernel takes the stream pre-viewed as
                # 128-lane rows; resolution scaling rides outside
                args = (
                    jnp.asarray(raw.reshape(3, -1, 128)),
                    jnp.asarray(res, jnp.float32),
                    jnp.asarray(plan.half_idx),
                ) + bank_extra
            else:
                args = (
                    jnp.asarray(raw), jnp.asarray(res, jnp.float32),
                    jnp.asarray(plan.half_idx),
                )
                if mode == "aligned8":
                    args = args + aligned_extra
                else:
                    args = args + (jnp.asarray(plan.offsets), E)
            # on-device parity spot check before timing: the first 64
            # markers through the Pallas kernel must match the XLA
            # ingest path — catches silent Mosaic miscompiles so the
            # recorded throughput is known-correct
            spot = positions[:64]
            raw_spot = raw[:, : int(spot.max()) + 2048]
            got = np.asarray(
                ingest_pallas.ingest_features_pallas(
                    raw_spot, res, spot, chunk=chunk, tile_b=tile_b,
                    mode=mode,
                )
            )
            want, _, _ = _gather_reference_rows(raw_spot, res, spot)
            # aligned8/bank128 use the block-style two-term
            # correction, whose f32 floor is 5e-5 (same gate as the
            # block variant); the bf16 bank gets the bf16 feature
            # tier's 5e-3 envelope (measured 1.7e-3 worst-case under
            # full-range DC + drift)
            tol = {
                "aligned8": 5e-5, "bank128": 5e-5, "bank128_bf16": 5e-3,
            }.get(mode, 5e-6)
            parity_dev = _check_parity(
                got, want, tol, f"pallas[{mode}]/XLA",
            )

            if mode in bank_modes:
                @jax.jit
                def loop(raw_rows, res_a, hi, blks, sh, Wvm, fold):
                    def body(acc, i):
                        from eeg_dataanalysispackage_tpu.ops import (
                            dwt as dwt_xla,
                            pallas_support,
                        )

                        # perturb the 128KB f32 fold matrix, not the
                        # GB-scale stream (anti-CSE; the bank itself
                        # may be bf16, where +1e-12 would round away)
                        rows_out = ingest_pallas.bank_ingest_rows(
                            raw_rows, hi, blks, sh,
                            Wvm, fold + i.astype(jnp.float32) * 1e-12,
                            tile_b=tile_b, chunk=chunk, feature_size=16,
                            slab_rows=slab_rows, bank_bf16=bank_bf16,
                            interpret=pallas_support.default_interpret(),
                        )
                        res_rows = jnp.tile(
                            res_a, rows_out.shape[0] // 3
                        )[:, None]
                        y = dwt_xla.safe_l2_normalize(
                            (rows_out * res_rows).reshape(-1, 48)
                        )
                        return acc + y.sum(), None

                    acc, _ = jax.lax.scan(body, jnp.float32(0),
                                          jnp.arange(iters))
                    return acc

            elif mode == "aligned8":
                @jax.jit
                def loop(raw_a, res_a, hi, offs8, sh, Wv, Mv, cs):
                    def body(acc, i):
                        from eeg_dataanalysispackage_tpu.ops import (
                            pallas_support,
                        )

                        y = ingest_pallas._ingest_tiles_aligned(
                            raw_a, res_a + i.astype(jnp.float32) * 1e-12,
                            hi, offs8, sh, Wv, Mv, cs,
                            tile_b=tile_b, chunk=chunk, window8=window,
                            feature_size=16,
                            interpret=pallas_support.default_interpret(),
                        )
                        return acc + y.sum(), None

                    acc, _ = jax.lax.scan(body, jnp.float32(0),
                                          jnp.arange(iters))
                    return acc

            else:
                @jax.jit
                def loop(raw_a, res_a, hi, offs, E_a):
                    def body(acc, i):
                        from eeg_dataanalysispackage_tpu.ops import (
                            pallas_support,
                        )

                        y = ingest_pallas._ingest_tiles(
                            raw_a, res_a + i.astype(jnp.float32) * 1e-12,
                            hi, offs,
                            E_a, tile_b=tile_b, chunk=chunk, window=window,
                            feature_size=16,
                            interpret=pallas_support.default_interpret(),
                        )
                        return acc + y.sum(), None

                    acc, _ = jax.lax.scan(body, jnp.float32(0),
                                          jnp.arange(iters))
                    return acc

            arg = args

    elif variant == "decode_ingest":
        from eeg_dataanalysispackage_tpu.ops import decode_ingest, device_ingest

        S = 200 + n * STRIDE + 1000
        raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
        base = np.arange(n, dtype=np.int64) * STRIDE + 200
        jitter = rng.randint(-200, 200, size=n)
        positions = np.clip(base + jitter, 100, S - 800)
        bytes_per_epoch = 3 * STRIDE * 2
        cap = ((n + 63) // 64) * 64
        pos_pad = np.zeros(cap, np.int32)
        pos_pad[:n] = positions
        mask = np.zeros(cap, bool)
        mask[:n] = True
        raw_p = np.pad(raw, ((0, 0), (0, 900)))

        formulation = (
            os.environ.get("BENCH_DECODE_FORMULATION")
            or decode_ingest.default_formulation()
        )
        feat = decode_ingest.make_decode_ingest_featurizer(
            formulation=formulation
        )
        # on-device parity spot check before timing (the block/pallas
        # contract): the first markers must match the gather
        # formulation. slice is subtract-first like the gather rung
        # (~6e-7 floor); bank128 carries the block-class two-term
        # correction's 5e-5 envelope.
        spot = positions[:64]
        raw_spot = np.pad(
            raw[:, : int(spot.max()) + 2048], ((0, 0), (0, 2048))
        )
        want, spot_pos, spot_mask = _gather_reference_rows(
            raw_spot, res, spot
        )
        got = np.asarray(
            feat(jnp.asarray(raw_spot), jnp.asarray(res),
                 spot_pos, spot_mask)
        )[: len(spot)]
        decode_parity = _check_parity(
            got, want, 5e-6 if formulation == "slice" else 5e-5,
            f"decode[{formulation}]/gather",
        )

        # the same-machine gather baseline: SAME data, SAME epoch
        # count, SAME best-of-2 discipline as the decode measurement
        # below, taken back-to-back — this box's load swings 2-4x
        # between minutes, so a ratio of two timings from different
        # moments (or different batch sizes: the gather's per-element
        # cost drops when the output fits cache) measures the
        # weather, not the kernels. The decode line's headline claim
        # is this ratio; the historical 54.8k eps chip figure rides
        # along as a second reference.
        def _best_eps(fn, reps=2):
            return _best_of_eps(fn, n, iters, reps)

        gather_feat = device_ingest.make_device_ingest_featurizer()
        gather_args = (
            jnp.asarray(raw_p), jnp.asarray(res),
            jnp.asarray(pos_pad), jnp.asarray(mask),
        )

        def _gather_pass():
            for _ in range(iters):
                jax.block_until_ready(gather_feat(*gather_args))

        if formulation == "slice":
            # host tile plan once (cached in ops/plan_cache), then the
            # timed loop drives the inner jitted program — planning is
            # per-layout metadata work, not per-step (the block_ingest
            # policy)
            pre = 100
            win = pre + 175 + 512
            tiles = decode_ingest.plan_decode_windows(
                pos_pad, mask, raw_p.shape[1], pre=pre, window=win,
                tile=decode_ingest.DEFAULT_TILE,
            )
            run_prog = decode_ingest._slice_program(
                8, 512, 175, 16, pre, decode_ingest.DEFAULT_TILE,
                False, False,
                splits=decode_ingest.default_splits(),
            )
            # the plan pads capacities up to the geometric bucket;
            # driving the inner program directly means padding the
            # mask the same way the library wrapper does (a cap that
            # is not 64*2^k would otherwise shape-mismatch)
            mask_b = (
                mask if tiles.size == mask.shape[0]
                else np.pad(mask, (0, tiles.size - mask.shape[0]))
            )
            args = (
                jnp.asarray(raw_p), jnp.asarray(res),
                jnp.asarray(tiles), jnp.asarray(mask_b),
            )

            # direct dispatch per iteration, NOT an outer jitted scan:
            # the slice program parallelizes its split scans across
            # cores only as a top-level computation — wrapped in an
            # outer scan body XLA:CPU executes them serially (measured
            # ~1.5x slower). The scan-loop discipline exists for the
            # axon tunnel's missing block_until_ready, and the slice
            # formulation never runs there (accelerators route decode
            # to bank128).
            def _decode_pass():
                for _ in range(iters):
                    jax.block_until_ready(run_prog(*args))

            # the ratio pair, measured back-to-back (see the
            # gather-baseline comment above)
            decode_eps_best = _best_eps(_decode_pass)
            gather_eps = _best_eps(_gather_pass)

            def loop(raw_a, res_a, tiles_a, mask_a):
                acc = 0.0
                for _ in range(iters):
                    acc += float(
                        np.asarray(
                            run_prog(raw_a, res_a, tiles_a, mask_a)
                        ).sum()
                    )
                return acc

            arg = args
        else:
            # bank128 routing: time the featurizer whole (host plan is
            # plan_cache-warm after the first call) — the kernel loop
            # shape lives in the pallas_ingest variant; here the
            # decode rung is measured as shipped
            args = (
                jnp.asarray(raw_p), jnp.asarray(res), pos_pad, mask,
            )
            jax.block_until_ready(feat(*args))  # compile + plan

            def _decode_pass():
                for _ in range(iters):
                    jax.block_until_ready(feat(*args))

            decode_eps_best = _best_eps(_decode_pass)
            gather_eps = _best_eps(_gather_pass)

            def loop(raw_a, res_a, pos_a, mask_a):
                acc = 0.0
                for _ in range(iters):
                    acc += float(
                        np.asarray(feat(raw_a, res_a, pos_a, mask_a)).sum()
                    )
                return acc

            arg = args

    elif variant == "sharded_ingest":
        import re

        from eeg_dataanalysispackage_tpu.io.brainvision import Marker
        from eeg_dataanalysispackage_tpu.ops import device_ingest
        from eeg_dataanalysispackage_tpu.parallel import (
            mesh as pmesh,
            sharded_ingest,
        )

        n_dev = min(8, jax.device_count())
        tmesh = pmesh.make_mesh(n_dev, axes=(pmesh.TIME_AXIS,))
        S = 200 + n * STRIDE + 2048
        block = sharded_ingest.shard_block_for(S, n_dev)
        T = n_dev * block
        raw = rng.randint(-3000, 3000, size=(3, T), dtype=np.int16)
        base = np.arange(n, dtype=np.int64) * STRIDE + 200
        jitter = rng.randint(-200, 200, size=n)
        positions = np.clip(base + jitter, 100, S - 800)
        bytes_per_epoch = 3 * STRIDE * 2
        markers = [
            Marker(f"Mk{i}", "Stimulus", f"S  {1 + i % 9}", int(p))
            for i, p in enumerate(positions)
        ]
        # guessed 0 matches nothing: every marker is a kept
        # non-target, so both paths featurize exactly n windows
        plan = sharded_ingest.plan_sharded_ingest(
            markers, 0, T, n_dev, block
        )
        extract = sharded_ingest.make_sharded_ingest(tmesh)
        staged = sharded_ingest.stage_recording_int16(raw, tmesh)

        # sharding structure, not just execution: the ring halo must
        # lower to a collective-permute on real (n>=2) meshes
        hlo = (
            extract._sharded_jit.lower(
                staged,
                jnp.asarray(res, jnp.float32),
                jnp.asarray(plan.local_positions),
                jnp.asarray(plan.mask),
            )
            .compile()
            .as_text()
        )
        permutes = len(re.findall(r"collective-permute(?:-start)?\(", hlo))
        assert n_dev < 2 or permutes >= 1, (
            f"sharded ingest compiled without a collective-permute "
            f"on a {n_dev}-device mesh"
        )

        # the same-machine single-device twin: the identical block
        # featurizer, unsharded, on the same markers — measured back
        # to back with the sharded pass (the decode rung's
        # same-machine-baseline discipline)
        twin_plan = device_ingest.plan_ingest(markers, 0, T)
        twin = device_ingest.make_block_ingest_featurizer()
        twin_args = (
            jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(twin_plan.positions), jnp.asarray(twin_plan.mask),
        )
        got = np.asarray(extract(staged, res, plan))
        want = np.asarray(twin(*twin_args))[twin_plan.mask]
        sharded_parity = _check_parity(
            got, want, 5e-5, "sharded/single-device",
        )

        def _sharded_pass():
            for _ in range(iters):
                extract(staged, res, plan)  # host fetch synchronizes

        def _twin_pass():
            for _ in range(iters):
                jax.block_until_ready(twin(*twin_args))

        sharded_eps_best = _best_of_eps(_sharded_pass, n, iters)
        single_eps = _best_of_eps(_twin_pass, n, iters)
        sharded_mesh_block = {
            "devices": n_dev,
            "axis": pmesh.TIME_AXIS,
            "block": int(block),
            "collective_permute": permutes,
            "single_device_eps": round(single_eps, 1),
            "sharded_eps_best": round(sharded_eps_best, 1),
            "vs_single_device": round(sharded_eps_best / single_eps, 2),
        }

        def loop(_staged, _res):
            acc = 0.0
            for _ in range(iters):
                acc += float(extract(_staged, _res, plan).sum())
            return acc

        arg = (staged, res)

    elif variant == "regular_ingest":
        from eeg_dataanalysispackage_tpu.ops import device_ingest

        formulation = os.environ.get("BENCH_FORMULATION", "auto")
        # tail slack covers the phase formulation's aligned slab
        S = 200 + n * REGULAR_STRIDE + 8192
        raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
        ing = device_ingest.make_regular_ingest_featurizer(
            REGULAR_STRIDE, n, formulation=formulation
        )
        bytes_per_epoch = 3 * REGULAR_STRIDE * 2
        args = (jnp.asarray(raw), jnp.asarray(res))

        @jax.jit
        def loop(raw_a, res_a):
            def body(acc, i):
                # perturb the (C,) resolutions, not the GB-scale int16
                # stream: a stream perturbation materializes a full
                # copy every iteration (unfusable into the reshape),
                # tripling the measured traffic
                y = ing(raw_a, res_a + i.astype(jnp.float32) * 1e-12, 150)
                return acc + y.sum(), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(iters))
            return acc

        arg = args

    elif variant == "train_step":
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        epochs = jax.random.normal(
            jax.random.PRNGKey(0), (n, 3, 1000), dtype=jnp.float32
        ) * 50.0
        labels = jnp.asarray(
            rng.randint(0, 2, size=n).astype(np.float32)
        )
        init_state, step = ptrain.make_train_step()
        state0 = init_state(jax.random.PRNGKey(0))
        mask = jnp.ones((n,), jnp.float32)
        bytes_per_epoch = 3 * 1000 * 4

        @jax.jit
        def loop(x, y, m):
            def body(state, i):
                state2, loss = step(state, x + i, y, m)
                return state2, loss

            state, losses = jax.lax.scan(
                body, state0, jnp.arange(iters, dtype=jnp.float32)
            )
            return jax.tree_util.tree_reduce(
                lambda a, b: a + b.sum(), state, jnp.float32(0)
            ) + losses.sum()

        arg = (epochs, labels, mask)

    elif variant == "train_step_512":
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        epochs = jax.random.normal(
            jax.random.PRNGKey(0), (n, 3, 512), dtype=jnp.float32
        ) * 50.0
        labels = jnp.asarray(
            rng.randint(0, 2, size=n).astype(np.float32)
        )
        init_state, step = ptrain.make_compact_train_step()
        state0 = init_state(jax.random.PRNGKey(0))
        mask = jnp.ones((n,), jnp.float32)
        bytes_per_epoch = 3 * 512 * 4

        @jax.jit
        def loop(x, y, m):
            def body(state, i):
                state2, loss = step(state, x + i, y, m)
                return state2, loss

            state, losses = jax.lax.scan(
                body, state0, jnp.arange(iters, dtype=jnp.float32)
            )
            return jax.tree_util.tree_reduce(
                lambda a, b: a + b.sum(), state, jnp.float32(0)
            ) + losses.sum()

        arg = (epochs, labels, mask)

    elif variant == "train_step_raw":
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        first = 150
        S = 200 + n * REGULAR_STRIDE + 8192
        raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
        labels = jnp.asarray(rng.randint(0, 2, size=n).astype(np.float32))
        init_state, step = ptrain.make_raw_train_step(
            REGULAR_STRIDE, n,
            formulation=os.environ.get("BENCH_FORMULATION", "auto"),
        )
        state0 = init_state(jax.random.PRNGKey(0))
        mask = jnp.ones((n,), jnp.float32)
        bytes_per_epoch = 3 * REGULAR_STRIDE * 2
        args = (jnp.asarray(raw), jnp.asarray(res), labels, mask)

        @jax.jit
        def loop(raw_a, res_a, y, m):
            def body(state, i):
                state2, loss = step(
                    state, raw_a, res_a + i * 1e-12, y, m, first
                )
                return state2, loss

            state, losses = jax.lax.scan(
                body, state0, jnp.arange(iters, dtype=jnp.float32)
            )
            return jax.tree_util.tree_reduce(
                lambda a, b: a + b.sum(), state, jnp.float32(0)
            ) + losses.sum()

        arg = args

    elif variant == "train_step_block":
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        S = 200 + n * STRIDE + 1000
        raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
        base = np.arange(n, dtype=np.int64) * STRIDE + 200
        jitter = rng.randint(-200, 200, size=n)
        positions = np.clip(base + jitter, 100, S - 800)
        cap = ((n + 63) // 64) * 64
        pos_pad = np.zeros(cap, np.int32)
        pos_pad[:n] = positions
        mask = np.zeros(cap, bool)
        mask[:n] = True
        labels = jnp.asarray(
            np.pad(rng.randint(0, 2, size=n).astype(np.float32),
                   (0, cap - n))
        )
        init_state, step = ptrain.make_irregular_train_step()
        state0 = init_state(jax.random.PRNGKey(0))
        # same byte model as the bare block_ingest variant (stream
        # bytes), so the two roofline numbers are directly comparable
        bytes_per_epoch = 3 * STRIDE * 2
        # no caller-side pad: the block featurizer zero-pads the
        # stream internally for overhanging slabs
        args = (
            jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos_pad), jnp.asarray(mask), labels,
        )

        @jax.jit
        def loop(raw_a, res_a, pos_a, mask_a, y):
            def body(state, i):
                state2, loss = step(
                    state, raw_a, res_a + i * 1e-12, pos_a, mask_a, y
                )
                return state2, loss

            state, losses = jax.lax.scan(
                body, state0, jnp.arange(iters, dtype=jnp.float32)
            )
            return jax.tree_util.tree_reduce(
                lambda a, b: a + b.sum(), state, jnp.float32(0)
            ) + losses.sum()

        arg = args

    elif variant == "train_step_bank":
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        S = 200 + n * STRIDE + 1000
        raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
        base = np.arange(n, dtype=np.int64) * STRIDE + 200
        jitter = rng.randint(-200, 200, size=n)
        positions = np.clip(base + jitter, 100, S - 800)
        labels = jnp.asarray(rng.randint(0, 2, size=n).astype(np.float32))
        mode = os.environ.get("BENCH_PALLAS_MODE") or "bank128"
        init_state, step = ptrain.make_irregular_bank_train_step(
            positions, mode=mode
        )
        state0 = init_state(jax.random.PRNGKey(0))
        # same byte model as train_step_block (stream bytes), so the
        # block vs bank training rows are directly comparable
        bytes_per_epoch = 3 * STRIDE * 2
        args = (jnp.asarray(raw), jnp.asarray(res), labels)

        @jax.jit
        def loop(raw_a, res_a, y):
            def body(state, i):
                state2, loss = step(state, raw_a, res_a + i * 1e-12, y)
                return state2, loss

            state, losses = jax.lax.scan(
                body, state0, jnp.arange(iters, dtype=jnp.float32)
            )
            return jax.tree_util.tree_reduce(
                lambda a, b: a + b.sum(), state, jnp.float32(0)
            ) + losses.sum()

        arg = args

    elif variant == "rf_train":
        from eeg_dataanalysispackage_tpu.models import trees, trees_device

        T, depth, bins = 100, 5, 32
        feats = rng.randn(n, 48)
        labels = (feats[:, 0] + 0.3 * rng.randn(n) > 0).astype(np.int32)
        edges = trees.compute_bin_edges(feats, bins)
        binned = trees.bin_features(feats, edges)
        boot = np.random.RandomState(12345).randint(0, n, size=(T, n))
        masks = trees_device.draw_feature_masks(
            T, trees_device.n_heap_nodes(depth - 1), 48, None
        )
        # dominant per-tree traffic: the bootstrap-gathered (n, 48)
        # int32 view each tree reads while building histograms
        bytes_per_epoch = T * 48 * 4
        args = (
            jnp.asarray(binned, jnp.int32), jnp.asarray(labels),
            jnp.asarray(boot), jnp.asarray(masks),
        )

        @jax.jit
        def loop(binned_a, labels_a, boot_a, masks_a):
            def body(acc, i):
                forest = trees_device.grow_forest(
                    binned_a, labels_a, (boot_a + i) % n, masks_a,
                    max_bins=bins, impurity="gini", max_depth=depth,
                    min_instances=1,
                )
                return acc + forest["prediction"].sum(), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(iters))
            return acc

        arg = args

    elif variant == "rf_predict":
        from eeg_dataanalysispackage_tpu.models import trees, trees_device

        T, depth, bins = 100, 5, 32
        feats = rng.randn(4096, 48)
        labels = (feats[:, 0] + 0.3 * rng.randn(4096) > 0).astype(np.int32)
        clf = trees.RandomForestClassifier(backend="device")
        # explicit config: the bench's walk depth and byte model must
        # never drift from what the forest was actually grown with
        clf.set_config({
            "config_max_bins": str(bins), "config_impurity": "gini",
            "config_max_depth": str(depth),
            "config_min_instances_per_node": "1",
            "config_num_trees": str(T), "config_feature_subset": "auto",
        })
        clf.fit(feats, labels.astype(np.float64))
        assert clf._params["max_depth"] == depth and len(clf.trees) == T
        test_feats = rng.randn(n, 48)
        binned = jnp.asarray(
            trees.bin_features(test_feats, clf.edges), jnp.int32
        )
        packed = trees_device.host_trees_to_device(clf.trees)
        # per-row forest traffic: each tree's walk gathers one bin
        # per level from the (n, 48) int32 row
        bytes_per_epoch = T * depth * 4
        args = (*packed, binned)

        # BENCH_RF_ROW_CHUNK=8192 runs the lax.map row-chunked form —
        # the fallback probe for the r4 full-size worker fault
        row_chunk = int(os.environ.get("BENCH_RF_ROW_CHUNK", 0))

        @jax.jit
        def loop(f, t, l, r, p, b):
            def body(acc, i):
                bb = (b + (i % 2).astype(jnp.int32)) % bins
                if row_chunk:
                    votes = trees_device.predict_linked_forest_chunked(
                        f, t, l, r, p, bb,
                        max_iters=depth, row_chunk=row_chunk,
                    )
                else:
                    votes = trees_device.predict_linked_forest(
                        f, t, l, r, p, bb,
                        max_iters=depth,  # bench walks what it bills
                    )
                return acc + votes.sum(), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(iters))
            return acc

        arg = args

    else:
        raise SystemExit(f"unknown variant {variant!r}")

    args = arg if isinstance(arg, tuple) else (arg,)
    float(loop(*args))  # compile + warmup
    start = time.perf_counter()
    checksum = float(loop(*args))
    elapsed = time.perf_counter() - start
    assert np.isfinite(checksum), "non-finite checksum"

    eps = n * iters / elapsed
    gbps = eps * bytes_per_epoch / 1e9
    platform = jax.devices()[0].platform
    payload = {
        "variant": variant,
        "epochs_per_s": round(eps, 1),
        "n": n,
        "iters": iters,
        "elapsed_s": round(elapsed, 3),
        "bytes_per_epoch": bytes_per_epoch,
        # the same number in bytes/sec (bench attribution: every
        # ingest line is auditable as a bandwidth, not only a rate)
        "bytes_per_s": round(eps * bytes_per_epoch, 1),
        # host->device transfer bytes the timed loop staged (the
        # device-resident argument set; the loop itself re-reads them
        # from device memory)
        "h2d_bytes": int(
            sum(
                int(getattr(a, "nbytes", 0))
                for a in (arg if isinstance(arg, tuple) else (arg,))
            )
        ),
        "achieved_GBps": round(gbps, 1),
        "platform": platform,
    }
    # pct_of_hbm_roofline divides by the v5e HBM bandwidth, which is
    # only a meaningful denominator when the timing came from a TPU;
    # CPU runs omit the field entirely so a fallback artifact can
    # never be misread as a roofline claim (VERDICT r3 weak #6)
    if platform in ("tpu", "axon"):
        payload["pct_of_hbm_roofline"] = round(100.0 * gbps / HBM_GBPS, 1)
    # attribution fields (ISSUE 1): every variant line records the
    # host-plan cache counters for this process and the persistent
    # compile cache directory in effect (None = caching off), so a
    # BENCH trajectory can tell a warm-plan/warm-compile speedup from
    # a kernel change
    from eeg_dataanalysispackage_tpu.io import feature_cache as _feature_cache
    from eeg_dataanalysispackage_tpu.ops import plan_cache as _plan_cache

    pstats = _plan_cache.stats()
    payload["plan_cache"] = {
        "hits": pstats["hits"], "misses": pstats["misses"],
    }
    payload["compile_cache"] = _compile_cache.active_cache_dir()
    # schema parity with the pipeline_e2e family (zeros here: kernel
    # variants never touch the feature cache)
    payload["feature_cache"] = _feature_cache.stats()
    # a failed _check_parity raised above, so published numbers are valid
    if variant == "pallas_ingest":
        payload["tile_fill"] = round(fill, 3)
        payload["parity_max_abs_dev"] = parity_dev
        payload["mode"] = mode  # the RESOLVED mode, not the env default
    elif variant == "block_ingest":
        payload["parity_max_abs_dev"] = block_parity
    elif variant == "sharded_ingest":
        payload["parity_max_abs_dev"] = sharded_parity
        payload["mesh"] = sharded_mesh_block
    elif variant == "decode_ingest":
        payload["parity_max_abs_dev"] = decode_parity
        payload["formulation"] = formulation
        # the headline ratio: decode and the element-gather rung,
        # same data, same epoch count, same best-of-2 discipline,
        # measured back-to-back — plus the historical chip figure.
        # The ">=10x the gather baseline" claim in one auditable
        # block.
        payload["gather_baseline"] = {
            "same_machine_eps": round(gather_eps, 1),
            "decode_eps_best": round(decode_eps_best, 1),
            "vs_same_machine": round(decode_eps_best / gather_eps, 2),
            "chip_r05_eps": 54800.0,
            "vs_chip_r05": round(decode_eps_best / 54800.0, 2),
        }
    if variant in ("regular_ingest", "train_step_raw"):
        from eeg_dataanalysispackage_tpu.ops import device_ingest

        payload["formulation"] = device_ingest.resolve_regular_formulation(
            os.environ.get("BENCH_FORMULATION", "auto"), REGULAR_STRIDE
        )
    return payload


if __name__ == "__main__":
    variant = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 131072
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    if variant == "sharded_ingest" and "jax" not in sys.modules:
        # the mesh variant needs real devices: when this child is
        # CPU-pinned (bench.py's fallback env), force a virtual
        # 8-device host platform BEFORE jax initializes — tier-1's and
        # the MULTICHIP dryrun's mechanism. Harmless on accelerator
        # runs (the flag only sizes the unused host platform).
        _flags = [
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        _flags.append("--xla_force_host_platform_device_count=8")
        os.environ["XLA_FLAGS"] = " ".join(_flags)
    # cross-process plan-cache persistence: each bench variant runs in
    # its own fresh child, so without a warm start every recorded line
    # showed plan_cache hits: 0 forever. When EEG_TPU_PLAN_CACHE_FILE
    # is set (bench.py primes it), load the previous child's plans
    # before timing and save the union after, so repeat runs — and
    # later variants planning the same layout — report real hits.
    from eeg_dataanalysispackage_tpu.ops import plan_cache as _pc

    _pc.load_file()
    _payload = run(variant, n, iters)
    _pc.save_file()
    print(json.dumps(_payload))
