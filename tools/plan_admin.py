"""Operator CLI for the plan service: audit journals and plan reports
without reading JSON by hand.

Usage:
    python tools/plan_admin.py list  (--journal DIR | --gateway URL)
            [--tenant NAME]
    python tools/plan_admin.py show <plan_id>
            (--journal DIR [--reports DIR] | --gateway URL)
    python tools/plan_admin.py stats --gateway URL [--tenant NAME]
    python tools/plan_admin.py tail --journal DIR
            [--interval S] [--count N]
    python tools/plan_admin.py fleet --journal DIR
    python tools/plan_admin.py trace <plan_id> --journal DIR
            [--trace-dir DIR]

``list`` renders every plan record as an aligned table — id, state,
attempts, timestamp, idempotency key, query — against either a journal
directory (offline: a dead server's journal audits fine) or a running
gateway (``--gateway http://host:port``, the live view including
queued/running states).

``show`` prints one plan's full record: the journaled statistics text
(the exactly-once evidence — byte-for-byte what the client was
served), the failure error + attempt history, and, when the per-plan
report tree is reachable (``--reports DIR``, or the record's own
``report_dir``, or the gateway's report endpoint), the rendered
``run_report.json`` via tools/obs_report.py — one rendering code path,
not two.

``stats`` pulls a running gateway's ``/stats`` payload; with
``--tenant`` it prints just that tenant's serve attribution (lane,
swap generation, outcome counters, latency percentiles — the
multiplexed serving block, serve/multiplex.py) instead of the whole
payload. ``list --tenant`` narrows the plan table to queries
mentioning that tenant.

``tail`` follows a journal directory and prints records as they land
or change state — the exactly-once behavior is auditable live:
``submitted`` appears before execution, exactly one terminal record
replaces it, and an idempotent re-submit changes nothing.

``trace`` stitches one plan's distributed trace back together: the
plan's journaled trace id (``meta.trace_id``) selects the matching
spans out of every replica's ``trace-<replica>.jsonl`` segment file
(``EEG_TPU_TRACE_DIR``, or ``--trace-dir``), and the segments render
as ONE tree ordered by wall time — a plan whose holder was SIGKILLed
mid-run shows the dead replica's truncated segment followed by the
surviving replica's takeover segment, with the boundary annotated.
Works offline, like ``fleet``: the trace files and the journal are
all it reads.

``fleet`` renders the replication view of a shared journal directory
(gateway/fleet.py): every lease file joined against its plan record —
holder replica, holder pid (and whether it still exists), heartbeat
age vs the ``EEG_TPU_LEASE_TIMEOUT_S`` break threshold, and the plan
state. A ``STALE`` row is a dead replica's claim a surviving peer will
break and take over on its next scan; unleased ``submitted`` rows are
up for grabs.

Stdlib only, like every tool in this repo.
"""

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import obs_report  # noqa: E402  (tools/obs_report.py, the renderer)


def _http(url: str):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read())
        except ValueError:
            raise SystemExit(f"{url}: HTTP {e.code}")
    except (urllib.error.URLError, OSError) as e:
        raise SystemExit(f"{url}: {e}")


def _journal_entries(journal_dir: str):
    from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

    if not os.path.isdir(journal_dir):
        raise SystemExit(f"no such journal directory: {journal_dir}")
    return PlanJournal(journal_dir).entries()


def _rows_from_entries(entries):
    rows = []
    for e in entries:
        meta = e.get("meta") or {}
        rows.append({
            "plan_id": e.get("plan_id", "?"),
            "state": e.get("state", "?"),
            "attempts": e.get("attempts", 0),
            "utc": e.get("completed_utc") or e.get("failed_utc")
            or e.get("submitted_utc") or "",
            "key": meta.get("idempotency_key") or "",
            "query": e.get("query", ""),
        })
    return rows


def _rows_from_gateway(url: str):
    payload = _http(url.rstrip("/") + "/plans")
    return [
        {
            "plan_id": p.get("plan_id", "?"),
            "state": p.get("state", "?"),
            "attempts": p.get("attempts", 0),
            "utc": "",
            "key": "",
            "query": p.get("query", ""),
        }
        for p in payload.get("plans", [])
    ]


def cmd_list(args) -> int:
    rows = (
        _rows_from_gateway(args.gateway)
        if args.gateway
        else _rows_from_entries(_journal_entries(args.journal))
    )
    tenant = getattr(args, "tenant", None)
    if tenant:
        # a tenant-keyed plan names its tenant in the query string
        # (tenant=<name> or a tenants= spec entry) — substring match
        # keeps both forms findable without a schema change
        rows = [r for r in rows if tenant in r["query"]]
    if not rows:
        print(
            f"(no plan records mentioning tenant {tenant!r})"
            if tenant else "(no plan records)"
        )
        return 0
    widths = {
        k: max(len(k), *(len(str(r[k])) for r in rows))
        for k in ("plan_id", "state", "attempts", "utc", "key")
    }
    header = (
        f"{'plan_id':<{widths['plan_id']}}  {'state':<{widths['state']}}  "
        f"{'attempts':>{widths['attempts']}}  {'utc':<{widths['utc']}}  "
        f"{'key':<{widths['key']}}  query"
    )
    print(header)
    for r in rows:
        query = r["query"]
        if len(query) > 80:
            query = query[:77] + "..."
        print(
            f"{r['plan_id']:<{widths['plan_id']}}  "
            f"{r['state']:<{widths['state']}}  "
            f"{str(r['attempts']):>{widths['attempts']}}  "
            f"{r['utc']:<{widths['utc']}}  "
            f"{str(r['key']):<{widths['key']}}  {query}"
        )
    states = {}
    for r in rows:
        states[r["state"]] = states.get(r["state"], 0) + 1
    print(
        f"\n{len(rows)} plans: "
        + "  ".join(f"{k}={v}" for k, v in sorted(states.items()))
    )
    return 0


def _show_entry(entry, report_dir=None):
    meta = entry.get("meta") or {}
    print(f"plan     {entry.get('plan_id')}")
    print(f"state    {entry.get('state')}")
    print(f"query    {entry.get('query')}")
    for field in ("submitted_utc", "completed_utc", "failed_utc"):
        if entry.get(field):
            print(f"{field.split('_')[0]:<10}{entry[field]}")
    if entry.get("attempts"):
        print(f"attempts {entry['attempts']}")
    if meta.get("idempotency_key"):
        print(f"idempotency_key {meta['idempotency_key']}")
    if meta.get("gateway"):
        print(f"gateway  {meta['gateway']}")
    if meta.get("recovered"):
        print("recovered: resumed from a prior process's journal")
    if entry.get("error"):
        print(f"\nerror: {entry['error']}")
    if entry.get("statistics"):
        print(
            f"\nstatistics (sha256 "
            f"{entry.get('statistics_sha256', '')[:16]}…):"
        )
        print(entry["statistics"].rstrip("\n"))
    report_dir = report_dir or meta.get("report_dir")
    if report_dir:
        path = os.path.join(report_dir, "run_report.json")
        crash = os.path.join(report_dir, "crash_report.json")
        if os.path.exists(path):
            print(f"\n--- run report ({path}) ---")
            obs_report.show(path)
        elif os.path.exists(crash):
            print(f"\n--- crash report ({crash}) ---")
            obs_report.show(crash)
        else:
            print(f"\n(no report artifact under {report_dir})")


def cmd_show(args) -> int:
    if args.gateway:
        base = args.gateway.rstrip("/")
        status = _http(f"{base}/plans/{args.plan_id}")
        if "error" in status and "state" not in status:
            print(status["error"])
            return 1
        print(json.dumps(status, indent=2, sort_keys=True))
        if status.get("state") in ("completed", "failed", "cancelled"):
            report = _http(f"{base}/plans/{args.plan_id}/report")
            if report.get("statistics"):
                print(
                    f"\nstatistics (sha256 "
                    f"{(report.get('statistics_sha256') or '')[:16]}…):"
                )
                print(report["statistics"].rstrip("\n"))
            if report.get("error"):
                print(f"\nerror: {report['error']}")
            run_report = report.get("run_report")
            if run_report is not None:
                # reuse the obs_report renderer on a temp copy — one
                # rendering path for local and remote artifacts
                import tempfile

                with tempfile.NamedTemporaryFile(
                    "w", suffix=".json", delete=False
                ) as f:
                    json.dump(run_report, f)
                    tmp = f.name
                try:
                    print("\n--- run report (via gateway) ---")
                    obs_report.show(tmp)
                finally:
                    os.unlink(tmp)
        return 0
    from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

    entry = PlanJournal(args.journal).entry(args.plan_id)
    if entry is None:
        print(f"no journal record for {args.plan_id} in {args.journal}")
        return 1
    report_dir = (
        os.path.join(args.reports, args.plan_id) if args.reports else None
    )
    _show_entry(entry, report_dir=report_dir)
    return 0


def cmd_stats(args) -> int:
    """The gateway's /stats payload; ``--tenant`` narrows it to one
    tenant's serve attribution — the operator's single-tenant view
    without scraping the full payload."""
    payload = _http(args.gateway.rstrip("/") + "/stats")
    if not args.tenant:
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0
    serve = payload.get("serve")
    if not serve:
        print(
            "gateway has no serve block (no prediction service "
            "attached)"
        )
        return 1
    tenants = serve.get("tenants") or {}
    block = tenants.get(args.tenant)
    if block is None:
        print(
            f"unknown tenant {args.tenant!r}; registered: "
            f"{sorted(tenants)}"
        )
        return 1
    print(json.dumps(
        {
            "tenant": args.tenant,
            **block,
            "tenant_quota": serve.get("tenant_quota"),
            "rung": serve.get("rung"),
        },
        indent=2, sort_keys=True,
    ))
    return 0


def cmd_tail(args) -> int:
    """Follow the journal: print each record when it first appears and
    again on every state change (the submitted -> terminal transition
    is the exactly-once audit trail)."""
    seen = {}
    printed = 0
    while True:
        for entry in _journal_entries(args.journal):
            pid = entry.get("plan_id")
            state = entry.get("state")
            if seen.get(pid) == state:
                continue
            seen[pid] = state
            stamp = (
                entry.get("completed_utc") or entry.get("failed_utc")
                or entry.get("submitted_utc") or ""
            )
            line = f"{stamp}  {pid:<8} {state:<10}"
            if state == "completed":
                line += (
                    f" attempts={entry.get('attempts')} sha256="
                    f"{(entry.get('statistics_sha256') or '')[:12]}…"
                )
            elif state == "failed":
                line += f" {str(entry.get('error', ''))[:100]}"
            else:
                line += f" {entry.get('query', '')[:80]}"
            print(line, flush=True)
            printed += 1
            if args.count and printed >= args.count:
                return 0
        if args.count and printed >= args.count:
            return 0
        time.sleep(args.interval)


def cmd_fleet(args) -> int:
    """The replication view: lease files joined against plan records.
    Works offline against any shared journal directory — auditing a
    fleet does not require a live replica."""
    from eeg_dataanalysispackage_tpu.scheduler import lease as lease_mod
    from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

    if not os.path.isdir(args.journal):
        raise SystemExit(f"no such journal directory: {args.journal}")
    journal = PlanJournal(args.journal)
    states = {
        e.get("plan_id"): e for e in journal.entries()
    }
    # observer-only LeaseDir: the holder id is never written because
    # this command never claims
    leases = lease_mod.LeaseDir(args.journal, holder="plan-admin")
    rows = []
    for info in leases.scan():
        entry = states.pop(info["plan_id"], None) or {}
        meta = entry.get("meta") or {}
        fleet_meta = meta.get("fleet") or {}
        rows.append({
            "plan_id": info["plan_id"],
            "state": entry.get("state", "(no record)"),
            "holder": info["holder"] or "?",
            "pid": f"{info['pid']}"
            + (" (dead)" if info["pid_dead"] else ""),
            "beat_age": f"{info['age_s']:.1f}s",
            "lease": "STALE" if info["stale"] else "held",
            "takeover": "yes" if fleet_meta.get("takeover") else "",
        })
    # unleased unfinished records: claimable by any replica's next scan
    for plan_id in sorted(states):
        entry = states[plan_id]
        if entry.get("state") != "submitted":
            continue
        rows.append({
            "plan_id": plan_id,
            "state": "submitted",
            "holder": "-",
            "pid": "-",
            "beat_age": "-",
            "lease": "unleased",
            "takeover": "",
        })
    timeout = lease_mod.lease_timeout()
    print(
        f"journal {args.journal}  "
        f"(lease break threshold {timeout:.0f}s + dead holder pid)"
    )
    if not rows:
        print("(no leases and no unfinished records — fleet is idle)")
        return 0
    cols = ("plan_id", "state", "holder", "pid", "beat_age", "lease",
            "takeover")
    widths = {
        c: max(len(c), *(len(str(r[c])) for r in rows)) for c in cols
    }
    print("  ".join(f"{c:<{widths[c]}}" for c in cols))
    for r in rows:
        print("  ".join(f"{str(r[c]):<{widths[c]}}" for c in cols))
    stale = sum(1 for r in rows if r["lease"] == "STALE")
    unleased = sum(1 for r in rows if r["lease"] == "unleased")
    print(
        f"\n{len(rows)} rows: {stale} stale (will be broken), "
        f"{unleased} unleased submitted (claimable)"
    )
    _print_device_pool(args.journal)
    return 0


def _print_device_pool(journal_dir: str) -> None:
    """The device-pool section of the fleet view: per-ordinal holders
    plus every WAITING plan with the footprint that blocks it —
    rendered only when a pool has ever run over this journal (the
    device-pool.json marker)."""
    from eeg_dataanalysispackage_tpu.scheduler import (
        placement as placement_mod,
    )

    size = placement_mod.pool_size_marker(journal_dir)
    if size is None:
        return
    devices = placement_mod.device_table(journal_dir)
    held = sum(1 for d in devices if not d["stale"])
    print(
        f"\ndevice pool: {size} ordinals, {held} held, "
        f"{size - held} claimable"
    )
    for d in devices:
        mark = "STALE" if d["stale"] else "held"
        print(
            f"  device {d['ordinal']:<3} {d['holder'] or '?':<16} "
            f"{d['age_s']:>7.1f}s  {mark}"
        )
    waiting = placement_mod.waiting_entries(journal_dir)
    for w in waiting:
        fp = w.get("footprint") or {}
        age = max(0.0, time.time() - float(w.get("since", 0.0)))
        print(
            f"  WAITING {w.get('plan_id') or '?':<10} "
            f"blocked on devices={fp.get('devices')} "
            f"hosts={fp.get('hosts')} "
            f"class={fp.get('memory_class')}  ({age:.1f}s, "
            f"promotes at {placement_mod.promotion_age():.1f}s)"
        )


def _load_trace_segments(trace_dir: str, trace_id: str):
    """Read every ``trace-*.jsonl`` segment file under ``trace_dir``
    and return the segments carrying ``trace_id``, ordered by wall
    start: ``[{segment, wall_start, takeover, attrs, spans}, ...]``.
    Unparseable lines are skipped (a SIGKILLed writer may leave a
    torn final line — that is exactly the scenario this audits)."""
    import glob

    segments = {}
    for path in sorted(glob.glob(os.path.join(trace_dir, "trace-*.jsonl"))):
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for raw in lines:
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("trace_id") != trace_id:
                continue
            name = rec.get("segment") or os.path.basename(path)
            seg = segments.setdefault(name, {
                "segment": name,
                "wall_start": None,
                "takeover": False,
                "attrs": {},
                "root_span_id": None,
                "spans": [],
            })
            if rec.get("kind") == "segment":
                seg["wall_start"] = rec.get("wall_start")
                attrs = rec.get("attrs") or {}
                seg["attrs"] = attrs
                seg["takeover"] = bool(attrs.get("takeover"))
                seg["root_span_id"] = rec.get("root_span_id")
            elif rec.get("kind") == "span":
                seg["spans"].append(rec)
                if seg["wall_start"] is None:
                    seg["wall_start"] = rec.get("wall_start")
    # a recorder only sinks spans as they FINISH: a segment whose
    # header promised a root span that never arrived belongs to a
    # writer that died with the span open (SIGKILL). Synthesize the
    # unfinished root so the dead holder's completed children hang
    # off a visible seam instead of floating parentless.
    for seg in segments.values():
        root_id = seg.get("root_span_id")
        if root_id and not any(
            s.get("span_id") == root_id for s in seg["spans"]
        ):
            seg["spans"].insert(0, {
                "kind": "span",
                "span_id": root_id,
                "parent_id": None,
                "name": "(segment root)",
                "wall_start": seg["wall_start"],
                "wall_end": None,
                "attrs": dict(seg["attrs"]),
            })
    return sorted(
        segments.values(),
        key=lambda s: (s["wall_start"] or 0.0, s["segment"]),
    )


def _render_segment_spans(spans) -> int:
    """Print one segment's spans as an indented tree (wall order
    within each level); returns the span count. A span without an end
    is rendered as UNFINISHED — the dead holder's in-flight work."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    children = {}
    roots = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)

    def walk(span, depth):
        start = span.get("wall_start") or 0.0
        end = span.get("wall_end")
        if end is None:
            timing = "UNFINISHED (holder died mid-span)"
        else:
            timing = f"{(end - start) * 1e3:.1f}ms"
        attrs = span.get("attrs") or {}
        extra = "".join(
            f" {k}={attrs[k]}" for k in sorted(attrs)
            if k not in ("plan_id", "takeover")
        )
        print(f"  {'  ' * depth}{span.get('name', '?')}  {timing}{extra}")
        for child in sorted(
            children.get(span.get("span_id"), []),
            key=lambda s: s.get("wall_start") or 0.0,
        ):
            walk(child, depth + 1)

    for root in sorted(roots, key=lambda s: s.get("wall_start") or 0.0):
        walk(root, 0)
    return len(spans)


def cmd_trace(args) -> int:
    """One plan's cross-replica trace, stitched from the per-replica
    segment files into a single tree with the takeover boundary
    annotated."""
    from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

    trace_dir = args.trace_dir or os.environ.get("EEG_TPU_TRACE_DIR")
    if not trace_dir:
        raise SystemExit(
            "no trace directory: pass --trace-dir or set "
            "EEG_TPU_TRACE_DIR"
        )
    if not os.path.isdir(args.journal):
        raise SystemExit(f"no such journal directory: {args.journal}")
    entry = PlanJournal(args.journal).entry(args.plan_id)
    if entry is None:
        print(f"no journal record for {args.plan_id} in {args.journal}")
        return 1
    trace_id = (entry.get("meta") or {}).get("trace_id")
    if not trace_id:
        print(
            f"plan {args.plan_id} has no journaled trace id (submitted "
            f"before tracing was enabled, or not via a gateway)"
        )
        return 1
    segments = _load_trace_segments(trace_dir, trace_id)
    if not segments:
        print(
            f"trace {trace_id} (plan {args.plan_id}): no segments under "
            f"{trace_dir} — was EEG_TPU_TRACE_DIR set on the replicas?"
        )
        return 1
    total = sum(len(s["spans"]) for s in segments)
    print(
        f"trace {trace_id}  plan {args.plan_id}  state "
        f"{entry.get('state', '?')}  — {len(segments)} segment(s), "
        f"{total} span(s)"
    )
    prev = None
    for seg in segments:
        marker = ""
        if seg["takeover"]:
            died = f" after {prev} died" if prev else ""
            marker = f"  <-- TAKEOVER boundary: continued{died}"
        print(f"\nsegment {seg['segment']}{marker}")
        _render_segment_spans(seg["spans"])
        prev = seg["segment"]
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="plan_admin", description=__doc__.split("\n\n")[0],
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_list = sub.add_parser("list", help="table of all plan records")
    p_show = sub.add_parser("show", help="one plan's full record + report")
    p_show.add_argument("plan_id")
    p_stats = sub.add_parser(
        "stats", help="gateway /stats (optionally one tenant's block)"
    )
    p_stats.add_argument("--gateway", required=True)
    p_stats.add_argument(
        "--tenant",
        help="print only this tenant's serve attribution",
    )
    p_tail = sub.add_parser("tail", help="follow a journal directory")
    p_fleet = sub.add_parser(
        "fleet", help="replication view: leases joined to plan records"
    )
    p_fleet.add_argument("--journal", required=True)
    p_trace = sub.add_parser(
        "trace",
        help="one plan's cross-replica trace tree (takeover-aware)",
    )
    p_trace.add_argument("plan_id")
    p_trace.add_argument("--journal", required=True)
    p_trace.add_argument(
        "--trace-dir", dest="trace_dir",
        help="trace segment directory (default: EEG_TPU_TRACE_DIR)",
    )
    for p in (p_list, p_show):
        p.add_argument("--journal", help="journal directory")
        p.add_argument("--gateway", help="running gateway URL")
    p_list.add_argument(
        "--tenant",
        help="only plans whose query mentions this tenant",
    )
    p_show.add_argument(
        "--reports",
        help="per-plan report root (<root>/<plan_id>/run_report.json)",
    )
    p_tail.add_argument("--journal", required=True)
    p_tail.add_argument("--interval", type=float, default=1.0)
    p_tail.add_argument(
        "--count", type=int, default=0,
        help="exit after N printed records (0 = follow forever)",
    )
    args = parser.parse_args(argv)
    if args.command in ("list", "show"):
        if bool(args.journal) == bool(args.gateway):
            parser.error("pass exactly one of --journal / --gateway")
    if args.command == "list":
        return cmd_list(args)
    if args.command == "show":
        return cmd_show(args)
    if args.command == "stats":
        return cmd_stats(args)
    if args.command == "fleet":
        return cmd_fleet(args)
    if args.command == "trace":
        return cmd_trace(args)
    return cmd_tail(args)


if __name__ == "__main__":
    # the repo root, so the journal reader imports without installation
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(130)
