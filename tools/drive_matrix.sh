#!/bin/bash
# Drive the full run-time configuration matrix end to end: every fe=
# mode x every classifier, through the CLI against the reference
# fixture. Hermetic (CPU; the axon hook is disabled for the children).
#
#   bash tools/drive_matrix.sh [result-dir]
#
# Prints one PASS/FAIL line per combination and exits non-zero if any
# combination fails. The NN passes the full required config (the
# reference has no code-level defaults — missing keys throw).
#
# Reading the accuracies: the fixture's test split is 4 points (25%
# per point). Linear/NN accuracies are stable across every fe mode;
# the TREE families (dt/rf/gbt and twins) can report different
# accuracies under different device feature paths — all paths agree
# to ~1e-4 of the f64 truth, but quantile BINNING of near-edge values
# amplifies that jitter into different split decisions. That is a
# property of discrete tree splits on a 11-epoch fixture, not a
# defect of any path (each path's features are pinned by tolerance
# tests against the f64 host truth).
set -u
cd "$(dirname "$0")/.."
if [ $# -ge 1 ]; then
  OUT=$1
  mkdir -p "$OUT" || { echo "cannot create $OUT" >&2; exit 2; }
else
  OUT=$(mktemp -d /tmp/drive_matrix.XXXX) || exit 2
fi
INFO=/root/reference/test-data/infoTrain.txt

FE_MODES="dwt-8 dwt-8-tpu dwt-8-tpu-bf16 dwt-8-tpu-compact dwt-8-tpu-compact-bf16 dwt-8-pallas dwt-8-fused dwt-8-fused-pallas dwt-8-fused-block"
CLASSIFIERS="logreg svm dt rf nn gbt dt-tpu rf-tpu gbt-tpu"

NN_CFG="config_seed=1&config_num_iterations=5&config_learning_rate=0.05\
&config_momentum=0.9&config_weight_init=xavier&config_updater=nesterovs\
&config_optimization_algo=stochastic_gradient_descent\
&config_loss_function=xent&config_pretrain=false&config_backprop=true\
&config_layer1_layer_type=dense&config_layer1_n_out=8\
&config_layer1_drop_out=0&config_layer1_activation_function=relu\
&config_layer2_layer_type=output&config_layer2_n_out=2\
&config_layer2_drop_out=0&config_layer2_activation_function=softmax"

fail=0
total=0
for fe in $FE_MODES; do
  for clf in $CLASSIFIERS; do
    total=$((total + 1))
    result="$OUT/${fe}_${clf}.txt"
    q="info_file=$INFO&fe=$fe&train_clf=$clf&result_path=$result"
    if [ "$clf" = nn ]; then q="$q&$NN_CFG"; fi
    if env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD:${PYTHONPATH:-}" \
        timeout 300 python -m eeg_dataanalysispackage_tpu.pipeline.cli "$q" \
        > "$OUT/${fe}_${clf}.log" 2>&1 \
        && grep -q "Accuracy:" "$result" 2>/dev/null; then
      acc=$(grep "Accuracy:" "$result" | head -1)
      echo "PASS $fe x $clf ($acc)"
    else
      echo "FAIL $fe x $clf — $OUT/${fe}_${clf}.log"
      fail=$((fail + 1))
    fi
  done
done
echo "matrix: $((total - fail))/$total passed (results in $OUT)"
exit $((fail > 0))
