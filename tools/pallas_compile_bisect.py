"""Bisect which Pallas construct crashes the axon remote compile helper.

Each candidate kernel is tiny (fast compiles) and compiled+run in
sequence; every step prints ok/error so the first failing feature is
identifiable. All state is per-step; a crash in compile raises, it
does not kill the process.
"""
import json
import os
import sys
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

print("platform:", jax.devices()[0].platform, flush=True)

CH, HALF, TILE_B, WIN, PRE = 3, 1024, 4, 792, 100
CHUNK = 2 * HALF


def step(name, fn):
    try:
        out = fn()
        print(json.dumps({"step": name, "ok": True,
                          "sum": float(np.asarray(out).sum())}), flush=True)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}"
        print(json.dumps({"step": name, "ok": False,
                          "error": msg[:500]}), flush=True)


# k0: trivial copy kernel, plain grid
def k0():
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0
    x = jnp.ones((8, 128), jnp.float32)
    return pl.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32)
    )(x)


# k1: PrefetchScalarGridSpec, scalar-prefetch-driven block index
def k1():
    def kernel(idx_ref, x_ref, o_ref):
        o_ref[:] = x_ref[:] + idx_ref[pl.program_id(0)].astype(jnp.float32)
    idx = jnp.arange(4, dtype=jnp.int32)
    x = jnp.ones((4 * 8, 128), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i, idx: (idx[i], 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, idx: (i, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((4 * 8, 128), jnp.float32),
    )(idx, x)


# k2: int16 input block -> f32
def k2():
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:].astype(jnp.float32) * 0.5
    x = jnp.ones((8, 128), jnp.int16)
    return pl.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32)
    )(x)


# k3: f32 VMEM scratch, halves assignment (C rows like the real kernel)
def k3():
    def kernel(a_ref, b_ref, o_ref, chunk_ref):
        chunk_ref[:, :HALF] = a_ref[:].astype(jnp.float32)
        chunk_ref[:, HALF:] = b_ref[:].astype(jnp.float32)
        o_ref[:] = chunk_ref[:, :128]
    a = jnp.ones((CH, HALF), jnp.int16)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((CH, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((CH, CHUNK), jnp.float32)],
    )(a, a)


# k4: dynamic lane slice with a traced (SMEM scalar) offset
def k4():
    def kernel(off_ref, x_ref, o_ref):
        off = off_ref[0]
        o_ref[:] = x_ref[:, pl.ds(off, 128)]
    off = jnp.array([37], jnp.int32)
    x = jnp.ones((8, 1024), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec((8, 1024), lambda i, off: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, off: (0, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )(off, x)


# k5: loop of dynamic lane slices (WIN=792 wide) + scratch stores
def k5():
    def kernel(offs_ref, x_ref, o_ref, xa_ref):
        for e in range(TILE_B):
            off = offs_ref[e]
            seg = x_ref[:, pl.ds(off, WIN)]
            base = jnp.mean(seg[:, :PRE], axis=1, keepdims=True)
            xa_ref[e * CH:(e + 1) * CH, :] = seg - base
        o_ref[:] = xa_ref[:]
    offs = jnp.array([0, 11, 23, 800], jnp.int32)
    x = jnp.ones((CH, CHUNK), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec((CH, CHUNK), lambda i, offs: (0, 0))],
        out_specs=pl.BlockSpec((TILE_B * CH, WIN), lambda i, offs: (0, 0)),
        scratch_shapes=[pltpu.VMEM((TILE_B * CH, WIN), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((TILE_B * CH, WIN), jnp.float32),
    )(offs, x)


# k6: dot_general HIGHEST from scratch operand
def k6():
    def kernel(x_ref, e_ref, o_ref):
        y = lax.dot_general(
            x_ref[:], e_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        o_ref[:] = y
    x = jnp.ones((TILE_B * CH, WIN), jnp.float32)
    E = jnp.ones((WIN, 16), jnp.float32)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((TILE_B * CH, 16), jnp.float32),
    )(x, E)


# k4b: dynamic lane slice at an 8-ALIGNED offset with multiple_of hint
# (the aligned8 kernel's slice shape) — if k4 crashes and this
# compiles, the fix path is confirmed
def k4b():
    def kernel(off_ref, x_ref, o_ref):
        off = pl.multiple_of(off_ref[0], 8)
        o_ref[:] = x_ref[:, pl.ds(off, 128)]
    off = jnp.array([32], jnp.int32)
    x = jnp.ones((8, 1024), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec((8, 1024), lambda i, off: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, off: (0, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )(off, x)


# k5b: aligned slice loop + variant-bank contraction + one-hot select
# (the aligned8 kernel's full compute shape on tiny operands)
def k5b():
    W8 = 800
    def kernel(offs_ref, sh_ref, x_ref, wv_ref, o_ref, xa_ref):
        for e in range(TILE_B):
            off = pl.multiple_of(offs_ref[e], 8)
            seg = x_ref[:, pl.ds(off, W8)]
            d = jnp.mean(seg, axis=1, keepdims=True)
            xa_ref[e * CH:(e + 1) * CH, :] = seg - d
        yv = lax.dot_general(
            xa_ref[:], wv_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        onehot = (
            sh_ref[:][:, None]
            == lax.broadcasted_iota(jnp.int32, (TILE_B, 8), 1)
        ).astype(jnp.float32)
        yb = yv.reshape(TILE_B, CH, 8, 16)
        o_ref[:] = jnp.sum(
            yb * onehot[:, None, :, None], axis=2
        ).reshape(TILE_B, CH * 16)
    offs = jnp.array([0, 8, 16, 800], jnp.int32)
    sh = jnp.array([0, 3, 7, 1], jnp.int32)
    x = jnp.ones((CH, CHUNK), jnp.float32)
    wv = jnp.ones((W8, 8 * 16), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(1,),
        in_specs=[
            pl.BlockSpec((CH, CHUNK), lambda i, offs, sh: (0, 0)),
            pl.BlockSpec((W8, 8 * 16), lambda i, offs, sh: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_B, CH * 16), lambda i, offs, sh: (0, 0)),
        scratch_shapes=[pltpu.VMEM((TILE_B * CH, W8), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((TILE_B, CH * 16), jnp.float32),
    )(offs, sh, x, wv)


# k7: the real _ingest_tiles on tiny shapes
def k7():
    from eeg_dataanalysispackage_tpu.ops import ingest_pallas, device_ingest
    raw = np.ones((CH, 8 * CHUNK), np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    E = jnp.asarray(device_ingest.ingest_matrix(
        window_len=WIN, fold_baseline=False))
    plan = ingest_pallas.plan_pallas_tiles(
        np.array([100, 900, 1700]), window=WIN, chunk=CHUNK, tile_b=TILE_B)
    return ingest_pallas._ingest_tiles(
        jnp.asarray(raw), jnp.asarray(res), jnp.asarray(plan.half_idx),
        jnp.asarray(plan.offsets), E, tile_b=TILE_B, chunk=CHUNK,
        window=WIN, feature_size=16, interpret=False)


# k8: the real aligned8 path end-to-end on tiny shapes
def k8():
    from eeg_dataanalysispackage_tpu.ops import ingest_pallas
    raw = np.ones((CH, 8 * CHUNK), np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    return ingest_pallas.ingest_features_pallas(
        raw, res, np.array([100, 900, 1700]), chunk=CHUNK, tile_b=TILE_B,
        interpret=False, mode="aligned8")


for name, fn in [("k0_copy", k0), ("k1_prefetch", k1), ("k2_int16", k2),
                 ("k3_scratch_halves", k3), ("k4_dyn_lane_slice", k4),
                 ("k4b_aligned_slice", k4b), ("k5_slice_loop", k5),
                 ("k5b_aligned_bank", k5b), ("k6_dot_highest", k6),
                 ("k7_full_tiny", k7), ("k8_aligned8_tiny", k8)]:
    step(name, fn)
print("done", flush=True)
