# The one real-chip collection list, sourced by tools/tunnel_watch.sh
# and tools/real_chip_sweep.sh — callers define `run name timeout cmd...`
# first. Order = evidence priority (VERDICT r2): irregular-ingest
# numbers and chip-staged rows first, driver bench + cost model once
# the core numbers are safe, Pallas (remote-compile helper-crash risk)
# dead last, the bisect very last because a helper crash may re-wedge
# the tunnel.
run parity        900 python tools/tpu_parity_check.py
run einsum        600 python tools/ingest_bench.py einsum 262144 50
run xla_ingest    900 python tools/ingest_bench.py xla_ingest 32768 10
run block_ingest  900 python tools/ingest_bench.py block_ingest 32768 10
BENCH_FORMULATION=phase run regular_phase 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=conv run regular_conv 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=reshape run regular_reshape 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
BENCH_FORMULATION=partial run regular_partial 900 \
  python tools/ingest_bench.py regular_ingest 262144 20
run train_raw     900 python tools/ingest_bench.py train_step_raw 131072 20
run train_block   900 python tools/ingest_bench.py train_step_block 32768 10
run rf_train      900 python tools/ingest_bench.py rf_train 65536 3
run rf_predict    600 python tools/ingest_bench.py rf_predict 262144 10
run einsum_flat   600 python tools/ingest_bench.py einsum_flat 262144 50
run einsum_2d     600 python tools/ingest_bench.py einsum_2d 262144 50
run einsum_bf16   600 python tools/ingest_bench.py einsum_bf16 262144 50
# bf16 roofline-gap diagnostics (VERDICT r2 item 4): layout A/B at
# 2-byte elements, plus batch-size halving/doubling for dispatch
# amortization
run einsum_bf16_flat 600 python tools/ingest_bench.py einsum_bf16_flat 262144 50
run einsum_bf16_131k 600 python tools/ingest_bench.py einsum_bf16 131072 50
run einsum_bf16_524k 600 python tools/ingest_bench.py einsum_bf16 524288 50
run train_step    600 python tools/ingest_bench.py train_step 131072 20
# multi-device scale-out rows (ROADMAP item 2): the time-sharded
# ingest's mesh block (collective-permute count + single-device twin
# ratio) and the member-axis sharded population vs its vmapped twin.
# On a 1-chip terminal both honestly record the degenerate mesh; on a
# pod slice they are the 1/N-wall-time evidence.
run sharded_ingest 900 python tools/ingest_bench.py sharded_ingest 32768 10
run population_sharded 900 python tools/pipeline_bench.py population_sharded 800 2
run population_vmap_twin 900 python tools/pipeline_bench.py population_vmap 800 2
# pod-scale rows (ISSUE 14): the 2-process loopback harness measures
# the multi-process machinery on this host (parity + degraded rung);
# on a REAL pod slice, run the same population query with the
# launcher's JAX_COORDINATOR/JAX_NUM_PROCESSES/JAX_PROCESS_ID env on
# every host instead — those rows are the ~1/N wall-time evidence the
# PR 9 decision path consumes (artifact lands -> default flips)
run population_multiproc 1800 python tools/pipeline_bench.py population_multiproc 800 2
# the int8 precision rung's gate decision on chip (the precision
# block + gate_seconds ride the line)
run pipeline_int8 900 python tools/pipeline_bench.py pipeline_e2e_int8 2000 4
# the int4 rung's gate decision on chip (bottom of the ladder — the
# widest envelope; same precision-block attribution)
run pipeline_int4 900 python tools/pipeline_bench.py pipeline_e2e_int4 2000 4
# outer timeout must exceed bench.py's worst case (probe 420 +
# variant budget 1800 + one variant overrun 420 = 2640 < 3600) so the
# caller never SIGTERMs bench mid-variant; 1800 gives all 8 variants
# headroom at the documented 1-3 min each
BENCH_TOTAL_BUDGET=1800 run bench_full 3600 python bench.py
# compile-only: XLA cost model (bytes/epoch) for the TPU-compiled hot
# programs — answers "does the compiled program move more bytes than
# the design assumed" for every below-roofline number above. 3600s:
# 7 fresh chip compiles in one process, printed as produced.
run cost_report  3600 python tools/cost_report.py 32768
# pallas_dwt first: it compiled to Mosaic on chip in rounds 2+4, so
# it separates "remote compiler regressed globally" from "a kernel
# construct is the crasher"
run pallas_dwt    900 python tools/ingest_bench.py pallas_dwt 131072 20
# pallas_ingest defaults to bank128 — the one formulation whose every
# construct compiles through the remote helper (r4 probe/bisect: the
# exact and aligned8 kernels' dynamic lane slices crash it, aligned
# or not, as do lane-split reshapes). Small run first (single SMEM
# tile group, small compile), then the full-scale 3-group program.
run pallas_bank_32k 1200 python tools/ingest_bench.py pallas_ingest 32768 10
run pallas_ingest 1800 python tools/ingest_bench.py pallas_ingest 131072 20
# the serve megakernel vs its fused twin, back-to-back on chip: this
# artifact IS the accelerator decision path's input
# (ops/serve_mega.accelerator_decision — a conc-16 mega/fused ratio
# >= 1.1 flips the accelerator engine default to mega, zero code
# change). Mosaic-compiled kernel, so it sits with the Pallas rows —
# a remote-compile crash here must not cost the core numbers above.
run serve_mega 1200 python tools/serve_bench.py serve_mega 2000 2
# the multiplexed multi-tenant engine vs the N-engine solo fleet, per
# tenant level on chip: this artifact IS the consolidation decision
# path's input (serve/multiplex.accelerator_decision — a 16-tenant
# conc-16 multiplexed/fleet ratio >= 1.0, pre-registered as
# MULTIPLEX_FLIP_RATIO, flips the consolidation call, zero code
# change). Same mega program family as serve_mega, so it sits here.
run serve_multitenant 1200 python tools/serve_bench.py serve_multitenant 2000 2
# the quantized (int4 packed + per-lane scales) tenant weight stack
# vs the f32 multiplexed twin on chip: this artifact IS the
# weight-residency decision path's input
# (ops/quant.accelerator_decision — a 16-tenant conc-16 quant/f32
# preds/sec ratio >= 0.95, pre-registered as
# WEIGHTS_QUANT_FLIP_RATIO, flips the default stack residency to
# int4, zero code change). Same program family, so it sits here.
run serve_multitenant_quant 1200 python tools/serve_bench.py serve_multitenant_quant 2000 2
run pallas_bisect 900 python tools/pallas_compile_bisect.py
run sublane_probe 900 python tools/pallas_sublane_probe.py
