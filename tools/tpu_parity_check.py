"""Real-hardware parity check: fixture golden sums on the TPU chip.

The hermetic test suite pins bit-exact parity on the host (float64)
path and float32-tolerance parity for the XLA path on CPU
(tests/test_dwt_parity.py). This tool closes the last gap: it runs the
full ingest -> DWT feature path on the *real* attached accelerator and
reports the deviation of the device (float32) features from the
bit-exact host (float64) reference, plus the golden sums themselves.

Usage: python tools/tpu_parity_check.py  (prints one JSON line)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

# One source of truth for the fold orders and golden constants: the
# hermetic parity tests themselves.
from tests.test_dwt_parity import java_feature_sum
from tests.test_epoch_parity import java_epoch_sum

REFERENCE_DATA = os.environ.get(
    "EEG_REFERENCE_DATA", "/root/reference/test-data"
)
FIXTURE = os.path.join(REFERENCE_DATA, "infoTrain.txt")
GOLDEN_EPOCH_SUM = -253772.18676757812
GOLDEN_FEATURE_SUM = -24.861844096031625


def main() -> None:
    from eeg_dataanalysispackage_tpu.features import wavelet
    from eeg_dataanalysispackage_tpu.io import provider

    if not os.path.exists(FIXTURE):
        sys.exit(
            f"fixture not found: {FIXTURE} — point EEG_REFERENCE_DATA at "
            "the reference test-data directory"
        )
    batch = provider.OfflineDataProvider([FIXTURE]).load()
    epoch_sum = java_epoch_sum(batch.epochs)

    host_fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="host")
    host_feats = host_fe.extract_batch(batch.epochs)
    feature_sum = java_feature_sum(host_feats)

    device_fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="xla")
    device_feats = np.asarray(
        device_fe.extract_batch(batch.epochs), dtype=np.float64
    )
    max_abs_dev = float(np.max(np.abs(device_feats - host_feats)))

    # fused device-ingest paths on the same fixture (f32, vs host f64).
    # A fused-path failure must not lose the baseline parity numbers
    # above, so capture errors instead of propagating.
    devs = {}
    for backend in ("xla", "block", "pallas"):
        try:
            odp = provider.OfflineDataProvider([FIXTURE])
            feats, _ = odp.load_features_device(backend=backend)
            devs[backend] = float(
                np.max(np.abs(np.asarray(feats, np.float64) - host_feats))
            )
        except Exception as e:  # noqa: BLE001 — tool must always print
            devs[backend] = f"error: {e}"[:300]

    print(
        json.dumps(
            {
                "platform": jax.devices()[0].platform,
                "epochs": list(batch.epochs.shape),
                "epoch_sum_bit_exact": epoch_sum == GOLDEN_EPOCH_SUM,
                "epoch_sum": epoch_sum,
                "host_feature_sum_bit_exact": feature_sum
                == GOLDEN_FEATURE_SUM,
                "host_feature_sum": feature_sum,
                "device_feature_max_abs_dev_vs_host_f64": max_abs_dev,
                "device_feature_sum": java_feature_sum(device_feats),
                "fused_ingest_max_abs_dev": devs["xla"],
                "block_ingest_max_abs_dev": devs["block"],
                "pallas_ingest_max_abs_dev": devs["pallas"],
            }
        )
    )
    if epoch_sum != GOLDEN_EPOCH_SUM or feature_sum != GOLDEN_FEATURE_SUM:
        sys.exit(1)
    # L2-normalized features are O(1); anything past f32 rounding noise
    # indicates a device-path defect. `not (x <= tol)` fails CLOSED on
    # NaN (a NaN deviation is a defect, not a pass).
    if not (max_abs_dev <= 1e-5):
        sys.exit(2)
    # The fused paths compute the baseline mean in f32 over DC-laden
    # raw (host: f64 scale + sequential f32 fold), so their inherent
    # tolerance is wider — tests/test_device_ingest.py pins 5e-4.
    fused_bad = any(
        not isinstance(v, float) or not (v <= 5e-4) for v in devs.values()
    )
    if fused_bad:
        sys.exit(3)


if __name__ == "__main__":
    main()
