"""Serving-path benchmark child (the serve_bench family).

Usage: python tools/serve_bench.py serve_bench <n_markers> <n_files>
           [--report-dir D]
       python tools/serve_bench.py serve_mega <n_markers> <n_files>
       python tools/serve_bench.py serve_multitenant <n_markers>
           <n_files>
       python tools/serve_bench.py serve_multitenant_quant
           <n_markers> <n_files>

One hermetic run proves the serving layer's whole contract and prints
one JSON line in the driver-facing schema (bench.py whitelists the
``serve`` field through to the artifact):

- **latency/throughput sweep** — a closed-loop load generator drives
  the resident service at swept concurrency (1/4/16 submitters);
  each level records p50/p99 latency (ms), sustained
  predictions/sec, the engine rung that served it, and the level's
  own mean batch size (completed/batches deltas), plus any sheds;
- **parity pin** — served predictions are compared element-wise
  against the batch pipeline's (``load_features_device`` features +
  ``classifier.predict`` on the same epochs); the line records
  ``bit_identical`` and the driver's smoke gate fails if it is false;
- **shed probe** — a burst against a depth-1 queue must shed (and
  count every shed): admission control provably rejects-with-evidence
  rather than queueing without bound;
- **chaos soak** — with ``serve.request``/``serve.batch`` faults
  firing at p=0.1, every submitted request must still RESOLVE
  (answer, shed, deadline-exceeded, or failure with evidence — no
  hang) and the graceful drain must complete; ``chaos_clean`` records
  the verdict.

The ``serve_mega`` variant is the megakernel family
(ops/serve_mega.py): TWO resident services over one loaded model —
one pinned to the PR 6 fused program (``engine_rung="fused"``), one
on the mega rung — swept back-to-back in ONE process at each
concurrency level (temporal adjacency: this box's load swings 2-4x
between runs, so the mega/fused ratio is only meaningful measured
seconds apart). The line records per-level preds/sec + p99 pairs
with rung attribution, the mega-vs-fused AND mega-vs-batch
prediction parity pins, the within-bucket bit-identity pin (one
window's margin is byte-equal whatever batch it rides in), the
engine's mega warmup-gate record, and the int8 precision rung's
warmup gate decision — the driver-facing evidence the accelerator
decision path (serve_mega.accelerator_decision) harvests from staged
chip runs.

The ``serve_multitenant`` variant is the multiplexed engine
(serve/multiplex.py): at each tenant level N in 1/4/16, ONE resident
multiplexed service carrying N tenant models is driven at concurrency
16 back-to-back against a fleet of N solo services over the same
models (temporal adjacency again). The line records per-level
preds/sec + p50/p99 pairs for both sides with their ratio, the
per-tenant multiplexed-vs-solo prediction parity pin, the XLA compile
counts for scaling 1→16 tenants and for a hot swap (both pinned at 0
— one compile serves any tenant mix), and the resident weight bytes
(one stacked matrix vs N engines). The accelerator decision path
(multiplex.accelerator_decision) harvests the 16-tenant level from
staged chip runs of this variant.

The ``serve_multitenant_quant`` variant is the quantized tenant
weight stack (``weights_precision=int4`` on the same multiplexed
engine): 16 tenants through the VMEM-resident packed int4 matrix +
per-lane scales (dequantized inside the program) driven at
concurrency 16 back-to-back against the SAME 16 tenants through the
f32 multiplexed twin. The line records the preds/sec pair + ratio,
the per-tenant margin-parity pin against the f32 twin (within the
derived weights gate tolerance), the engine's weights-quant warmup
gate record, the resident-weight-bytes reduction (f32 stack /
packed stack — >=4x at int4), and the XLA compile counts for tenant
add/swap/remove on the LIVE quantized stack (pinned 0 — the f32
host mirror stays master, requantized and republished without a
recompile). The weight-residency decision path
(ops/quant.accelerator_decision) harvests the 16-tenant block from
staged chip runs of this variant against the pre-registered
WEIGHTS_QUANT_FLIP_RATIO.

Everything is fabricated by tests/_synthetic.py; the model is trained
and saved by the real pipeline in-process before the service loads it.
"""

import json
import os
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

# hermetic: no cross-run feature-cache coupling; serving measures the
# resident program, not cache luck
os.environ["EEG_TPU_NO_FEATURE_CACHE"] = "1"

_MARKER_STRIDE = 1000
#: raw int16 bytes per served window (3 channels x 850 samples x 2 B)
_BYTES_PER_EPOCH = 3 * 850 * 2

_CONFIG = (
    "&config_num_iterations=20&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)

_SWEEP_CONCURRENCY = (1, 4, 16)
#: requests per sweep level (windows recycle round-robin);
#: SERVE_BENCH_REQUESTS overrides (e.g. for a longer chip soak)
_REQUESTS_PER_LEVEL = int(os.environ.get("SERVE_BENCH_REQUESTS", 400))


def _build_session(data_dir: str, n_markers: int, n_files: int) -> str:
    import _synthetic

    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        guessed = 2 + (i % 7)
        _synthetic.write_recording(
            data_dir, name=name, n_markers=n_markers, guessed=guessed,
            seed=i, marker_stride=_MARKER_STRIDE,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(data_dir, "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


def _drive_level(service, windows, resolutions, concurrency: int,
                 n_requests: int, deadline_s: float,
                 tenants=None) -> dict:
    """Closed-loop load at one concurrency level: ``concurrency``
    submitter threads, each waiting for its own previous result
    before submitting the next (classic closed-loop load). The level
    dict carries its own batch-formation attribution
    (``mean_batch_size`` from the completed/batches counter deltas —
    the ``serve_flush_us`` knob's measurement surface) and the engine
    rung that served it. ``tenants`` (a name list) makes the drive
    multiplexed-service-aware: submitters spread requests round-robin
    across the tenant set, so every bucket the batcher forms is a
    mixed-tenant bucket."""
    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod

    counters_before, _ = service.batcher.snapshot()
    per_thread = max(1, n_requests // concurrency)
    latencies = []
    # deadline/shed/failed are RESOLVED outcomes (the service answered
    # with evidence); unresolved — a future nobody ever resolved — is
    # the only bad one, and the no-wedge contract says it stays 0
    outcomes = {
        "completed": 0, "shed": 0, "deadline": 0, "failed": 0,
        "unresolved": 0,
    }
    lock = threading.Lock()

    def submitter(tid: int) -> None:
        for i in range(per_thread):
            w = windows[(tid + i * concurrency) % len(windows)]
            kwargs = {}
            if tenants:
                kwargs["tenant"] = tenants[
                    (tid + i * concurrency) % len(tenants)
                ]
            try:
                fut = service.submit(
                    w, resolutions, deadline_s=deadline_s,
                    block_s=deadline_s, **kwargs,
                )
                r = fut.result(timeout=deadline_s + 10.0)
                with lock:
                    outcomes["completed"] += 1
                    latencies.append(r.latency_s)
            except batcher_mod.ShedError:
                with lock:
                    outcomes["shed"] += 1
            except deadline_mod.DeadlineExceededError:
                # subclasses TimeoutError but IS a resolution: the
                # request was failed with deadline evidence
                with lock:
                    outcomes["deadline"] += 1
            except TimeoutError:
                with lock:
                    outcomes["unresolved"] += 1
            except batcher_mod.ServeError:
                with lock:
                    outcomes["failed"] += 1

    threads = [
        threading.Thread(target=submitter, args=(t,), daemon=True)
        for t in range(concurrency)
    ]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - start
    # the same nearest-rank percentile the service's stats block uses
    from eeg_dataanalysispackage_tpu.serve.service import _percentile

    lat = sorted(latencies)
    counters_after, _ = service.batcher.snapshot()
    d_completed = (
        counters_after.get("completed", 0)
        - counters_before.get("completed", 0)
    )
    d_batches = (
        counters_after.get("batches", 0)
        - counters_before.get("batches", 0)
    )
    return {
        "concurrency": concurrency,
        "requests": per_thread * concurrency,
        **outcomes,
        "wall_s": round(wall, 3),
        "preds_per_s": round(outcomes["completed"] / wall, 1)
        if wall > 0 else 0.0,
        "p50_ms": round(_percentile(lat, 50.0) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 99.0) * 1e3, 3),
        # batch-formation attribution for THIS level (the global
        # stats block mixes all levels): how full the buckets ran
        "mean_batch_size": round(d_completed / max(1, d_batches), 3),
        "rung": service.engine.rung,
    }


def _drive_fleet(services, windows, resolutions, concurrency: int,
                 n_requests: int, deadline_s: float) -> dict:
    """The solo-fleet twin of :func:`_drive_level`: the same closed
    loop and total concurrency, but submitter thread ``t`` drives
    ``services[t % N]`` — N independent resident engines sharing the
    box, the deployment the multiplexed engine replaces. Aggregate
    preds/sec over one shared wall clock; ``mean_batch_size`` from
    the fleet-summed counter deltas (each engine only ever sees its
    own tenant's traffic, so its buckets fill from one stream)."""
    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod
    from eeg_dataanalysispackage_tpu.serve.service import _percentile

    counters_before = [s.batcher.snapshot()[0] for s in services]
    per_thread = max(1, n_requests // concurrency)
    latencies = []
    outcomes = {
        "completed": 0, "shed": 0, "deadline": 0, "failed": 0,
        "unresolved": 0,
    }
    lock = threading.Lock()

    def submitter(tid: int) -> None:
        service = services[tid % len(services)]
        for i in range(per_thread):
            w = windows[(tid + i * concurrency) % len(windows)]
            try:
                fut = service.submit(
                    w, resolutions, deadline_s=deadline_s,
                    block_s=deadline_s,
                )
                r = fut.result(timeout=deadline_s + 10.0)
                with lock:
                    outcomes["completed"] += 1
                    latencies.append(r.latency_s)
            except batcher_mod.ShedError:
                with lock:
                    outcomes["shed"] += 1
            except deadline_mod.DeadlineExceededError:
                with lock:
                    outcomes["deadline"] += 1
            except TimeoutError:
                with lock:
                    outcomes["unresolved"] += 1
            except batcher_mod.ServeError:
                with lock:
                    outcomes["failed"] += 1

    threads = [
        threading.Thread(target=submitter, args=(t,), daemon=True)
        for t in range(concurrency)
    ]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - start
    lat = sorted(latencies)
    d_completed = d_batches = 0
    for service, before in zip(services, counters_before):
        after, _ = service.batcher.snapshot()
        d_completed += after.get("completed", 0) - before.get(
            "completed", 0
        )
        d_batches += after.get("batches", 0) - before.get("batches", 0)
    return {
        "concurrency": concurrency,
        "engines": len(services),
        "requests": per_thread * concurrency,
        **outcomes,
        "wall_s": round(wall, 3),
        "preds_per_s": round(outcomes["completed"] / wall, 1)
        if wall > 0 else 0.0,
        "p50_ms": round(_percentile(lat, 50.0) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 99.0) * 1e3, 3),
        "mean_batch_size": round(d_completed / max(1, d_batches), 3),
        "rung": services[0].engine.rung,
    }


def _prepare(tmp: str, n_markers: int, n_files: int):
    """One hermetic session + trained/saved model + the serving
    windows and the batch-path prediction baseline — the setup both
    variants share."""
    from eeg_dataanalysispackage_tpu.epochs.extractor import BalanceState
    from eeg_dataanalysispackage_tpu.io import provider
    from eeg_dataanalysispackage_tpu.models import registry as clf_registry
    from eeg_dataanalysispackage_tpu.pipeline import builder
    from eeg_dataanalysispackage_tpu.serve import engine

    info = _build_session(tmp, n_markers, n_files)
    model = os.path.join(tmp, "model")

    # train + save the model with the real pipeline (load-once is
    # the serving story; training cost is not measured)
    builder.PipelineBuilder(
        f"info_file={info}&fe=dwt-8-fused&train_clf=logreg"
        f"&save_clf=true&save_name={model}&cache=false{_CONFIG}"
    ).execute()

    # the session as serving requests + the batch-path baseline
    import numpy as np

    odp = provider.OfflineDataProvider([info])
    balance = BalanceState()
    windows, targets, resolutions = [], [], None
    for _rel, guessed, rec in odp.iter_recordings():
        ws, rec_targets, resolutions = engine.windows_from_recording(
            rec, odp.channel_indices_for(rec), guessed,
            pre=odp.pre, post=odp.post, balance=balance,
        )
        windows.extend(ws)
        targets.append(rec_targets)
    targets = np.concatenate(targets)
    classifier = clf_registry.create("logreg")
    classifier.load(model)
    batch_features, _ = provider.OfflineDataProvider(
        [info]
    ).load_features_device(wavelet_index=8, backend="xla")
    batch_predictions = classifier.predict(batch_features)
    return (
        info, model, windows, targets, resolutions, classifier,
        batch_features, batch_predictions,
    )


def run(n_markers: int, n_files: int, report_dir=None) -> dict:
    import numpy as np

    from eeg_dataanalysispackage_tpu.obs import chaos
    from eeg_dataanalysispackage_tpu.pipeline import builder
    from eeg_dataanalysispackage_tpu.serve import (
        InferenceService, ServeConfig, ShedError,
    )

    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="eeg_tpu_serve_bench_")
    (
        info, model, windows, _targets, resolutions, classifier,
        _batch_features, batch_predictions,
    ) = _prepare(tmp, n_markers, n_files)

    service = InferenceService.from_saved("logreg", model)
    service.start()
    try:
        # 3. parity: served == batch, element-wise
        results = service.predict_all(windows, resolutions)
        served = np.array([r.prediction for r in results])
        parity = {
            "n": len(windows),
            "bit_identical": bool(
                np.array_equal(served, batch_predictions)
            ),
            "mismatches": int((served != batch_predictions).sum()),
        }

        # 4. the concurrency sweep
        sweep = [
            _drive_level(
                service, windows, resolutions, c,
                _REQUESTS_PER_LEVEL, deadline_s=5.0,
            )
            for c in _SWEEP_CONCURRENCY
        ]
    finally:
        service.stop(drain=True)
    stats = service.stats_block()

    # 5. shed probe: a burst against a depth-1 queue MUST shed, and
    # every shed must be counted (never a silent drop)
    probe = InferenceService(
        classifier, config=ServeConfig(
            max_batch=2, queue_depth=1, coalesce_s=0.2,
        ),
    )
    probe.start()
    shed = 0
    futs = []
    for i in range(32):
        try:
            futs.append(probe.submit(windows[0], resolutions))
        except ShedError:
            shed += 1
    probe.stop(drain=True)
    probe_counters = probe.stats_block()["requests"]
    shed_probe = {
        "burst": 32,
        "shed": shed,
        "counted_shed": probe_counters["shed"],
        "ok": shed > 0 and probe_counters["shed"] == shed,
    }

    # 6. chaos soak: with request/batch faults firing, every request
    # resolves and the drain completes — the no-wedge contract
    soak = InferenceService(
        classifier, config=ServeConfig(
            max_attempts=4, retry_backoff_s=0.01,
            default_deadline_s=5.0,
        ),
    )
    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod

    outcomes = {
        "completed": 0, "shed": 0, "deadline": 0, "failed": 0,
        "unresolved": 0,
    }
    with chaos.faults(
        "serve.request:p=0.1;serve.batch:p=0.1;seed=7"
    ):
        soak.start()
        futures = []
        for i in range(min(len(windows) * 2, 400)):
            try:
                futures.append(soak.submit(
                    windows[i % len(windows)], resolutions,
                    deadline_s=5.0, block_s=5.0,
                ))
            except ShedError:
                outcomes["shed"] += 1
        for fut in futures:
            try:
                fut.result(timeout=20.0)
                outcomes["completed"] += 1
            except deadline_mod.DeadlineExceededError:
                # resolved WITH deadline evidence — a clean outcome
                # under the no-wedge contract, not an unresolved hang
                outcomes["deadline"] += 1
            except TimeoutError:
                outcomes["unresolved"] += 1
            except Exception:
                outcomes["failed"] += 1
        drained = soak.stop(drain=True)
    chaos_block = {
        **outcomes,
        "drained_cleanly": drained,
        "chaos_clean": outcomes["unresolved"] == 0 and drained,
        "soak_counters": soak.stats_block()["requests"],
    }

    # 7. optional run_report.json with the serve block, via the real
    # serve=true pipeline mode (the smoke gate cross-checks it)
    if report_dir:
        builder.PipelineBuilder(
            f"info_file={info}&fe=dwt-8-fused&serve=true"
            f"&load_clf=logreg&load_name={model}&report={report_dir}"
        ).execute()

    import jax

    from eeg_dataanalysispackage_tpu.io import feature_cache
    from eeg_dataanalysispackage_tpu.ops import plan_cache
    from eeg_dataanalysispackage_tpu.utils import compile_cache

    best = max(s["preds_per_s"] for s in sweep)
    pstats = plan_cache.stats()
    return {
        "variant": "serve_bench",
        "epochs_per_s": best,
        "n": len(windows),
        "iters": _REQUESTS_PER_LEVEL,
        "bytes_per_epoch": _BYTES_PER_EPOCH,
        "wall_s": round(time.perf_counter() - t0, 3),
        "n_markers_per_file": n_markers,
        "n_files": n_files,
        "platform": jax.devices()[0].platform,
        "serve": {
            "sweep": sweep,
            "parity": parity,
            "shed_probe": shed_probe,
            "chaos": chaos_block,
            "service": stats,
        },
        "plan_cache": {
            "hits": pstats["hits"], "misses": pstats["misses"],
        },
        "compile_cache": compile_cache.active_cache_dir(),
        "feature_cache": feature_cache.stats(),
    }


def run_mega(n_markers: int, n_files: int) -> dict:
    """The serve_mega measurement: mega vs fused back-to-back in one
    process (see the module docstring)."""
    import numpy as np

    from eeg_dataanalysispackage_tpu.serve import (
        InferenceService, ServeConfig,
    )

    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="eeg_tpu_serve_mega_")
    (
        info, model, windows, _targets, resolutions, classifier,
        _batch_features, batch_predictions,
    ) = _prepare(tmp, n_markers, n_files)

    fused_svc = InferenceService(
        classifier, config=ServeConfig(), engine_rung="fused"
    )
    mega_svc = InferenceService(
        classifier, config=ServeConfig(), engine_rung="mega"
    )
    fused_svc.start()
    mega_svc.start()
    try:
        # 1. parity: the mega rung's served predictions vs the fused
        # twin's AND vs the batch pipeline's, element-wise
        mega_served = np.array([
            r.prediction
            for r in mega_svc.predict_all(windows, resolutions)
        ])
        fused_served = np.array([
            r.prediction
            for r in fused_svc.predict_all(windows, resolutions)
        ])
        parity = {
            "n": len(windows),
            "bit_identical": bool(
                np.array_equal(mega_served, fused_served)
            ),
            "vs_batch_bit_identical": bool(
                np.array_equal(mega_served, batch_predictions)
            ),
            "mismatches": int((mega_served != fused_served).sum()),
        }

        # 2. within-bucket bit-identity: one window's mega MARGIN is
        # byte-equal whether it rides alone or in a full batch (one
        # compiled program per bucket, row-independent compute)
        probe = windows[: min(8, len(windows))]
        _, margins_batch = mega_svc.engine.execute(probe, resolutions)
        solo = [
            mega_svc.engine.execute([w], resolutions)[1][0]
            for w in probe
        ]
        bucket_identical = bool(
            np.array_equal(np.asarray(solo), margins_batch)
        )

        # 3. the back-to-back sweep: fused then mega at EACH level —
        # temporal adjacency keeps this box's load swings out of the
        # per-level ratio
        sweep = []
        for c in _SWEEP_CONCURRENCY:
            fused_level = _drive_level(
                fused_svc, windows, resolutions, c,
                _REQUESTS_PER_LEVEL, deadline_s=5.0,
            )
            mega_level = _drive_level(
                mega_svc, windows, resolutions, c,
                _REQUESTS_PER_LEVEL, deadline_s=5.0,
            )
            sweep.append({
                "concurrency": c,
                "fused": fused_level,
                "mega": mega_level,
                "preds_speedup": round(
                    mega_level["preds_per_s"]
                    / max(1e-9, fused_level["preds_per_s"]), 3
                ),
                "p99_ratio": round(
                    mega_level["p99_ms"]
                    / max(1e-9, fused_level["p99_ms"]), 3
                ),
            })
    finally:
        mega_drained = mega_svc.stop(drain=True)
        fused_svc.stop(drain=True)

    # 4. the int8 precision rung's warmup gate decision, recorded on
    # the same line (the smoke gate reads it here)
    int8_svc = InferenceService(
        classifier, config=ServeConfig(max_batch=16),
        precision="int8",
    )
    int8_svc.start()
    int8_svc.predict_window(windows[0], resolutions)
    int8_svc.stop(drain=True)

    import jax

    from eeg_dataanalysispackage_tpu.ops import serve_mega as mega_mod

    best_mega = max(level["mega"]["preds_per_s"] for level in sweep)
    return {
        "variant": "serve_mega",
        "epochs_per_s": best_mega,
        "n": len(windows),
        "iters": _REQUESTS_PER_LEVEL,
        "bytes_per_epoch": _BYTES_PER_EPOCH,
        "wall_s": round(time.perf_counter() - t0, 3),
        "n_markers_per_file": n_markers,
        "n_files": n_files,
        "platform": jax.devices()[0].platform,
        "serve": {
            "mega_vs_fused": {
                "sweep": sweep,
                "parity": parity,
                "bucket_identical": bucket_identical,
                "mega_rung": mega_svc.engine.rung,
                "fused_rung": fused_svc.engine.rung,
                "drained_cleanly": mega_drained,
            },
            "engine": {
                "mega": mega_svc.engine.mega_record,
                "accelerator_decision": mega_mod.accelerator_decision(),
            },
            "int8_gate": int8_svc.engine.precision_record,
        },
    }


def run_lifecycle(n_markers: int, n_files: int, report_dir=None) -> dict:
    """The serve_lifecycle measurement: the model lifecycle manager
    (serve/lifecycle.py) under load.

    Four pieces on one line:

    - **no-swap byte-identity** — a lifecycle-enabled service with
      ``swap_gate=off`` serves the session (feedback fed for every
      window) and its predictions must be bit-identical to the batch
      pipeline's: staging + shadow-scoring a candidate provably never
      touches the live path;
    - **swap under load** — a permissive-gate service is swept at each
      concurrency level twice, back-to-back: a steady-state pass, then
      a pass with a feedback feeder thread running so partial-fit
      chunks, gate checks, and (behind the gate) a promotion land
      DURING the traffic; per-level p50/p99 + preds/sec pairs and the
      across-promotion p99 ratio are the line's headline, with
      swaps/rollbacks/drift counted from the lifecycle block;
    - **promoted==batch parity** — after the promotion, the session is
      re-served and compared element-wise against a fresh classifier
      loaded from the promoted checkpoint (``promoted.npz``) run over
      the batch features;
    - **chaos soak** — with ``serve.swap``/``serve.adapt`` firing at
      p=0.2, every submitted request still resolves, the drain
      completes, and a failed swap leaves the live model untouched
      (swap_failures counted; the live-model identity is asserted
      in-process and recorded).
    """
    import numpy as np

    from eeg_dataanalysispackage_tpu.models import (
        registry as clf_registry,
    )
    from eeg_dataanalysispackage_tpu.obs import chaos
    from eeg_dataanalysispackage_tpu.pipeline import builder
    from eeg_dataanalysispackage_tpu.serve import (
        InferenceService, LifecycleConfig, ServeConfig,
    )

    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="eeg_tpu_serve_lifecycle_")
    (
        info, model, windows, targets, resolutions, classifier,
        batch_features, batch_predictions,
    ) = _prepare(tmp, n_markers, n_files)

    # 1. no-swap byte-identity: gate off, full feedback, predictions
    # bit-identical to batch
    no_swap = InferenceService.from_saved(
        "logreg", model,
        lifecycle=LifecycleConfig(
            adapt_batch=16, adapt_iters=10, drift_window=32,
            gate_mode="off", gate_ratio=None,
        ),
    )
    no_swap.start()
    try:
        results = no_swap.predict_all(windows, resolutions)
        for w, y in zip(windows, targets):
            no_swap.feedback(w, resolutions, float(y))
        no_swap.lifecycle.flush(timeout_s=60.0)
    finally:
        no_swap.stop(drain=True)
    no_swap_served = np.array([r.prediction for r in results])
    no_swap_block = no_swap.stats_block()["lifecycle"]
    no_swap_parity = {
        "n": len(windows),
        "bit_identical": bool(
            np.array_equal(no_swap_served, batch_predictions)
        ),
        "swaps": no_swap_block["swaps"],
        "batches": no_swap_block["feedback"]["batches"],
    }

    # 2. swap under load: steady-state level, then the same level with
    # the feedback feeder (and therefore a promotion) racing it
    ckpt = os.path.join(tmp, "lifecycle")
    svc = InferenceService.from_saved(
        "logreg", model,
        lifecycle=LifecycleConfig(
            adapt_batch=16, adapt_iters=10, drift_window=32,
            gate_mode="cost", gate_ratio=100.0, checkpoint_dir=ckpt,
        ),
    )
    svc.start()
    stop_feeder = threading.Event()

    def feeder():
        i = 0
        while not stop_feeder.is_set():
            try:
                svc.feedback(
                    windows[i % len(windows)], resolutions,
                    float(targets[i % len(windows)]),
                )
            except Exception:
                return
            i += 1
            if i % 64 == 0:
                time.sleep(0.001)

    sweep = []
    try:
        for c in _SWEEP_CONCURRENCY:
            steady = _drive_level(
                svc, windows, resolutions, c, _REQUESTS_PER_LEVEL,
                deadline_s=5.0,
            )
            swaps_before = svc.lifecycle.block()["swaps"]
            feeder_thread = threading.Thread(target=feeder, daemon=True)
            stop_feeder.clear()
            feeder_thread.start()
            under_adapt = _drive_level(
                svc, windows, resolutions, c, _REQUESTS_PER_LEVEL,
                deadline_s=5.0,
            )
            stop_feeder.set()
            feeder_thread.join(timeout=10.0)
            svc.lifecycle.flush(timeout_s=30.0)
            sweep.append({
                "concurrency": c,
                "steady": steady,
                "under_adapt": under_adapt,
                "swaps_during": (
                    svc.lifecycle.block()["swaps"] - swaps_before
                ),
                "p99_ratio": round(
                    under_adapt["p99_ms"]
                    / max(1e-9, steady["p99_ms"]), 3
                ),
                "preds_ratio": round(
                    under_adapt["preds_per_s"]
                    / max(1e-9, steady["preds_per_s"]), 3
                ),
            })
        lifecycle_block = svc.lifecycle.block()

        # 3. promoted==batch parity: re-serve through the (promoted)
        # service and compare against the promoted checkpoint's batch
        # predictions
        promoted_parity = {"swapped": lifecycle_block["swaps"] >= 1}
        if lifecycle_block["swaps"] >= 1:
            served = np.array([
                r.prediction
                for r in svc.predict_all(windows, resolutions)
            ])
            promoted = clf_registry.create("logreg")
            promoted.load(lifecycle_block["promoted_path"])
            # the batch feature matrix was computed once in _prepare;
            # re-featurizing inside the timed child would bill device
            # ingest against the bench wall for no new information
            promoted_batch = promoted.predict(batch_features)
            promoted_parity.update({
                "n": len(windows),
                "bit_identical": bool(
                    np.array_equal(served, promoted_batch)
                ),
                "mismatches": int((served != promoted_batch).sum()),
            })
    finally:
        stop_feeder.set()
        svc.stop(drain=True)

    # 4. chaos soak on the lifecycle points: every request resolves,
    # a failed swap leaves the live model untouched
    soak = InferenceService.from_saved(
        "logreg", model,
        config=ServeConfig(max_attempts=4, retry_backoff_s=0.01),
        lifecycle=LifecycleConfig(
            adapt_batch=16, adapt_iters=10, drift_window=32,
            gate_mode="cost", gate_ratio=100.0,
        ),
    )
    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod

    outcomes = {
        "completed": 0, "shed": 0, "deadline": 0, "failed": 0,
        "unresolved": 0,
    }
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod

    with chaos.faults("serve.swap:p=0.2;serve.adapt:p=0.2;seed=13"):
        soak.start()
        futures = []
        for i in range(min(len(windows) * 2, 400)):
            w = windows[i % len(windows)]
            try:
                futures.append(soak.submit(
                    w, resolutions, deadline_s=5.0, block_s=5.0,
                    label=float(targets[i % len(windows)]),
                ))
            except batcher_mod.ShedError:
                # a shed IS a resolution (rejected with evidence at
                # the door) — counted, never a crashed variant
                outcomes["shed"] += 1
        for fut in futures:
            try:
                fut.result(timeout=20.0)
                outcomes["completed"] += 1
            except deadline_mod.DeadlineExceededError:
                outcomes["deadline"] += 1
            except TimeoutError:
                outcomes["unresolved"] += 1
            except Exception:
                outcomes["failed"] += 1
        soak.lifecycle.flush(timeout_s=30.0)
        soak_block = soak.lifecycle.block()
        drained = soak.stop(drain=True)

    # the failed-swap identity probe: with EVERY promotion attempt
    # chaos-failed, the live classifier OBJECT must survive untouched
    # and the candidate stay staged — measured directly, not inferred
    # from a soak where a successful swap legitimately changes the
    # model
    probe = InferenceService.from_saved(
        "logreg", model,
        lifecycle=LifecycleConfig(
            adapt_batch=16, adapt_iters=10, drift_window=32,
            gate_mode="cost", gate_ratio=100.0,
        ),
    )
    probe_live = probe.engine.classifier
    with chaos.faults("serve.swap:every@1"):
        probe.start()
        for i in range(len(windows)):
            probe.feedback(
                windows[i], resolutions, float(targets[i])
            )
        probe.lifecycle.flush(timeout_s=30.0)
        probe_block = probe.lifecycle.block()
        probe.stop(drain=True)
    live_untouched_ok = (
        probe_block["swap_failures"] >= 1
        and probe_block["swaps"] == 0
        and probe.engine.classifier is probe_live
    )
    chaos_block = {
        **outcomes,
        "drained_cleanly": drained,
        "chaos_clean": outcomes["unresolved"] == 0 and drained,
        "swaps": soak_block["swaps"],
        "swap_failures": soak_block["swap_failures"],
        "adapt_failures": soak_block["feedback"]["failures"],
        "probe_swap_failures": probe_block["swap_failures"],
        "live_untouched_on_failed_swap": bool(live_untouched_ok),
    }

    # 5. optional run_report.json with the lifecycle block, via the
    # real serve=true&adapt=true pipeline mode (the smoke gate
    # cross-checks it)
    if report_dir:
        builder.PipelineBuilder(
            f"info_file={info}&fe=dwt-8-fused&serve=true"
            f"&load_clf=logreg&load_name={model}&adapt=true"
            f"&swap_gate=off&drift_window=32&report={report_dir}"
        ).execute()

    import jax

    best = max(
        level["under_adapt"]["preds_per_s"] for level in sweep
    )
    return {
        "variant": "serve_lifecycle",
        "epochs_per_s": best,
        "n": len(windows),
        "iters": _REQUESTS_PER_LEVEL,
        "bytes_per_epoch": _BYTES_PER_EPOCH,
        "wall_s": round(time.perf_counter() - t0, 3),
        "n_markers_per_file": n_markers,
        "n_files": n_files,
        "platform": jax.devices()[0].platform,
        "serve": {
            "sweep": sweep,
            "no_swap_parity": no_swap_parity,
            "promoted_parity": promoted_parity,
            "lifecycle": lifecycle_block,
            "chaos": chaos_block,
        },
    }


#: tenant counts swept by serve_multitenant (the 16-tenant level is
#: the one multiplex.accelerator_decision harvests from chip runs)
_TENANT_LEVELS = (1, 4, 16)


def _clone_tenants(model: str, n: int) -> dict:
    """N tenant models from one saved checkpoint: tenant 0 is the
    checkpoint verbatim; the rest are deterministically perturbed
    clones — genuinely distinct weights (a cross-tenant mixup would
    show as a parity break), zero extra training cost."""
    import numpy as np

    from eeg_dataanalysispackage_tpu.models import (
        registry as clf_registry,
    )

    tenants = {}
    for i in range(n):
        clf = clf_registry.create("logreg")
        clf.load(model)
        if i:
            r = np.random.default_rng(1000 + i)
            clf.weights = (
                clf.weights
                * (1.0 + 0.02 * r.standard_normal(clf.weights.shape))
            ).astype(np.float32)
            clf.intercept = float(
                clf.intercept + 0.01 * r.standard_normal()
            )
        tenants[f"t{i:02d}"] = clf
    return tenants


def run_multitenant(n_markers: int, n_files: int) -> dict:
    """The serve_multitenant measurement: one multiplexed engine vs
    the solo fleet it replaces, back-to-back per tenant level (see
    the module docstring)."""
    import numpy as np

    from eeg_dataanalysispackage_tpu.obs.report import (
        CompilationMonitor,
    )
    from eeg_dataanalysispackage_tpu.serve import (
        InferenceService, MultiplexedService, ServeConfig,
    )
    from eeg_dataanalysispackage_tpu.serve import multiplex
    from eeg_dataanalysispackage_tpu.serve.engine import ServingEngine

    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="eeg_tpu_serve_multitenant_")
    (
        _info, model, windows, _targets, resolutions, _classifier,
        _batch_features, _batch_predictions,
    ) = _prepare(tmp, n_markers, n_files)

    max_tenants = max(_TENANT_LEVELS)
    tenant_models = _clone_tenants(model, max_tenants)
    names = list(tenant_models)

    # ONE multiplexed service, built at 1 tenant and SCALED in place
    # to 16 — the add_tenant path is the measurement, not a per-level
    # rebuild. Warmup compiles are attributed separately from the
    # scaling compiles (the latter are the 0-recompile pin).
    with CompilationMonitor() as warm_mon:
        service = MultiplexedService(
            {names[0]: tenant_models[names[0]]},
            config=ServeConfig(),
        )
        service.engine.warmup()
    warmup = warm_mon.snapshot()
    counters_available = bool(warmup.get("available"))
    scaling_compiles = 0
    service.start()
    levels = []
    try:
        for n_tenants in _TENANT_LEVELS:
            with CompilationMonitor() as grow_mon:
                for name in names[len(service.tenants):n_tenants]:
                    service.add_tenant(name, tenant_models[name])
            grown = grow_mon.snapshot()
            if grown.get("available"):
                scaling_compiles += grown["compilations"]
            active = names[:n_tenants]

            multiplexed = _drive_level(
                service, windows, resolutions, 16,
                _REQUESTS_PER_LEVEL, deadline_s=5.0, tenants=active,
            )
            # the solo fleet over the SAME models, seconds later
            fleet = [
                InferenceService(
                    tenant_models[name], config=ServeConfig(),
                )
                for name in active
            ]
            for svc in fleet:
                svc.start()
            try:
                solo_fleet = _drive_fleet(
                    fleet, windows, resolutions, 16,
                    _REQUESTS_PER_LEVEL, deadline_s=5.0,
                )
            finally:
                for svc in fleet:
                    svc.stop(drain=True)
            levels.append({
                "tenants": n_tenants,
                "multiplexed": multiplexed,
                "solo_fleet": solo_fleet,
                "ratio": round(
                    multiplexed["preds_per_s"]
                    / max(1e-9, solo_fleet["preds_per_s"]), 3
                ),
            })

        # per-tenant parity at the full tenant level: every tenant's
        # rows out of a 16-way mixed stream vs that tenant's solo
        # engine, element-wise
        mix = [names[i % max_tenants] for i in range(len(windows))]
        served = np.array([
            r.prediction
            for r in service.predict_all(windows, resolutions, mix)
        ])
        mismatches = 0
        for name in names:
            solo = ServingEngine(tenant_models[name], capacity=64)
            solo.warmup()
            sp = np.concatenate([
                solo.execute(windows[i:i + 64], resolutions)[0]
                for i in range(0, len(windows), 64)
            ])
            rows = [i for i, t in enumerate(mix) if t == name]
            mismatches += int((served[rows] != sp[rows]).sum())
        parity = {
            "n": len(windows),
            "tenants": max_tenants,
            "bit_identical": mismatches == 0,
            "mismatches": mismatches,
        }

        # the hot-swap pin: rewrite one tenant's column and serve —
        # 0 compiles, and the swapped tenant serves the new model
        replacement = _clone_tenants(model, 2)[names[1]]
        with CompilationMonitor() as swap_mon:
            service.swap_tenant(names[0], replacement)
            swap_result = service.predict_window(
                windows[0], resolutions, tenant=names[0],
            )
        swapped = swap_mon.snapshot()
        swap_compiles = (
            swapped["compilations"] if swapped.get("available") else 0
        )
        swap_block = {
            "compiles": swap_compiles,
            "served_after_swap": swap_result.prediction in (0.0, 1.0),
            "generation": service.engine.tenant_info(
                names[0]
            )["generation"],
        }
        stats = service.stats_block()
    finally:
        drained = service.stop(drain=True)

    import jax

    per_engine_bytes = int(
        tenant_models[names[0]].weights.nbytes
    )
    best = max(
        level["multiplexed"]["preds_per_s"] for level in levels
    )
    return {
        "variant": "serve_multitenant",
        "epochs_per_s": best,
        "n": len(windows),
        "iters": _REQUESTS_PER_LEVEL,
        "bytes_per_epoch": _BYTES_PER_EPOCH,
        "wall_s": round(time.perf_counter() - t0, 3),
        "n_markers_per_file": n_markers,
        "n_files": n_files,
        "platform": jax.devices()[0].platform,
        "serve": {
            "multitenant": {
                "levels": levels,
                "parity": parity,
                "compiles": {
                    "available": counters_available,
                    "warmup": warmup.get("compilations"),
                    # 1 -> 16 tenants on the resident program: the
                    # 0-recompile scaling pin (one compile serves any
                    # tenant mix)
                    "scaling": scaling_compiles,
                    "scaling_zero_ok": (
                        not counters_available
                        or scaling_compiles == 0
                    ),
                },
                "swap": swap_block,
                "resident": {
                    # one stacked (d, 128) matrix, whatever N is...
                    "multiplexed_bytes": (
                        service.engine.resident_weight_bytes
                    ),
                    # ...vs one weight vector per fleet engine
                    "fleet_bytes_per_engine": per_engine_bytes,
                    "fleet_bytes_16": 16 * per_engine_bytes,
                },
                "rung": service.engine.rung,
                "drained_cleanly": drained,
                "service": stats,
                "accelerator_decision": (
                    multiplex.accelerator_decision()
                ),
            },
        },
    }


def run_multitenant_quant(n_markers: int, n_files: int) -> dict:
    """The serve_multitenant_quant measurement: 16 tenants through
    the packed int4 weight stack vs the same 16 through the f32
    multiplexed twin, back-to-back at concurrency 16 (see the module
    docstring)."""
    import numpy as np

    from eeg_dataanalysispackage_tpu.obs.report import (
        CompilationMonitor,
    )
    from eeg_dataanalysispackage_tpu.ops import quant
    from eeg_dataanalysispackage_tpu.serve import (
        MultiplexedService, ServeConfig,
    )

    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="eeg_tpu_serve_mt_quant_")
    (
        _info, model, windows, _targets, resolutions, _classifier,
        _batch_features, _batch_predictions,
    ) = _prepare(tmp, n_markers, n_files)

    n_tenants = max(_TENANT_LEVELS)
    tenant_models = _clone_tenants(model, n_tenants)
    names = list(tenant_models)

    with CompilationMonitor() as warm_mon:
        service = MultiplexedService(
            tenant_models, config=ServeConfig(),
            weights_precision="int4",
        )
        service.engine.warmup()
    warmup = warm_mon.snapshot()
    counters_available = bool(warmup.get("available"))
    weights_record = service.engine.weights_record

    twin = MultiplexedService(tenant_models, config=ServeConfig())
    twin.engine.warmup()

    service.start()
    twin.start()
    try:
        quant_level = _drive_level(
            service, windows, resolutions, 16, _REQUESTS_PER_LEVEL,
            deadline_s=5.0, tenants=names,
        )
        # the f32 multiplexed twin over the SAME models, seconds
        # later (temporal adjacency — this box's load swings 2-4x
        # between runs)
        f32_level = _drive_level(
            twin, windows, resolutions, 16, _REQUESTS_PER_LEVEL,
            deadline_s=5.0, tenants=names,
        )

        # per-tenant margin parity out of a 16-way mixed stream: the
        # quantized stack's margins vs the f32 twin's, element-wise,
        # pinned within the derived weights gate tolerance (the same
        # envelope the warmup gate enforced)
        mix = [names[i % n_tenants] for i in range(len(windows))]
        q_served = service.predict_all(windows, resolutions, mix)
        f_served = twin.predict_all(windows, resolutions, mix)
        q_margins = np.array([r.margin for r in q_served])
        f_margins = np.array([r.margin for r in f_served])
        tol = quant.weights_gate_tolerance(
            "int4", service.engine._w_host
        )
        margin_dev = float(np.max(np.abs(q_margins - f_margins)))
        pred_mismatches = int(sum(
            a.prediction != b.prediction
            for a, b in zip(q_served, f_served)
        ))
        parity = {
            "n": len(windows),
            "tenants": n_tenants,
            "max_abs_margin_dev": margin_dev,
            "tolerance": tol,
            "within_tolerance": margin_dev <= tol,
            "prediction_mismatches": pred_mismatches,
        }

        # the 0-compile admin pin ON THE LIVE QUANTIZED STACK: add,
        # swap, remove — the f32 host mirror stays master, the packed
        # matrix + scales are requantized and republished, and the
        # resident program never recompiles
        replacement = _clone_tenants(model, 2)[names[1]]
        with CompilationMonitor() as admin_mon:
            service.add_tenant("t_extra", replacement)
            service.swap_tenant(names[0], replacement)
            service.remove_tenant("t_extra")
            admin_result = service.predict_window(
                windows[0], resolutions, tenant=names[0],
            )
        admined = admin_mon.snapshot()
        admin_compiles = (
            admined["compilations"] if admined.get("available") else 0
        )
        admin_block = {
            "compiles": admin_compiles,
            "compiles_zero_ok": (
                not counters_available or admin_compiles == 0
            ),
            "served_after_admin": admin_result.prediction in (
                0.0, 1.0
            ),
            "still_quantized": (
                service.engine.weights_precision == "int4"
            ),
        }
        stats = service.stats_block()
    finally:
        drained = service.stop(drain=True)
        twin_drained = twin.stop(drain=True)

    import jax

    f32_bytes = twin.engine.resident_weight_bytes
    quant_bytes = service.engine.resident_weight_bytes
    return {
        "variant": "serve_multitenant_quant",
        "epochs_per_s": quant_level["preds_per_s"],
        "n": len(windows),
        "iters": _REQUESTS_PER_LEVEL,
        "bytes_per_epoch": _BYTES_PER_EPOCH,
        "wall_s": round(time.perf_counter() - t0, 3),
        "n_markers_per_file": n_markers,
        "n_files": n_files,
        "platform": jax.devices()[0].platform,
        "serve": {
            "multitenant_quant": {
                "tenants": n_tenants,
                "weights_precision": (
                    service.engine.weights_precision
                ),
                "weights": weights_record,
                "quant": quant_level,
                "f32": f32_level,
                "ratio": round(
                    quant_level["preds_per_s"]
                    / max(1e-9, f32_level["preds_per_s"]), 3
                ),
                "parity": parity,
                "compiles": {
                    "available": counters_available,
                    "warmup": warmup.get("compilations"),
                },
                "admin": admin_block,
                "resident": {
                    "f32_bytes": f32_bytes,
                    "quant_bytes": quant_bytes,
                    # the VMEM-residency win the packed stack buys:
                    # >=4x is the acceptance bar (int4 measures
                    # ~6.9x — packed nibbles + per-lane f32 scales)
                    "reduction": round(
                        f32_bytes / max(1, quant_bytes), 3
                    ),
                },
                "rung": service.engine.rung,
                "drained_cleanly": drained and twin_drained,
                "service": stats,
                "accelerator_decision": (
                    quant.accelerator_decision()
                ),
            },
        },
    }


def main(argv) -> dict:
    variant = argv[0] if argv else "serve_bench"
    if variant not in (
        "serve_bench", "serve_mega", "serve_lifecycle",
        "serve_multitenant", "serve_multitenant_quant",
    ):
        raise SystemExit(f"unknown variant {variant!r}")
    n_markers = int(argv[1]) if len(argv) > 1 else 400
    n_files = int(argv[2]) if len(argv) > 2 else 2
    report_dir = None
    for arg in argv[3:]:
        if arg.startswith("--report-dir="):
            report_dir = arg.split("=", 1)[1]
        else:
            raise SystemExit(f"unknown argument {arg!r}")
    if variant == "serve_mega":
        return run_mega(n_markers, n_files)
    if variant == "serve_lifecycle":
        return run_lifecycle(n_markers, n_files, report_dir=report_dir)
    if variant == "serve_multitenant":
        return run_multitenant(n_markers, n_files)
    if variant == "serve_multitenant_quant":
        return run_multitenant_quant(n_markers, n_files)
    return run(n_markers, n_files, report_dir=report_dir)


if __name__ == "__main__":
    from eeg_dataanalysispackage_tpu.utils import strict_json

    # strict JSON at the source: a degenerate metric (NaN percentile,
    # an empty sweep) must serialize as null, never a bare NaN token
    print(strict_json.dumps(main(sys.argv[1:])))
