"""Whole-pipeline end-to-end benchmark child (the pipeline_e2e family).

Usage: python tools/pipeline_bench.py <variant> <n_markers> <n_files>
           [--data-dir D] [--cache-dir D]

Variants:
  pipeline_e2e_cold     one full query run — parse + fused featurize +
                        train + test — against a FRESH feature cache
                        (every entry a miss, stored for later runs)
  pipeline_e2e_warm     the same query against a cache populated by a
                        separate child process, so the timed run's
                        process state (jit caches, imports) matches the
                        cold child's exactly and the measured delta is
                        the feature cache alone: ingest, staging, and
                        the device featurizer never run on a hit
  pipeline_e2e_fanout5  classifiers=logreg,svm,dt,rf,nn against a
                        fresh cache: one ingest+featurization pass
                        amortized over five classifiers (vs five full
                        reference-shaped runs)
  pipeline_e2e_overlap  the cold query with overlap=true: recording
                        K+1's decode+featurize runs on the staging
                        producer thread while the consumer collects
                        recording K (io/staging.prefetch stage_fn).
                        report_sha256 equality against the cold line
                        is the bit-identical-statistics contract
  pipeline_e2e_bf16     the cold query with precision=bf16: the DWT
                        matmul in bfloat16 behind the per-run f32
                        reference gate — the line's ``precision``
                        block records the gate decision (used=bf16
                        within tolerance, or the auto-disable) plus
                        the gate's own double-featurize cost
                        (``gate_seconds`` — so the line separates
                        gate overhead from steady-state throughput)
  pipeline_e2e_int8     the cold query with precision=int8: finished
                        f32 feature rows quantized per subband
                        (ops/decode_ingest.quantize_dequantize_int8)
                        behind the same per-run gate machinery — the
                        rung below bf16, same ``precision`` block
                        attribution
  pipeline_e2e_int4     the cold query with precision=int4: finished
                        f32 feature rows quantized per (channel,
                        subband) group, two nibbles per byte
                        (ops/quant.quantize_dequantize_int4) behind
                        the same per-run gate machinery — the bottom
                        rung, widest envelope, same ``precision``
                        block attribution and its own int4 feature
                        cache class
  population_vmap       a 16-member population (cv=4 folds x a 2x2
                        lr/reg grid, models/population.py) trained
                        as ONE vmapped program — the compile- and
                        dispatch-amortized training engine
  population_looped     the identical member set trained sequentially
                        (population_mode=looped): the per-member
                        dispatch baseline the vmapped engine is
                        measured against. Identical per-member
                        statistics (report_sha256 equality) are the
                        parity contract; the ``stages.train`` delta is
                        the engine's win
  population_sharded    the identical member set with the MEMBER axis
                        sharded over a device mesh (devices=N through
                        parallel/population.train_linear_population_
                        sharded). On the CPU fallback the child forces
                        an 8-device host platform (--devices, default
                        8) so the real multi-device program runs; the
                        line's ``mesh`` block records the rung/shape/
                        per-device member counts and ``members_per_s``
                        the rate — population_vmap from the same bench
                        run is its same-machine single-device twin,
                        and report_sha256 equality across the pair is
                        the sharded==vmap statistics contract
  population_multiproc  the same member set as a 2-PROCESS loopback
                        pod (processes=2 over a gloo coordinator;
                        each process ingests its disjoint recording
                        half, the member axis spans both processes'
                        virtual devices) vs its single-process twin
                        in an equally fresh process — the multiproc
                        block carries members/sec for both, the
                        statistics-parity sha verdict, the pod mesh
                        block, and the degraded-coordinator run
                        (unreachable coordinator -> single-host rung,
                        parity held). On one box the ratio measures
                        harness overhead; on a pod slice the staged
                        chip rows are the ~1/N evidence
  seizure_e2e           the continuous-EEG seizure workload
                        (task=seizure, docs/workloads.md): sliding-
                        window epoching over a synthetic annotated
                        continuous session, subband features, and a
                        COST-SWEPT population — sweep=cost_fn:1,8
                        trains the unit-weight member and the
                        8x-positive-weight member in one vmapped
                        program. The line records windows/sec, the
                        class ratio, and per-member recall/expected-
                        cost at the swept costs; the smoke gate
                        compares the weighted member against its
                        unweighted twin from the SAME line
  scheduler_multi       the multi-tenant plan executor
                        (scheduler/executor.py): N=4 plans sharing one
                        synthetic session run SEQUENTIALLY (one worker,
                        fresh cache) and then CONCURRENTLY (4 workers,
                        fresh cache) after a jit warmup — the line
                        records the wall-clock pair and ratio
                        (``concurrent_speedup``), per-plan feature-
                        cache hit attribution from each plan's
                        ISOLATED metrics scope, the single-flight
                        store count (exactly one rebuild kept under
                        concurrency), per-plan run_report.json
                        integrity, and a kill-and-resume scenario
                        (a SIGKILLed child of this script + journal
                        recovery, statistics pinned identical to
                        uninterrupted twins)
  scheduler_suicide     internal: the kill-and-resume child — submits
                        1 fast + 2 slow plans against --journal-dir,
                        lets the first complete, SIGKILLs itself
  plan_service          the networked plan service (gateway/ over
                        scheduler/executor.py): a shared-prefix tenant
                        pair submitted over loopback HTTP computes its
                        ingest+featurize prefix exactly once (one
                        feature-cache store, the follower a dedup hit)
                        with BOTH plans' statistics byte-identical to
                        their solo dedup=false twins; an idempotency-
                        keyed re-submit of the completed leader
                        replays the original plan id (HTTP 200, no
                        re-execution); and a many-client soak — N
                        client threads POSTing clean and chaos-bearing
                        (faults=scheduler.plan) plans concurrently —
                        records submits/sec at the front door, the
                        dedup hit ratio, and the isolation verdict
                        (every plan resolves; every clean statistics
                        byte-equal to solo)
  populate              internal: run the cold query to fill
                        --cache-dir, print nothing (the warm variant's
                        helper child)

Everything is hermetic: the input session is fabricated by
tests/_synthetic.py (INT_16 BrainVision triplets + info.txt) in a temp
dir, so the family runs anywhere — including ``cpu_fallback``, where
the numbers are still meaningful because the wins are host-side
(parallel parse, skipped featurization, amortized ingest).

The persistent XLA compile cache is disabled in this process (and its
populate child): the e2e family measures honest cold compiles, not
whatever a previous bench run left serialized. Prints one JSON line in
the driver-facing ingest_bench schema (epochs_per_s / bytes_per_epoch
/ plan_cache / compile_cache) plus ``wall_s``, ``feature_cache``
hit/miss attribution, and a ``report_sha256`` over the
ClassificationStatistics text so parity across cold/warm runs is
checkable from the artifact alone.
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

# honest cold compiles (see module docstring); must precede jax import
os.environ["EEG_TPU_NO_COMPILE_CACHE"] = "1"

#: the bytes each epoch's window reads from the int16 stream at the
#: synthetic generator's default 1000-sample marker stride — the same
#: stream-byte model the fused ingest variants bill.
_MARKER_STRIDE = 1000
_BYTES_PER_EPOCH = 3 * _MARKER_STRIDE * 2

#: config union: every classifier picks the keys it knows, so one
#: query string configures the whole fan-out (small/fast settings —
#: the family measures pipeline amortization, not model quality)
_CONFIG = (
    "&config_num_iterations=20&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0&config_reg_param=0.01"
    "&config_max_bins=16&config_impurity=gini&config_max_depth=4"
    "&config_min_instances_per_node=1&config_num_trees=5"
    "&config_feature_subset=auto"
    "&config_seed=1&config_learning_rate=0.1&config_momentum=0.9"
    "&config_weight_init=xavier&config_updater=nesterovs"
    "&config_optimization_algo=stochastic_gradient_descent"
    "&config_pretrain=false&config_backprop=true"
    "&config_loss_function=xent"
    "&config_layer1_layer_type=dense&config_layer1_n_out=8"
    "&config_layer1_drop_out=0.0&config_layer1_activation_function=relu"
    "&config_layer2_layer_type=output&config_layer2_n_out=2"
    "&config_layer2_drop_out=0.0"
    "&config_layer2_activation_function=softmax"
)

_FANOUT_CLASSIFIERS = "logreg,svm,dt,rf,nn"

#: the population bench family's member axes: cv=4 folds x a 2x2
#: lr/reg grid = 16 members (the ISSUE-5 acceptance shape). Every
#: member is a genuinely DISTINCT training trajectory — a seeds= axis
#: would be inert here (full-batch zero-init SGD's seed only keys the
#: minibatch sampler; review finding), and a live minibatch axis
#: would make per-member Bernoulli sampling dominate the measured
#: stage. The feature cache is off (cache=false) so both modes pay
#: the identical ingest+featurize cost and the train-stage delta
#: isolates the engine; iterations are raised so member training, not
#: parse, dominates the measured stage.
_POPULATION_AXES = "cv=4&sweep=lr:1.0,0.5;reg:0.0,0.01&cache=false"
_POPULATION_ITERS = 6000
_POPULATION_FRACTION = 1.0

#: the seizure_e2e family's fixed geometry: for this variant n_markers
#: means SAMPLES PER FILE (a continuous recording has no markers) and
#: n_files the recording count. sweep=cost_fn:1,8 trains BOTH the
#: unit-weight member (the cost-blind baseline) and the
#: 8x-positive-weight member in one vmapped program; expected_cost is
#: evaluated for every member at the run's cost_fp=1/cost_fn=8 (a
#: missed seizure bills 8x a false alarm), so the pair is directly
#: comparable from one line.
_SEIZURE_FE = "dwt-4:level=4:stats=energy,std"
_SEIZURE_WINDOW = 512
_SEIZURE_STRIDE = 256
_SEIZURE_COST_FN = 8.0
_SEIZURE_ITERS = 200


def build_seizure_query(info: str) -> str:
    return (
        f"info_file={info}&task=seizure&fe={_SEIZURE_FE}"
        f"&window={_SEIZURE_WINDOW}&stride={_SEIZURE_STRIDE}"
        f"&train_clf=logreg&cache=false"
        f"&sweep=cost_fn:1,{_SEIZURE_COST_FN:g}"
        f"&config_num_iterations={_SEIZURE_ITERS}&config_step_size=1.0"
        f"&config_mini_batch_fraction=1.0"
        f"&cost_fp=1&cost_fn={_SEIZURE_COST_FN:g}"
    )


def write_seizure_session(directory: str, n_samples: int,
                          n_files: int) -> str:
    import _synthetic

    return _synthetic.write_seizure_session(
        directory, n_files=n_files, n_samples=n_samples
    )

#: scratch dir this invocation created itself (cleaned on exit)
_OWNED_TMP = None


def write_session(directory: str, n_markers: int, n_files: int) -> str:
    """Fabricate an ``n_files``-recording session; returns info.txt."""
    import _synthetic

    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        guessed = 2 + (i % 7)
        _synthetic.write_recording(
            directory,
            name=name,
            n_markers=n_markers,
            guessed=guessed,
            seed=i,
            marker_stride=_MARKER_STRIDE,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(directory, "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


def build_query(info: str, fanout: bool, train_clf: str = "logreg",
                extra: str = "", fe: str = "dwt-8-fused") -> str:
    classifier = (
        f"classifiers={_FANOUT_CLASSIFIERS}"
        if fanout
        else f"train_clf={train_clf}"
    )
    return f"info_file={info}&fe={fe}&{classifier}{_CONFIG}{extra}"


def _einsum_probe_eps(n: int = 8192, iters: int = 3) -> float:
    """The einsum-headline probe, run in-process immediately after
    the timed cold query: the machine-speed denominator for the
    plateau comparison. Two requirements, both load-bearing:

    - temporal adjacency — this box's load swings 2-4x between bench
      variants, so normalizing by an einsum measured 20 minutes
      earlier re-imports exactly the noise normalization removes;
    - IDENTICAL loop semantics to the committed artifacts' einsum
      line (tools/ingest_bench.run: the jitted scan with the
      anti-CSE ``x + i`` perturbation, whose full-width copy is part
      of that number) — a bare-extractor timing runs ~4x faster and
      would make the pr5 ratio meaningless. So this literally calls
      ingest_bench.run("einsum").
    """
    import importlib.util as iu

    spec = iu.spec_from_file_location(
        "ingest_bench",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "ingest_bench.py"),
    )
    ib = iu.module_from_spec(spec)
    spec.loader.exec_module(ib)
    return float(ib.run("einsum", n, iters)["epochs_per_s"])


def plateau_block(eps_now: float) -> dict:
    """The committed BENCH_pr5 plateau comparison, embedded on the
    pipeline_e2e_cold line so the 'cold number moved' acceptance is
    auditable from BENCH_pr8.json alone. Raw eps across artifacts
    mixes machine state into the comparison (this box's load swings
    2-4x between runs), so the block also carries the
    machine-normalized form: cold eps divided by an einsum probe run
    ADJACENT to the cold query, against the same ratio from the
    committed artifact (tools/e2e_smoke.py gates the same form)."""
    path = os.path.join(_REPO, "BENCH_pr5.json")
    try:
        with open(path) as f:
            rec = json.loads(f.read().strip().splitlines()[-1])
        variants = rec.get("variants", {})
        pr5_cold = variants.get("pipeline_e2e_cold", {}).get(
            "epochs_per_s"
        )
        pr5_einsum = variants.get("einsum", {}).get("epochs_per_s")
    except (OSError, ValueError):
        return {}
    if not pr5_cold:
        return {}
    block = {
        "pr5_cold_eps": pr5_cold,
        "pr5_einsum_eps": pr5_einsum,
        "cold_eps": round(eps_now, 1),
        "vs_pr5_cold": round(eps_now / pr5_cold, 3),
    }
    if pr5_einsum:
        probe = _einsum_probe_eps()
        ratio_now = eps_now / probe
        ratio_pr5 = pr5_cold / pr5_einsum
        block.update({
            "einsum_probe_eps": round(probe, 1),
            "normalized_ratio": round(ratio_now, 5),
            "pr5_normalized_ratio": round(ratio_pr5, 5),
            "beats_pr5_plateau_normalized": bool(
                ratio_now > ratio_pr5
            ),
        })
    return block


def build_population_query(info: str, mode: str,
                           devices: int = 0) -> str:
    """The population family's query: identical member set, only the
    training engine differs (population_mode=vmap | looped;
    ``devices`` > 0 adds the mesh axis — the sharded engine)."""
    return (
        f"info_file={info}&fe=dwt-8-fused&train_clf=logreg"
        f"&{_POPULATION_AXES}&population_mode={mode}"
        + (f"&devices={devices}" if devices else "")
        + f"&config_num_iterations={_POPULATION_ITERS}"
        "&config_step_size=1.0"
        f"&config_mini_batch_fraction={_POPULATION_FRACTION}"
    )


#: the scheduler_multi member plans: four tenants over ONE session —
#: distinct classifier configs (so the executor genuinely multi-
#: tenants) that all share the same fused feature build through the
#: content-addressed cache + its single-flight guard. Training is
#: deliberately HEAVY (raised iteration count): the shared feature
#: build is serialized by design (single-flight — one rebuild kept),
#: so the concurrency dividend the variant measures is the per-plan
#: TRAIN stages overlapping (XLA CPU executions release the GIL);
#: trivially-light plans would measure executor overhead + noise.
_SCHEDULER_ITERS = 4000
_SCHEDULER_PLANS = (
    ("logreg", "&config_step_size=1.0"),
    ("svm", "&config_reg_param=0.01"),
    ("logreg", "&config_step_size=0.5"),
    ("svm", "&config_reg_param=0.1"),
)


def scheduler_queries(info: str):
    # dedup=false: this variant measures the feature cache's
    # single-flight seam and the executor's train-stage concurrency —
    # prefix dedup (the plan_service variant's subject) sits above
    # both and would (correctly) let every plan skip them
    return [
        build_query(
            info, fanout=False, train_clf=clf,
            extra=extra + f"&config_num_iterations={_SCHEDULER_ITERS}"
            "&dedup=false",
        )
        for clf, extra in _SCHEDULER_PLANS
    ]


def scheduler_suicide_queries(info: str):
    """The kill-and-resume trio: one fast plan that COMPLETES before
    the SIGKILL, two slow ones (fresh compile at a big static
    iteration count) the kill provably interrupts. Host fe= path: no
    feature cache in play, so the resumed twins are a pure
    determinism pin."""
    qa = build_query(info, fanout=False, fe="dwt-8")
    slow = "&config_num_iterations=150000"
    qb = build_query(
        info, fanout=False, fe="dwt-8",
        extra=slow + "&config_step_size=0.5",
    )
    qc = build_query(
        info, fanout=False, fe="dwt-8",
        extra=slow + "&config_step_size=0.25",
    )
    return qa, qb, qc


def run_scheduler_multi(info: str, scratch: str) -> dict:
    """The scheduler_multi measurement: N plans sequential vs the same
    N concurrent (each phase against its own FRESH feature cache, both
    after a jit warmup), per-plan isolated cache attribution, the
    single-flight store pin, per-plan report integrity, and the
    kill-and-resume scenario."""
    import hashlib as _hashlib
    import signal as _signal

    from eeg_dataanalysispackage_tpu import obs
    from eeg_dataanalysispackage_tpu.pipeline import builder as _builder
    from eeg_dataanalysispackage_tpu.scheduler import PlanExecutor

    queries = scheduler_queries(info)
    report_root = os.path.join(scratch, "scheduler_reports")

    # jit warmup OUTSIDE both timed phases (cache=false: full builds,
    # so the fused featurizer AND both classifier programs compile
    # now, not inside whichever phase runs first)
    for q in (queries[0], queries[1]):
        run_query(q + "&cache=false")

    phases = {}
    for phase, workers in (("sequential", 1), ("concurrent", 4)):
        os.environ["EEG_TPU_FEATURE_CACHE_DIR"] = os.path.join(
            scratch, f"fc_{phase}"
        )
        before = obs.metrics.snapshot()["counters"]
        start = time.perf_counter()
        with PlanExecutor(
            max_concurrent=workers,
            report_root=os.path.join(report_root, phase),
        ) as ex:
            handles = [ex.submit(q) for q in queries]
            results = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - start
        after = obs.metrics.snapshot()["counters"]

        def _delta(name):
            return int(after.get(name, 0.0) - before.get(name, 0.0))

        per_plan = {}
        for (clf, extra), r in zip(_SCHEDULER_PLANS, results):
            counters = r.builder.run_metrics.snapshot()["counters"]
            per_plan[r.plan_id] = {
                "classifier": clf + extra,
                "feature_cache": {
                    "hits": int(counters.get("feature_cache.hit", 0)),
                    "misses": int(
                        counters.get("feature_cache.miss", 0)
                    ),
                },
                "statistics_sha256": _hashlib.sha256(
                    str(r.statistics).encode()
                ).hexdigest(),
            }
        reports_ok = True
        for r in results:
            path = os.path.join(
                report_root, phase, r.plan_id, "run_report.json"
            )
            try:
                with open(path) as f:
                    rep = json.load(f)
                reports_ok = reports_ok and (
                    rep["plan_id"] == r.plan_id
                    and rep["statistics_sha256"]
                    == per_plan[r.plan_id]["statistics_sha256"]
                    and rep["outcome"] == "ok"
                )
            except (OSError, ValueError, KeyError):
                reports_ok = False
        phases[phase] = {
            "wall_s": round(wall, 3),
            "epochs": _delta("pipeline.epochs_loaded"),
            "stores": _delta("feature_cache.store"),
            "single_flight_waits": _delta(
                "feature_cache.single_flight_wait"
            ),
            "per_plan": per_plan,
            "reports_ok": reports_ok,
            "statistics_sha256": _hashlib.sha256(
                "".join(sorted(
                    v["statistics_sha256"] for v in per_plan.values()
                )).encode()
            ).hexdigest(),
        }

    # kill-and-resume: a SIGKILLed child of this script leaves 1
    # completed + 2 unfinished journal records; recovery resumes the
    # unfinished pair to statistics identical to uninterrupted twins
    journal_dir = os.path.join(scratch, "journal")
    proc = subprocess.run(
        [
            sys.executable, os.path.abspath(__file__),
            "scheduler_suicide", "0", "0",
            f"--data-dir={os.path.dirname(info)}",
            # scratch-rooted: the child SIGKILLs itself by design, so
            # its own cleanup never runs — without an explicit
            # cache dir it would mkdtemp an _OWNED_TMP and leak it
            # every run
            f"--cache-dir={os.path.join(scratch, 'suicide_cache')}",
            f"--journal-dir={journal_dir}",
        ],
        capture_output=True, text=True,
    )
    killed = proc.returncode == -_signal.SIGKILL
    ex = PlanExecutor(max_concurrent=2, journal_dir=journal_dir)
    recovery = ex.recover()
    resumed = [
        (h.query, h.result(timeout=600))
        for h in recovery["resumed"]
    ]
    ex.close()
    twin_queries = {q for q, _ in resumed} | {
        e["query"] for e in recovery["completed"]
    }
    twins = {
        q: str(_builder.PipelineBuilder(q).execute())
        for q in twin_queries
    }
    identical = all(
        str(r.statistics) == twins[q] for q, r in resumed
    ) and all(
        e["statistics"] == twins[e["query"]]
        for e in recovery["completed"]
    )
    crash_block = {
        "killed": killed,
        "completed_kept": len(recovery["completed"]),
        "resumed": len(resumed),
        "identical": bool(identical and resumed),
    }

    seq, conc = phases["sequential"], phases["concurrent"]
    return {
        "wall_s": conc["wall_s"],
        "epochs": conc["epochs"],
        "scheduler": {
            "plans": len(queries),
            "wall_sequential_s": seq["wall_s"],
            "wall_concurrent_s": conc["wall_s"],
            "concurrent_speedup": round(
                seq["wall_s"] / conc["wall_s"], 3
            ) if conc["wall_s"] > 0 else 0.0,
            "parity_sequential_vs_concurrent": (
                seq["statistics_sha256"] == conc["statistics_sha256"]
            ),
            "sequential": {
                k: seq[k] for k in (
                    "wall_s", "stores", "single_flight_waits",
                    "per_plan", "reports_ok",
                )
            },
            "concurrent": {
                k: conc[k] for k in (
                    "wall_s", "stores", "single_flight_waits",
                    "per_plan", "reports_ok",
                )
            },
            "crash_recovery": crash_block,
        },
    }


#: the plan_service tenant pair: identical ingest+featurize prefix
#: (same session, same fused fe=), distinct classifier suffixes — the
#: common-subplan case the dedup registry exists for
_PLAN_SERVICE_TENANTS = (
    ("logreg", ""),
    ("svm", "&config_reg_param=0.1"),
)
#: soak shape: clients x plans-per-client, every other client
#: chaos-bearing (faults=scheduler.plan:p=0.3 — absorbed by executor
#: retries inside that plan's own fault domain)
_PLAN_SERVICE_CLIENTS = 6
_PLAN_SERVICE_PLANS_PER_CLIENT = 3
_PLAN_SERVICE_SOAK_ATTEMPTS = 8

#: fleet shape (gateway_fleet): real replica processes over ONE shared
#: journal; quick plans spread over the survivors plus ONE heavy plan
#: on the victim. The heavy iteration count sizes a multi-second train
#: (the compiled SGD loop costs ~1.4s/1M iterations on this box's CPU
#: class) so the SIGKILL provably lands mid-execution, and the lease
#: timeout is cranked down so takeover latency — not the 30s
#: production default — dominates the measured failover wall.
_FLEET_REPLICAS = 3
_FLEET_QUICK_PLANS = 3
# sized for a reliable mid-run SIGKILL window (~seconds) at the
# fleet's small bench session — per-iteration cost scales with the
# session, so at bigger shapes this count would stretch the twin and
# the takeover re-run into minutes without sharpening any pin
_FLEET_HEAVY_ITERATIONS = 600_000
_FLEET_LEASE_TIMEOUT_S = "2"

#: fleet_placement shape: the same 3-replica fleet over a forced
#: 8-virtual-device host, run twice — device pool on vs off — driving
#: one whole-pool gang plan plus 4 single-device plans. The small
#: iteration count keeps real overlap on the pool (smalls granted,
#: the gang waiting, backfill past it) without stretching either
#: phase's makespan past the failover-class budget.
_PLACEMENT_POOL = 8
_PLACEMENT_SMALL_PLANS = 4
_PLACEMENT_SMALL_ITERATIONS = 100_000
_PLACEMENT_PROMOTION_S = "2"


def _http_json(url: str, body: str = None, method: str = "GET",
               headers: dict = None, timeout: float = 60.0):
    """(status, payload) for one JSON request against the gateway."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url,
        data=body.encode() if body is not None else None,
        method=method, headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _await_plan(base: str, plan_id: str, deadline_s: float = 600.0):
    """Poll GET /plans/<id> until terminal; returns the final state."""
    start = time.monotonic()
    while True:
        _, status = _http_json(f"{base}/plans/{plan_id}")
        if status.get("state") in ("completed", "failed", "cancelled"):
            return status["state"]
        if time.monotonic() - start > deadline_s:
            return f"timeout in state {status.get('state')}"
        time.sleep(0.05)


def _spawn_multiproc_worker(query: str, timeout_s: str = "60",
                            xla_devices: str = "2"):
    """One fresh pipeline process for the population_multiproc family:
    ``xla_devices`` virtual CPU devices (2 for the pod twins, the pool
    size for fleet_placement's gang twin), gloo collectives (set by
    the worker branch before the backend initializes), feature cache
    off (the pod path bypasses it anyway — the twin must match)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={xla_devices}"
    )
    env["EEG_TPU_NO_FEATURE_CACHE"] = "1"
    env["EEG_TPU_POD_TIMEOUT_S"] = timeout_s
    env.pop("EEG_TPU_FAULTS", None)
    env.pop("EEG_TPU_RUN_REPORT_DIR", None)
    # the query alone decides each worker's pod membership: a pod
    # launcher's exported env twins must not leak into the twin or
    # the degraded worker (they would resolve a pod the variant never
    # asked for and burn the bootstrap timeout)
    for var in (
        "JAX_NUM_PROCESSES", "JAX_COORDINATOR",
        "JAX_COORDINATOR_ADDRESS", "JAX_PROCESS_ID",
    ):
        env.pop(var, None)
    return subprocess.Popen(
        [
            sys.executable, os.path.abspath(__file__),
            "multiproc_worker", "0", "0", f"--query={query}",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _reap_worker(proc, timeout=600) -> dict:
    out, err = proc.communicate(timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"multiproc worker failed (rc {proc.returncode}): "
            f"{err[-1500:]}"
        )
    return json.loads(out.strip().splitlines()[-1])


def run_population_multiproc(info: str) -> dict:
    """The pod-scale measurement (ISSUE 14): the population_vmap
    member set run as a 2-process loopback pod (per-host partitioned
    ingest feeding the global member axis over the gloo DCN stand-in)
    against its single-process twin on the SAME data in an equally
    fresh process — members/sec ratio and the statistics-parity sha
    ride the line, plus the degraded-coordinator run (unreachable
    coordinator -> single-host rung, plan completes, parity holds).

    On a one-host box both pod processes share the machine, so the
    ratio measures harness overhead honestly (expect ~1x or below);
    on a real pod slice each process owns its chips and the same rows
    are the ~1/N evidence (tools/collect_chip_runs.sh stages them).
    """
    import socket as _socket

    base_query = build_population_query(info, "vmap") + "&dedup=false"

    def _free_port_pair() -> int:
        """A port whose NEIGHBOR is also bindable — the preflight
        rendezvouses on coordinator port + 1, so both must be free.
        (Still a close-then-use window, but probing the pair removes
        the common collision: an ephemeral port whose neighbor is a
        listening service.)"""
        for _ in range(16):
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            try:
                s2 = _socket.socket()
                try:
                    s2.bind(("", port + 1))
                except OSError:
                    continue
                s2.close()
                return port
            finally:
                s.close()
        raise RuntimeError("no free coordinator port pair found")

    port = _free_port_pair()

    workers = [
        _spawn_multiproc_worker(
            base_query
            + f"&processes=2&coordinator=127.0.0.1:{port}"
            + f"&process_id={pid}"
        )
        for pid in range(2)
    ]
    twin_proc = _spawn_multiproc_worker(base_query)
    results = [_reap_worker(p) for p in workers]
    twin = _reap_worker(twin_proc)

    # the degraded-coordinator run: nobody listens on a fresh port,
    # the preflight times out inside the bootstrap budget, the run
    # lands the single-host rung and still matches the twin
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    degraded = _reap_worker(
        _spawn_multiproc_worker(
            base_query
            + f"&processes=2&coordinator=127.0.0.1:{dead_port}"
            + "&process_id=1",
            timeout_s="3",
        )
    )

    members = int(results[0].get("members") or 0)
    pod_train_s = max(r.get("train_s") or 0.0 for r in results)
    twin_train_s = twin.get("train_s") or 0.0
    deg_pod = (degraded.get("mesh") or {}).get("pod") or {}
    block = {
        "processes": 2,
        "members": members,
        "parity_sha_ok": bool(
            results[0]["sha"] == results[1]["sha"] == twin["sha"]
        ),
        "members_per_s": (
            round(members / pod_train_s, 2) if pod_train_s > 0 else 0.0
        ),
        "twin_members_per_s": (
            round(members / twin_train_s, 2) if twin_train_s > 0 else 0.0
        ),
        "speedup_vs_twin": (
            round(twin_train_s / pod_train_s, 3)
            if pod_train_s > 0 and twin_train_s > 0
            else None
        ),
        "mesh": results[0].get("mesh"),
        "degraded_coordinator": {
            "rung": deg_pod.get("rung"),
            "error_present": bool(deg_pod.get("error")),
            "parity_ok": bool(degraded["sha"] == twin["sha"]),
        },
    }
    return {
        "workers": results,
        "twin": twin,
        "multiproc": block,
        "wall_s": max(r["wall_s"] for r in results),
        "epochs": int(results[0].get("epochs") or 0),
        "report_sha256": twin["sha"],
    }


def run_plan_service(info: str, scratch: str) -> dict:
    """The plan_service measurement: the shared-prefix dedup pair over
    HTTP (exactly one prefix build, both statistics byte-identical to
    solo), the idempotent re-submit replay, and the many-client chaos
    soak with submits/sec at the loopback front door."""
    import hashlib as _hashlib
    import threading as _threading

    from eeg_dataanalysispackage_tpu import obs
    from eeg_dataanalysispackage_tpu.gateway import GatewayServer
    from eeg_dataanalysispackage_tpu.scheduler import dedup as dedup_mod

    def tenant_query(clf, extra):
        return build_query(info, fanout=False, train_clf=clf,
                           extra=extra)

    def sha(text):
        return _hashlib.sha256(str(text).encode()).hexdigest()

    # -- solo twins (dedup=false, in-process): the unshared baseline
    # statistics AND the jit warmup, so the timed phases below measure
    # the service, not XLA compiles
    os.environ["EEG_TPU_FEATURE_CACHE_DIR"] = os.path.join(
        scratch, "fc_solo"
    )
    solo_sha = {}
    for clf, extra in _PLAN_SERVICE_TENANTS:
        statistics, _, _, _, _ = run_query(
            tenant_query(clf, extra) + "&dedup=false&cache=false"
        )
        solo_sha[clf] = sha(statistics)

    # -- phase 1: the shared-prefix pair over HTTP ----------------------
    os.environ["EEG_TPU_FEATURE_CACHE_DIR"] = os.path.join(
        scratch, "fc_pair"
    )
    dedup_mod.reset()
    before = obs.metrics.snapshot()["counters"]
    pair_start = time.perf_counter()
    with GatewayServer(
        journal_dir=os.path.join(scratch, "journal_pair"),
        report_root=os.path.join(scratch, "reports_pair"),
        max_concurrent=2, queue_depth=8,
    ) as gw:
        base = gw.url
        submitted = []
        for clf, extra in _PLAN_SERVICE_TENANTS:
            code, payload = _http_json(
                f"{base}/plans", body=tenant_query(clf, extra),
                method="POST",
                headers={"X-Idempotency-Key": f"bench-{clf}"},
            )
            submitted.append((clf, code, payload))
        states = {
            payload["plan_id"]: _await_plan(base, payload["plan_id"])
            for _, _, payload in submitted
        }
        _, dedup_stats = _http_json(f"{base}/stats")
        pair_wall = time.perf_counter() - pair_start
        reports = {}
        for clf, _, payload in submitted:
            _, rep = _http_json(
                f"{base}/plans/{payload['plan_id']}/report"
            )
            reports[clf] = rep
        # idempotent re-submit of the COMPLETED leader: same key, same
        # body -> HTTP 200, the original plan id, nothing re-executed
        leader_clf, _, leader_payload = submitted[0]
        recode, repayload = _http_json(
            f"{base}/plans",
            body=tenant_query(*_PLAN_SERVICE_TENANTS[0]),
            method="POST",
            headers={"X-Idempotency-Key": f"bench-{leader_clf}"},
        )
    after = obs.metrics.snapshot()["counters"]
    pair_epochs = int(
        after.get("pipeline.epochs_loaded", 0.0)
        - before.get("pipeline.epochs_loaded", 0.0)
    )
    # either tenant may have won the lead (two workers pop nearly
    # simultaneously) — attribute from whichever report FOLLOWED
    follower_report = next(
        (
            blk
            for clf, _, _ in submitted
            if (blk := (reports[clf].get("run_report") or {})
                .get("dedup") or {}).get("role") == "follower"
        ),
        {},
    )
    pair_block = {
        "submitted": [
            {"tenant": clf, "http": code, "plan_id": p.get("plan_id")}
            for clf, code, p in submitted
        ],
        "states": states,
        "stores": int(
            after.get("feature_cache.store", 0.0)
            - before.get("feature_cache.store", 0.0)
        ),
        "dedup": dedup_stats.get("dedup", {}),
        "statistics_identical_to_solo": all(
            reports[clf].get("statistics_sha256") == solo_sha[clf]
            for clf, _ in _PLAN_SERVICE_TENANTS
        ),
        # the follower's own run report carries the attribution: who
        # led, bytes/seconds it never spent
        "follower_attribution": {
            k: follower_report.get(k)
            for k in ("role", "leader_plan", "bytes_saved",
                      "seconds_saved")
        },
        "idempotent_resubmit": {
            "http": recode,
            "same_plan_id": (
                repayload.get("plan_id") == leader_payload["plan_id"]
            ),
            "replayed": bool(repayload.get("idempotent_replay")),
        },
        "wall_s": round(pair_wall, 3),
    }

    # -- phase 2: the many-client chaos soak ----------------------------
    os.environ["EEG_TPU_FEATURE_CACHE_DIR"] = os.path.join(
        scratch, "fc_soak"
    )
    dedup_mod.reset()
    before = obs.metrics.snapshot()["counters"]
    clean_q = tenant_query(*_PLAN_SERVICE_TENANTS[0])
    with GatewayServer(
        journal_dir=os.path.join(scratch, "journal_soak"),
        max_concurrent=4,
        queue_depth=2 * _PLAN_SERVICE_CLIENTS
        * _PLAN_SERVICE_PLANS_PER_CLIENT,
        max_attempts=_PLAN_SERVICE_SOAK_ATTEMPTS,
    ) as gw:
        base = gw.url
        results = [None] * _PLAN_SERVICE_CLIENTS

        def client(idx):
            # every other client chaos-bearing: its OWN plans absorb
            # scheduler.plan faults through executor retries; its
            # neighbours must never notice
            chaos = (
                f"&faults=scheduler.plan:p=0.3;seed={idx}"
                if idx % 2 else ""
            )
            out = []
            for j in range(_PLAN_SERVICE_PLANS_PER_CLIENT):
                code, payload = _http_json(
                    f"{base}/plans", body=clean_q + chaos,
                    method="POST",
                )
                out.append((code, payload))
            results[idx] = out

        soak_start = time.perf_counter()
        threads = [
            _threading.Thread(target=client, args=(i,))
            for i in range(_PLAN_SERVICE_CLIENTS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        submit_wall = time.perf_counter() - soak_start
        flat = [item for out in results for item in (out or [])]
        sheds = sum(1 for code, _ in flat if code == 429)
        admitted = [p["plan_id"] for code, p in flat if code == 201]
        final = {pid: _await_plan(base, pid) for pid in admitted}
        soak_wall = time.perf_counter() - soak_start
        shas = {}
        for pid in admitted:
            _, rep = _http_json(f"{base}/plans/{pid}/report")
            shas[pid] = rep.get("statistics_sha256")
        _, soak_stats = _http_json(f"{base}/stats")
    after = obs.metrics.snapshot()["counters"]
    soak_epochs = int(
        after.get("pipeline.epochs_loaded", 0.0)
        - before.get("pipeline.epochs_loaded", 0.0)
    )
    expected = solo_sha[_PLAN_SERVICE_TENANTS[0][0]]
    soak_block = {
        "clients": _PLAN_SERVICE_CLIENTS,
        "submissions": len(flat),
        "submits_per_s": round(len(flat) / submit_wall, 1)
        if submit_wall > 0 else 0.0,
        "sheds": sheds,
        "all_resolved": all(
            state == "completed" for state in final.values()
        ),
        "statistics_identical": all(
            s == expected for s in shas.values()
        ),
        "chaos_fired": int(
            after.get("chaos.fired.scheduler.plan", 0.0)
            - before.get("chaos.fired.scheduler.plan", 0.0)
        ),
        "dedup": soak_stats.get("dedup", {}),
        "wall_s": round(soak_wall, 3),
    }
    return {
        "epochs": pair_epochs + soak_epochs,
        "wall_s": round(pair_wall + soak_block["wall_s"], 3),
        "plan_service": {
            "pair": pair_block,
            "soak": soak_block,
            "solo_sha256": solo_sha,
        },
        "report_sha256": reports[
            _PLAN_SERVICE_TENANTS[0][0]
        ].get("statistics_sha256") or "",
    }


def _spawn_gateway_replica(replica_id: str, journal_dir: str,
                           report_root: str, cache_dir: str,
                           extra_env: dict = None):
    """One REAL fleet replica process via the production entrypoint
    (``python -m eeg_dataanalysispackage_tpu.gateway --fleet``) — the
    bench kills and drains exactly what an operator runs. CPU-forced:
    three concurrent processes must never contend for one
    accelerator. ``extra_env`` overlays the defaults (fleet_placement
    turns the device pool on and forces the virtual host size).
    Returns (Popen, stderr tempfile path)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["EEG_TPU_FEATURE_CACHE_DIR"] = cache_dir
    env["EEG_TPU_LEASE_TIMEOUT_S"] = _FLEET_LEASE_TIMEOUT_S
    env["EEG_TPU_FLEET_SCAN_INTERVAL_S"] = "0.1"
    env.pop("EEG_TPU_FAULTS", None)
    env.pop("EEG_TPU_RUN_REPORT_DIR", None)
    env.pop("EEG_TPU_NO_FEATURE_CACHE", None)
    env.update(extra_env or {})
    # stderr to a file, not a pipe: replicas log freely and nobody
    # drains the pipe while the bench orchestrates the kill
    err = tempfile.NamedTemporaryFile(
        mode="w+", suffix=f".{replica_id}.err", delete=False
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "eeg_dataanalysispackage_tpu.gateway",
            "--port", "0", "--journal-dir", journal_dir,
            "--report-root", report_root, "--max-concurrent", "2",
            "--drain-timeout-s", "120",
            "--fleet", "--replica-id", replica_id,
        ],
        env=env, stdout=subprocess.PIPE, stderr=err, text=True,
    )
    return proc, err.name


def _replica_url(proc, deadline_s: float = 120.0) -> str:
    """Parse the replica's flushed listening line off its stdout."""
    import select as _select

    buf = ""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica exited rc={proc.returncode} before listening"
            )
        ready, _, _ = _select.select([proc.stdout], [], [], 0.5)
        if not ready:
            continue
        chunk = os.read(proc.stdout.fileno(), 4096).decode()
        if not chunk:
            continue
        buf += chunk
        for line in buf.splitlines():
            if "listening on " in line:
                return line.split("listening on ", 1)[1].split()[0]
    raise RuntimeError("replica never printed its listening line")


def run_gateway_fleet(info: str, scratch: str) -> dict:
    """The replicated-gateway measurement (gateway/fleet.py): three
    real replica processes over one shared journal; quick plans spread
    across two of them, one heavy plan on the third; SIGKILL the heavy
    plan's holder MID-RUN and measure the survivors finishing it under
    its original id — statistics sha pinned byte-identical against an
    uninterrupted fresh-process twin. The journal audit (exactly one
    terminal record per plan, zero corrupt quarantines, zero leftover
    leases) plus the survivors' ``scheduler.completed`` sum against
    the expected execution count is the zero-double-execution
    evidence; the close-out is a real SIGTERM drain of the survivors
    (exit 0 pinned)."""
    import signal as _signal

    def q(iterations):
        # replace, don't append: get_raw_param takes the FIRST
        # occurrence of a duplicated key
        base = build_query(info, fanout=False) + "&dedup=false"
        if iterations:
            base = base.replace(
                "config_num_iterations=20",
                f"config_num_iterations={iterations}",
            )
        return base

    # -- uninterrupted twins, each in its own fresh CPU process (the
    # same spawn the replicas' plans run under): the shas every fleet
    # execution — takeover included — must reproduce byte-identically.
    # Independent of each other (cache off, read-only data), so they
    # run concurrently
    quick_proc = _spawn_multiproc_worker(q(0))
    heavy_proc = _spawn_multiproc_worker(q(_FLEET_HEAVY_ITERATIONS))
    quick_twin = _reap_worker(quick_proc)
    heavy_twin = _reap_worker(heavy_proc)

    journal_dir = os.path.join(scratch, "journal_fleet")
    report_root = os.path.join(scratch, "reports_fleet")
    cache_dir = os.path.join(scratch, "fc_fleet")
    ids = [f"gw-{chr(ord('a') + i)}" for i in range(_FLEET_REPLICAS)]
    procs, err_files, urls = [], [], []
    start = time.perf_counter()
    try:
        for rid in ids:
            proc, err = _spawn_gateway_replica(
                rid, journal_dir, report_root, cache_dir
            )
            procs.append(proc)
            err_files.append(err)
        for proc in procs:
            urls.append(_replica_url(proc))
        # routable = /readyz 200 (journal writable, executor
        # accepting) — the fleet's own routing contract, probed here
        # exactly as a load balancer would
        for url in urls:
            ready_deadline = time.monotonic() + 120
            while True:
                try:
                    code, _ = _http_json(f"{url}/readyz", timeout=5)
                except OSError:
                    code = 0
                if code == 200:
                    break
                if time.monotonic() > ready_deadline:
                    raise RuntimeError(f"{url} never became ready")
                time.sleep(0.2)
        startup_wall = time.perf_counter() - start

        # -- submit: heavy to the victim (replica 0), quick plans
        # round-robin over the survivors
        code, heavy = _http_json(
            f"{urls[0]}/plans", body=q(_FLEET_HEAVY_ITERATIONS),
            method="POST",
            headers={"X-Idempotency-Key": "fleet-heavy"},
        )
        if code != 201:
            raise RuntimeError(f"heavy submit failed: {code} {heavy}")
        heavy_id = heavy["plan_id"]
        quick = []
        for i in range(_FLEET_QUICK_PLANS):
            url = urls[1 + i % (_FLEET_REPLICAS - 1)]
            code, payload = _http_json(
                f"{url}/plans", body=q(0), method="POST",
                headers={"X-Idempotency-Key": f"fleet-q{i}"},
            )
            if code != 201:
                raise RuntimeError(
                    f"quick submit {i} failed: {code} {payload}"
                )
            quick.append(payload["plan_id"])

        # -- the kill: wait until the heavy plan is RUNNING on the
        # victim, then SIGKILL — no drain, no goodbye; the lease
        # heartbeat just stops and the pid dies
        kill_deadline = time.monotonic() + 240
        while True:
            _, status = _http_json(f"{urls[0]}/plans/{heavy_id}")
            if status.get("state") == "running":
                break
            if status.get("state") in ("completed", "failed"):
                raise RuntimeError(
                    f"heavy plan finished before the kill "
                    f"({status.get('state')}) — raise "
                    f"_FLEET_HEAVY_ITERATIONS"
                )
            if time.monotonic() > kill_deadline:
                raise RuntimeError("heavy plan never started running")
            time.sleep(0.05)
        kill_at = time.perf_counter()
        procs[0].kill()
        procs[0].wait(timeout=60)

        # -- takeover: every plan reaches a terminal state, observed
        # through a SURVIVOR (any replica answers for any plan via the
        # shared journal)
        base = urls[1]
        final = {
            pid: _await_plan(base, pid, deadline_s=600.0)
            for pid in [heavy_id] + quick
        }
        takeover_wall = time.perf_counter() - kill_at

        # -- keyed re-submit of the taken-over plan to a survivor
        # that never accepted it: the fleet-wide replay contract
        recode, repayload = _http_json(
            f"{urls[2]}/plans", body=q(_FLEET_HEAVY_ITERATIONS),
            method="POST",
            headers={"X-Idempotency-Key": "fleet-heavy"},
        )

        survivor_stats = []
        for url in urls[1:]:
            _, stats = _http_json(f"{url}/stats")
            survivor_stats.append(stats)

        # -- one fleet view off the live fleet (tools/fleet_top.py):
        # scrape every replica's /metrics — the SIGKILLed victim must
        # render as a DOWN row, the table degrades per-replica — and
        # join the shared lease directory. The scraped counters are
        # independent evidence for the journal audit below: the
        # survivors' own exposition must agree about completions and
        # the takeover.
        sys.path.insert(
            0, os.path.dirname(os.path.abspath(__file__))
        )
        import fleet_top
        metrics_snap = fleet_top.snapshot(urls, journal_dir=journal_dir)

        # -- graceful close-out: real SIGTERM, drain, exit 0
        for proc in procs[1:]:
            proc.send_signal(_signal.SIGTERM)
        drain_rcs = [p.wait(timeout=180) for p in procs[1:]]
        wall = time.perf_counter() - start
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for name in err_files:
            try:
                os.unlink(name)
            except OSError:
                pass

    # -- offline journal audit (the dead fleet's records speak for
    # themselves, exactly as plan_admin fleet reads them)
    from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

    entries = {
        e["plan_id"]: e for e in PlanJournal(journal_dir).entries()
    }
    heavy_entry = entries.get(heavy_id, {})
    heavy_fleet = (heavy_entry.get("meta") or {}).get("fleet") or {}
    corrupt = [
        n for n in os.listdir(journal_dir) if n.endswith(".corrupt")
    ]
    leases = [
        n for n in os.listdir(journal_dir) if n.endswith(".lease")
    ]
    # exactly-once across processes: the survivors' own completion
    # counters must sum to precisely the executions the fleet owed
    # them — the quick plans they accepted plus the one takeover (the
    # keyed re-submit replays, never re-runs). One more would BE a
    # double execution.
    completed_counts = [
        int((s.get("scheduler") or {}).get("scheduler.completed", 0))
        for s in survivor_stats
    ]
    expected_completions = _FLEET_QUICK_PLANS + 1

    epochs = 0
    for pid in entries:
        path = os.path.join(report_root, pid, "run_report.json")
        try:
            with open(path) as f:
                counters = (json.load(f).get("metrics") or {}).get(
                    "counters"
                ) or {}
            epochs += int(counters.get("pipeline.epochs_loaded", 0))
        except (OSError, ValueError):
            pass

    fleet_block = {
        "replicas": _FLEET_REPLICAS,
        "victim": ids[0],
        "killed_in_state": "running",
        "startup_to_ready_s": round(startup_wall, 3),
        "plans": {
            "heavy": heavy_id, "quick": quick, "states": final,
        },
        "all_terminal": all(
            s in ("completed", "failed") for s in final.values()
        ),
        "all_completed": all(s == "completed" for s in final.values()),
        "takeover": {
            "plan_id": heavy_id,
            "completed_by": heavy_fleet.get("replica"),
            "takeover_recorded": bool(heavy_fleet.get("takeover")),
            "not_victim": heavy_fleet.get("replica") not in
            (None, ids[0]),
            "wall_s": round(takeover_wall, 3),
            "lease_timeout_s": float(_FLEET_LEASE_TIMEOUT_S),
            "sha_identical_to_twin": (
                heavy_entry.get("statistics_sha256") == heavy_twin["sha"]
            ),
        },
        "quick_sha_identical": all(
            entries.get(pid, {}).get("statistics_sha256")
            == quick_twin["sha"]
            for pid in quick
        ),
        "resubmit_after_takeover": {
            "http": recode,
            "same_plan_id": repayload.get("plan_id") == heavy_id,
            "replayed": bool(repayload.get("idempotent_replay")),
        },
        "journal_audit": {
            "terminal_records": sum(
                1 for e in entries.values()
                if e.get("state") in ("completed", "failed")
            ),
            "expected_records": 1 + _FLEET_QUICK_PLANS,
            "corrupt_quarantined": len(corrupt),
            "leftover_leases": len(leases),
        },
        "survivor_completed_counts": completed_counts,
        "zero_double_executions": (
            sum(completed_counts) == expected_completions
            and len(entries) == 1 + _FLEET_QUICK_PLANS
        ),
        "survivor_fleet_stats": [
            s.get("fleet") for s in survivor_stats
        ],
        # the live /metrics scrape (fleet_top), taken after the
        # takeover and before the drain: the victim DOWN, the
        # survivors' summed counters agreeing with the journal
        "metrics": metrics_snap,
        "drain_exit_codes": drain_rcs,
        "drained_cleanly": all(rc == 0 for rc in drain_rcs),
    }
    return {
        "fleet": fleet_block,
        "wall_s": round(wall, 3),
        # epochs actually loaded BY THE FLEET, summed from the
        # per-plan run reports the replicas wrote (the victim's
        # partial pass died with its process — unreported, honestly)
        "epochs": epochs,
        "report_sha256": heavy_twin["sha"],
    }


def run_fleet_placement(info: str, scratch: str) -> dict:
    """The device-aware placement measurement (scheduler/placement.py
    over the gateway fleet): the SAME 3-replica fleet workload run
    twice on a forced-8-virtual-device host — once with the shared
    device pool on (``EEG_TPU_DEVICE_POOL=8``) and once with placement
    disabled — driving one 8-device gang plan plus 4 single-device
    plans. The line carries the makespan ratio (placement vs the
    disabled twin), byte-identical sha parity for every plan against
    uninterrupted fresh-process twins, and the device-lease audit:
    held ordinals sampled live while the fleet runs (never more than
    the pool, never an ordinal twice), the gang's journal meta naming
    all 8 leased ordinals, zero device leases left after the SIGTERM
    drain."""
    import signal as _signal

    from eeg_dataanalysispackage_tpu.scheduler import (
        placement as placement_mod,
    )
    from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

    def q(extra="", iterations=0):
        base = build_query(info, fanout=False) + "&dedup=false" + extra
        if iterations:
            base = base.replace(
                "config_num_iterations=20",
                f"config_num_iterations={iterations}",
            )
        return base

    heavy_q = q(f"&devices={_PLACEMENT_POOL}", _FLEET_HEAVY_ITERATIONS)
    small_q = q("", _PLACEMENT_SMALL_ITERATIONS)

    # -- fresh-process twins: the shas every fleet execution — placed,
    # backfilled, or unplaced — must reproduce byte-identically. The
    # gang twin runs on the same 8-virtual-device host shape.
    small_twin_proc = _spawn_multiproc_worker(small_q)
    heavy_twin_proc = _spawn_multiproc_worker(
        heavy_q, xla_devices=str(_PLACEMENT_POOL)
    )
    small_twin = _reap_worker(small_twin_proc)
    heavy_twin = _reap_worker(heavy_twin_proc)

    def phase(tag: str, pool: str) -> dict:
        journal_dir = os.path.join(scratch, f"journal_pl_{tag}")
        report_root = os.path.join(scratch, f"reports_pl_{tag}")
        # per-phase feature cache: both phases pay the same cold
        # ingest, so the makespan ratio compares placement, not cache
        # warmth
        cache_dir = os.path.join(scratch, f"fc_pl_{tag}")
        extra_env = {
            "EEG_TPU_DEVICE_POOL": pool,
            "EEG_TPU_GANG_PROMOTION_S": _PLACEMENT_PROMOTION_S,
            "XLA_FLAGS": (
                "--xla_force_host_platform_device_count="
                f"{_PLACEMENT_POOL}"
            ),
        }
        ids = [
            f"gw-{tag}-{chr(ord('a') + i)}"
            for i in range(_FLEET_REPLICAS)
        ]
        procs, err_files, urls = [], [], []
        max_held = 0
        double_held = 0
        waiting_seen = 0
        try:
            for rid in ids:
                proc, err = _spawn_gateway_replica(
                    rid, journal_dir, report_root, cache_dir,
                    extra_env=extra_env,
                )
                procs.append(proc)
                err_files.append(err)
            for proc in procs:
                urls.append(_replica_url(proc))
            for url in urls:
                ready_deadline = time.monotonic() + 120
                while True:
                    try:
                        code, _ = _http_json(f"{url}/readyz", timeout=5)
                    except OSError:
                        code = 0
                    if code == 200:
                        break
                    if time.monotonic() > ready_deadline:
                        raise RuntimeError(f"{url} never became ready")
                    time.sleep(0.2)

            # -- submit: smalls first (they grant and the gang must
            # wait behind them — the backfill/promotion window the
            # pool exists to manage), then the whole-pool gang
            start = time.perf_counter()
            small_ids = []
            for i in range(_PLACEMENT_SMALL_PLANS):
                code, payload = _http_json(
                    f"{urls[i % _FLEET_REPLICAS]}/plans",
                    body=small_q, method="POST",
                    headers={"X-Idempotency-Key": f"pl-{tag}-s{i}"},
                )
                if code != 201:
                    raise RuntimeError(
                        f"small submit {i} failed: {code} {payload}"
                    )
                small_ids.append(payload["plan_id"])
            code, payload = _http_json(
                f"{urls[0]}/plans", body=heavy_q, method="POST",
                headers={"X-Idempotency-Key": f"pl-{tag}-heavy"},
            )
            if code != 201:
                raise RuntimeError(
                    f"gang submit failed: {code} {payload}"
                )
            heavy_id = payload["plan_id"]

            # -- await all terminal, auditing the shared lease
            # directory live: the union of held ordinals must never
            # exceed the pool and no ordinal may ever be held twice
            pending = set(small_ids + [heavy_id])
            states = {}
            deadline = time.monotonic() + 600
            while pending:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"plans never finished: {sorted(pending)}"
                    )
                rows = placement_mod.device_table(journal_dir)
                ordinals = [r["ordinal"] for r in rows]
                max_held = max(max_held, len(ordinals))
                if len(ordinals) != len(set(ordinals)):
                    double_held += 1
                waiting_seen = max(
                    waiting_seen,
                    len(placement_mod.waiting_entries(journal_dir)),
                )
                for pid in list(pending):
                    _, status = _http_json(f"{urls[1]}/plans/{pid}")
                    if status.get("state") in (
                        "completed", "failed", "cancelled",
                    ):
                        states[pid] = status["state"]
                        pending.discard(pid)
                time.sleep(0.05)
            makespan = time.perf_counter() - start

            for proc in procs:
                proc.send_signal(_signal.SIGTERM)
            drain_rcs = [p.wait(timeout=180) for p in procs]
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            for name in err_files:
                try:
                    os.unlink(name)
                except OSError:
                    pass

        entries = {
            e["plan_id"]: e for e in PlanJournal(journal_dir).entries()
        }
        heavy_meta = (
            entries.get(heavy_id, {}).get("meta") or {}
        ).get("fleet") or {}
        leftover_devices = [
            n for n in os.listdir(journal_dir)
            if n.startswith("device-") and n.endswith(".lease")
        ]
        return {
            "pool": pool,
            "makespan_s": round(makespan, 3),
            "states": states,
            "all_completed": all(
                s == "completed" for s in states.values()
            ),
            "sha_identical": {
                "gang": entries.get(heavy_id, {}).get(
                    "statistics_sha256"
                ) == heavy_twin["sha"],
                "small": all(
                    entries.get(pid, {}).get("statistics_sha256")
                    == small_twin["sha"]
                    for pid in small_ids
                ),
            },
            "device_audit": {
                "pool_size": _PLACEMENT_POOL,
                "max_held": max_held,
                "double_held_samples": double_held,
                "waiting_seen": waiting_seen,
                "leftover_device_leases": len(leftover_devices),
                "gang_leased_ordinals": heavy_meta.get("devices"),
            },
            "drain_exit_codes": drain_rcs,
            "drained_cleanly": all(rc == 0 for rc in drain_rcs),
        }

    start = time.perf_counter()
    placed = phase("on", str(_PLACEMENT_POOL))
    disabled = phase("off", "0")
    wall = time.perf_counter() - start

    gang_ordinals = placed["device_audit"]["gang_leased_ordinals"]
    placement_block = {
        "replicas": _FLEET_REPLICAS,
        "plans": {
            "gang_devices": _PLACEMENT_POOL,
            "small": _PLACEMENT_SMALL_PLANS,
        },
        "placed": placed,
        "disabled": disabled,
        # the headline comparison: the placed fleet must not be slower
        # than the free-for-all twin — exclusive ordinals instead of
        # time-sharing the same host. 10% noise allowance, same
        # precedent as the other wall-clock gates (makespans here are
        # ~20s and scheduler jitter on a shared host exceeds a strict
        # <=); the exact ratio stays in the line for trend tracking.
        "makespan_ratio": round(
            placed["makespan_s"] / disabled["makespan_s"], 3
        ) if disabled["makespan_s"] else 0.0,
        "placement_no_slower": (
            placed["makespan_s"] <= disabled["makespan_s"] * 1.10
        ),
        "sha_parity": (
            placed["sha_identical"]["gang"]
            and placed["sha_identical"]["small"]
            and disabled["sha_identical"]["gang"]
            and disabled["sha_identical"]["small"]
        ),
        "zero_double_held": (
            placed["device_audit"]["double_held_samples"] == 0
            and placed["device_audit"]["max_held"] <= _PLACEMENT_POOL
            and placed["device_audit"]["leftover_device_leases"] == 0
        ),
        "gang_fully_leased": (
            sorted(gang_ordinals or [])
            == list(range(_PLACEMENT_POOL))
        ),
    }
    # epochs actually pushed through both fleets, from the per-plan
    # run reports the replicas wrote
    epochs = 0
    for tag in ("on", "off"):
        root = os.path.join(scratch, f"reports_pl_{tag}")
        try:
            plan_dirs = os.listdir(root)
        except OSError:
            plan_dirs = []
        for pid in plan_dirs:
            path = os.path.join(root, pid, "run_report.json")
            try:
                with open(path) as f:
                    counters = (
                        json.load(f).get("metrics") or {}
                    ).get("counters") or {}
                epochs += int(counters.get("pipeline.epochs_loaded", 0))
            except (OSError, ValueError):
                pass
    return {
        "placement": placement_block,
        "wall_s": round(wall, 3),
        "epochs": epochs,
        "report_sha256": heavy_twin["sha"],
    }


def run_query(query: str):
    """(statistics, wall_s, n_epochs, stage dict, extras) for one
    pipeline execution. The stage dict is the builder's StageTimer
    breakdown (total/count/min/max/mean per stage), so every bench
    line carries where the wall time went, not just that it went;
    ``extras`` carries the h2d transfer bytes (the ``ingest.h2d_bytes``
    metric delta) and, when telemetry ran, the precision/overlap
    attribution."""
    from eeg_dataanalysispackage_tpu import obs
    from eeg_dataanalysispackage_tpu.pipeline import builder

    before = obs.metrics.snapshot()["counters"]
    start = time.perf_counter()
    pb = builder.PipelineBuilder(query)
    statistics = pb.execute()
    wall = time.perf_counter() - start
    after = obs.metrics.snapshot()["counters"]
    n_epochs = int(
        after.get("pipeline.epochs_loaded", 0.0)
        - before.get("pipeline.epochs_loaded", 0.0)
    )
    stages = {
        name: {k: round(v, 6) if isinstance(v, float) else v
               for k, v in entry.items()}
        for name, entry in pb.timers.as_dict().items()
    }
    extras = {
        "h2d_bytes": int(
            after.get("ingest.h2d_bytes", 0.0)
            - before.get("ingest.h2d_bytes", 0.0)
        ),
    }
    if pb.precision_resolved is not None:
        extras["precision"] = pb.precision_resolved
    if pb.overlap_resolved is not None:
        extras["overlap"] = pb.overlap_resolved
    if pb.mesh_resolved is not None:
        extras["mesh"] = pb.mesh_resolved
    return statistics, wall, n_epochs, stages, extras


def main(argv) -> dict:
    variant = argv[0]
    n_markers = int(argv[1]) if len(argv) > 1 else 240
    n_files = int(argv[2]) if len(argv) > 2 else 3
    data_dir = cache_dir = report_dir = journal_dir = None
    worker_query = None
    train_clf = "logreg"
    fe = "dwt-8-fused"
    devices = 8
    for arg in argv[3:]:
        if arg.startswith("--data-dir="):
            data_dir = arg.split("=", 1)[1]
        elif arg.startswith("--cache-dir="):
            cache_dir = arg.split("=", 1)[1]
        elif arg.startswith("--report-dir="):
            report_dir = arg.split("=", 1)[1]
        elif arg.startswith("--devices="):
            # population_sharded's mesh size (the smoke gate's
            # devices=1 degenerate-case run passes 1)
            devices = int(arg.split("=", 1)[1])
        elif arg.startswith("--train-clf="):
            # the smoke gate's per-classifier single runs: the
            # fan-out compile-sharing comparison needs each leg's own
            # single-classifier compile count, not 5x logreg's
            train_clf = arg.split("=", 1)[1]
        elif arg.startswith("--fe="):
            # the smoke gate's rung A/B: the same cold query forced
            # onto an explicit fused backend (e.g. dwt-8-fused-xla,
            # the pre-decode rung), so the decode rung's e2e win is
            # measured against its own alternative on this machine
            fe = arg.split("=", 1)[1]
        elif arg.startswith("--journal-dir="):
            # scheduler_suicide's write-ahead journal location (the
            # parent scheduler_multi run recovers from it)
            journal_dir = arg.split("=", 1)[1]
        elif arg.startswith("--query="):
            # multiproc_worker's full pipeline query (spawned by
            # population_multiproc with the pod knobs composed in)
            worker_query = arg.split("=", 1)[1]
        else:
            raise SystemExit(f"unknown argument {arg!r}")
    if variant not in (
        "pipeline_e2e_cold", "pipeline_e2e_warm", "pipeline_e2e_fanout5",
        "pipeline_e2e_overlap", "pipeline_e2e_bf16",
        "pipeline_e2e_int8", "pipeline_e2e_int4",
        "population_vmap", "population_looped", "population_sharded",
        "population_multiproc", "multiproc_worker",
        "seizure_e2e", "scheduler_multi", "scheduler_suicide",
        "plan_service", "gateway_fleet", "fleet_placement", "populate",
    ):
        raise SystemExit(f"unknown variant {variant!r}")

    if variant == "multiproc_worker":
        # one pod (or twin) process: the query's own processes= knobs
        # drive the bootstrap inside the builder, which configures
        # the gloo CPU collectives itself once the preflight passes —
        # so the twin and the degraded-coordinator runs initialize a
        # plain single-process backend
        statistics, wall, n_epochs, stages, extras = run_query(
            worker_query
        )
        try:
            members = len(statistics)
        except TypeError:
            members = 1
        return {
            "sha": hashlib.sha256(
                str(statistics).encode()
            ).hexdigest(),
            "wall_s": round(wall, 3),
            "train_s": stages.get("train", {}).get("seconds", 0.0),
            "epochs": n_epochs,
            "members": members,
            "mesh": extras.get("mesh"),
        }

    if variant == "population_sharded" and "jax" not in sys.modules:
        # the real multi-device program needs real devices: on the CPU
        # fallback (bench.py sets JAX_PLATFORMS=cpu) force a virtual
        # --devices host platform BEFORE jax initializes — the same
        # XLA_FLAGS mechanism tier-1 and the MULTICHIP dryrun use. On
        # accelerator runs the flag only affects the (unused) host
        # platform; the mesh resolves against the real chips and a
        # too-small machine degrades to single-device, recorded on the
        # line's mesh block.
        flags = [
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)

    global _OWNED_TMP
    if data_dir is None or cache_dir is None:
        _OWNED_TMP = tempfile.mkdtemp(prefix="eeg_tpu_e2e_")
        data_dir = data_dir or os.path.join(_OWNED_TMP, "data")
        cache_dir = cache_dir or os.path.join(_OWNED_TMP, "cache")
    os.makedirs(data_dir, exist_ok=True)
    os.makedirs(cache_dir, exist_ok=True)
    info = os.path.join(data_dir, "info.txt")
    if not os.path.exists(info):
        if variant == "seizure_e2e":
            # continuous annotated recordings: n_markers means
            # samples-per-file here (a continuous session has no
            # marker count to size by)
            info = write_seizure_session(data_dir, n_markers, n_files)
        else:
            info = write_session(data_dir, n_markers, n_files)

    # the feature cache must be live in this child regardless of the
    # hermetic-test default, and must point at the per-run directory
    os.environ.pop("EEG_TPU_NO_FEATURE_CACHE", None)
    os.environ["EEG_TPU_FEATURE_CACHE_DIR"] = cache_dir
    # --report-dir: the timed run writes a run_report.json there
    # (obs/report.py) so the smoke gate can cross-check the bench line
    # against the report's own attribution. The populate child never
    # inherits it (it must not overwrite the timed run's artifact).
    os.environ.pop("EEG_TPU_RUN_REPORT_DIR", None)
    if report_dir and variant != "populate":
        os.environ["EEG_TPU_RUN_REPORT_DIR"] = report_dir

    if variant == "populate":
        run_query(build_query(info, fanout=False))
        return {}

    if variant == "scheduler_suicide":
        # the kill-and-resume child: 1 fast plan completes, 2 slow
        # plans are journaled (one likely mid-run) when the SIGKILL
        # lands — the parent recovers from --journal-dir
        import signal as _signal

        from eeg_dataanalysispackage_tpu.scheduler import PlanExecutor

        qa, qb, qc = scheduler_suicide_queries(info)
        ex = PlanExecutor(max_concurrent=1, journal_dir=journal_dir)
        ex.submit(qa).result(timeout=600)
        ex.submit(qb)
        ex.submit(qc)
        os.kill(os.getpid(), _signal.SIGKILL)

    if variant == "scheduler_multi":
        scratch = _OWNED_TMP or cache_dir
        result = run_scheduler_multi(info, scratch)
        import jax

        from eeg_dataanalysispackage_tpu.io import feature_cache
        from eeg_dataanalysispackage_tpu.ops import plan_cache
        from eeg_dataanalysispackage_tpu.utils import compile_cache

        pstats = plan_cache.stats()
        sched = result["scheduler"]
        wall = result["wall_s"]
        n_epochs = result["epochs"]
        return {
            "variant": variant,
            # the headline rate is the CONCURRENT phase's: epochs
            # through the executor per wall second with 4 tenants in
            # flight (the sequential twin's wall is in the scheduler
            # block for the ratio)
            "epochs_per_s": round(n_epochs / wall, 1) if wall else 0.0,
            "n": n_epochs,
            "iters": 1,
            "wall_s": wall,
            "elapsed_s": wall,
            "bytes_per_epoch": _BYTES_PER_EPOCH,
            "bytes_per_s": round(
                (n_epochs / wall) * _BYTES_PER_EPOCH, 1
            ) if wall else 0.0,
            "n_markers_per_file": n_markers,
            "n_files": n_files,
            "platform": jax.devices()[0].platform,
            "feature_cache": feature_cache.stats(),
            "plan_cache": {
                "hits": pstats["hits"], "misses": pstats["misses"],
            },
            "compile_cache": compile_cache.active_cache_dir(),
            "scheduler": sched,
            "report_sha256": sched["concurrent"]["per_plan"][
                min(sched["concurrent"]["per_plan"])
            ]["statistics_sha256"],
        }

    if variant == "population_multiproc":
        result = run_population_multiproc(info)
        import jax

        from eeg_dataanalysispackage_tpu.io import feature_cache
        from eeg_dataanalysispackage_tpu.ops import plan_cache
        from eeg_dataanalysispackage_tpu.utils import compile_cache

        pstats = plan_cache.stats()
        wall = result["wall_s"]
        n_epochs = result["epochs"]
        return {
            "variant": variant,
            # the headline rate is the POD run's: epochs through the
            # 2-process partitioned ingest per wall second (each
            # process read half the bytes; the twin's rate and the
            # members/sec ratio are in the multiproc block)
            "epochs_per_s": round(n_epochs / wall, 1) if wall else 0.0,
            "n": n_epochs,
            "iters": 1,
            "wall_s": wall,
            "elapsed_s": wall,
            "bytes_per_epoch": _BYTES_PER_EPOCH,
            "bytes_per_s": round(
                (n_epochs / wall) * _BYTES_PER_EPOCH, 1
            ) if wall else 0.0,
            "n_markers_per_file": n_markers,
            "n_files": n_files,
            "platform": jax.devices()[0].platform,
            "feature_cache": feature_cache.stats(),
            "plan_cache": {
                "hits": pstats["hits"], "misses": pstats["misses"],
            },
            "compile_cache": compile_cache.active_cache_dir(),
            "mesh": result["multiproc"].get("mesh"),
            "members_per_s": result["multiproc"]["members_per_s"],
            "multiproc": result["multiproc"],
            "report_sha256": result["report_sha256"],
        }

    if variant == "plan_service":
        scratch = _OWNED_TMP or cache_dir
        result = run_plan_service(info, scratch)
        import jax

        from eeg_dataanalysispackage_tpu.io import feature_cache
        from eeg_dataanalysispackage_tpu.ops import plan_cache
        from eeg_dataanalysispackage_tpu.utils import compile_cache

        pstats = plan_cache.stats()
        wall = result["wall_s"]
        n_epochs = result["epochs"]
        return {
            "variant": variant,
            # the headline rate is epochs through the SERVICE per wall
            # second across both timed phases — deliberately counting
            # only what was actually loaded: dedup means followers
            # load nothing, so this rate RISES with the hit ratio (the
            # interesting front-door rate, submits/sec, is in the
            # plan_service.soak block)
            "epochs_per_s": round(n_epochs / wall, 1) if wall else 0.0,
            "n": n_epochs,
            "iters": 1,
            "wall_s": wall,
            "elapsed_s": wall,
            "bytes_per_epoch": _BYTES_PER_EPOCH,
            "bytes_per_s": round(
                (n_epochs / wall) * _BYTES_PER_EPOCH, 1
            ) if wall else 0.0,
            "n_markers_per_file": n_markers,
            "n_files": n_files,
            "platform": jax.devices()[0].platform,
            "feature_cache": feature_cache.stats(),
            "plan_cache": {
                "hits": pstats["hits"], "misses": pstats["misses"],
            },
            "compile_cache": compile_cache.active_cache_dir(),
            "plan_service": result["plan_service"],
            "report_sha256": result["report_sha256"],
        }

    if variant == "gateway_fleet":
        scratch = _OWNED_TMP or cache_dir
        result = run_gateway_fleet(info, scratch)
        import jax

        from eeg_dataanalysispackage_tpu.io import feature_cache
        from eeg_dataanalysispackage_tpu.ops import plan_cache
        from eeg_dataanalysispackage_tpu.utils import compile_cache

        pstats = plan_cache.stats()
        wall = result["wall_s"]
        n_epochs = result["epochs"]
        return {
            "variant": variant,
            # the headline rate is epochs through the WHOLE fleet per
            # wall second — replica startup, the kill, the lease
            # timeout and the takeover re-execution all inside the
            # denominator, because failover latency is exactly what
            # this line exists to measure (the takeover wall alone is
            # in the fleet block)
            "epochs_per_s": round(n_epochs / wall, 1) if wall else 0.0,
            "n": n_epochs,
            "iters": 1,
            "wall_s": wall,
            "elapsed_s": wall,
            "bytes_per_epoch": _BYTES_PER_EPOCH,
            "bytes_per_s": round(
                (n_epochs / wall) * _BYTES_PER_EPOCH, 1
            ) if wall else 0.0,
            "n_markers_per_file": n_markers,
            "n_files": n_files,
            "platform": jax.devices()[0].platform,
            "feature_cache": feature_cache.stats(),
            "plan_cache": {
                "hits": pstats["hits"], "misses": pstats["misses"],
            },
            "compile_cache": compile_cache.active_cache_dir(),
            "fleet": result["fleet"],
            "report_sha256": result["report_sha256"],
        }

    if variant == "fleet_placement":
        scratch = _OWNED_TMP or cache_dir
        result = run_fleet_placement(info, scratch)
        import jax

        from eeg_dataanalysispackage_tpu.io import feature_cache
        from eeg_dataanalysispackage_tpu.ops import plan_cache
        from eeg_dataanalysispackage_tpu.utils import compile_cache

        pstats = plan_cache.stats()
        wall = result["wall_s"]
        n_epochs = result["epochs"]
        return {
            "variant": variant,
            # the headline rate spans BOTH phases (placed + disabled
            # twin): the line exists for the makespan ratio and the
            # audit in the placement block, not for raw throughput
            "epochs_per_s": round(n_epochs / wall, 1) if wall else 0.0,
            "n": n_epochs,
            "iters": 1,
            "wall_s": wall,
            "elapsed_s": wall,
            "bytes_per_epoch": _BYTES_PER_EPOCH,
            "bytes_per_s": round(
                (n_epochs / wall) * _BYTES_PER_EPOCH, 1
            ) if wall else 0.0,
            "n_markers_per_file": n_markers,
            "n_files": n_files,
            "platform": jax.devices()[0].platform,
            "feature_cache": feature_cache.stats(),
            "plan_cache": {
                "hits": pstats["hits"], "misses": pstats["misses"],
            },
            "compile_cache": compile_cache.active_cache_dir(),
            "placement": result["placement"],
            "report_sha256": result["report_sha256"],
        }

    if variant == "pipeline_e2e_warm":
        # populate from a separate process so the timed run's jit/
        # import state matches the cold child's — the measured delta
        # is the feature cache, nothing else
        subprocess.run(
            [
                sys.executable, os.path.abspath(__file__), "populate",
                str(n_markers), str(n_files),
                f"--data-dir={data_dir}", f"--cache-dir={cache_dir}",
            ],
            check=True,
            stdout=subprocess.DEVNULL,
        )

    if variant.startswith("population_"):
        mode = "looped" if variant == "population_looped" else "vmap"
        query = build_population_query(
            info, mode,
            devices=devices if variant == "population_sharded" else 0,
        )
    elif variant == "seizure_e2e":
        query = build_seizure_query(info)
    else:
        # the overlap/bf16 twins run the COLD query plus their knob,
        # so report_sha256 against pipeline_e2e_cold isolates exactly
        # one variable (scheduling / numeric class)
        extra = {
            "pipeline_e2e_overlap": "&overlap=true",
            "pipeline_e2e_bf16": "&precision=bf16",
            "pipeline_e2e_int8": "&precision=int8",
            "pipeline_e2e_int4": "&precision=int4",
        }.get(variant, "")
        query = build_query(
            info, fanout=variant == "pipeline_e2e_fanout5",
            train_clf=train_clf, extra=extra, fe=fe,
        )
    statistics, wall, n_epochs, stages, extras = run_query(query)

    import jax

    from eeg_dataanalysispackage_tpu.io import feature_cache
    from eeg_dataanalysispackage_tpu.ops import plan_cache
    from eeg_dataanalysispackage_tpu.utils import compile_cache

    pstats = plan_cache.stats()
    payload = {
        "variant": variant,
        "epochs_per_s": round(n_epochs / wall, 1) if wall > 0 else 0.0,
        "n": n_epochs,
        "iters": 1,
        "wall_s": round(wall, 3),
        "elapsed_s": round(wall, 3),
        "bytes_per_epoch": _BYTES_PER_EPOCH,
        # bench attribution: the same rate as a bandwidth, plus the
        # host->device bytes the run actually staged (the
        # ingest.h2d_bytes metric delta — zero for cache-hit runs,
        # which is the point: a hit ships nothing)
        "bytes_per_s": round(
            (n_epochs / wall) * _BYTES_PER_EPOCH, 1
        ) if wall > 0 else 0.0,
        "h2d_bytes": extras["h2d_bytes"],
        "n_markers_per_file": n_markers,
        "n_files": n_files,
        "platform": jax.devices()[0].platform,
        "feature_cache": feature_cache.stats(),
        "plan_cache": {
            "hits": pstats["hits"], "misses": pstats["misses"],
        },
        "compile_cache": compile_cache.active_cache_dir(),
        "stages": stages,
        "report_sha256": hashlib.sha256(
            str(statistics).encode()
        ).hexdigest(),
    }
    if "precision" in extras:
        payload["precision"] = extras["precision"]
    if "overlap" in extras:
        payload["overlap"] = extras["overlap"]
    if "mesh" in extras:
        payload["mesh"] = extras["mesh"]
    if variant == "pipeline_e2e_cold" and fe == "dwt-8-fused":
        plateau = plateau_block(payload["epochs_per_s"])
        if plateau:
            payload["plateau"] = plateau
    if variant == "pipeline_e2e_fanout5":
        payload["classifiers"] = _FANOUT_CLASSIFIERS.split(",")
        payload["accuracy"] = {
            name: round(s.calc_accuracy(), 6)
            for name, s in statistics.items()
        }
    elif variant == "seizure_e2e":
        # windows/sec rides the epochs_per_s field (one window = one
        # epoch through the shared counter). statistics is the
        # cost-swept PopulationStatistics: one member per swept
        # cost_fn value; the member with cost_fn == 1 IS the
        # unweighted baseline, trained in the same vmapped program,
        # so weighted-vs-unweighted is comparable from this one line.
        def member_block(s):
            return {
                "recall": round(s.recall(), 6),
                "precision": round(s.precision(), 6),
                "f1": round(s.f1(), 6),
                "balanced_accuracy": round(s.balanced_accuracy(), 6),
                "expected_cost": round(s.expected_cost(), 6),
                "accuracy": round(s.calc_accuracy(), 6),
            }

        members = {label: member_block(s) for label, s in
                   statistics.items()}
        any_member = next(iter(statistics.values()))
        unweighted = statistics["f0.s42.cfn1"]
        weighted = statistics[f"f0.s42.cfn{_SEIZURE_COST_FN:g}"]
        payload["seizure"] = {
            "windows_per_s": payload["epochs_per_s"],
            "class_ratio": round(
                (any_member.true_positives + any_member.false_negatives)
                / max(1, any_member.num_patterns), 6
            ),
            "cost_fp": any_member.cost_fp,
            "cost_fn": any_member.cost_fn,
            "members": members,
            "unweighted": member_block(unweighted),
            "weighted": member_block(weighted),
        }
        payload["accuracy"] = round(statistics.calc_accuracy(), 6)
    elif variant.startswith("population_"):
        # the per-member table plus the cross-member digest: the
        # artifact alone shows what the 16 members scored, and the
        # vmap/looped/sharded report_sha256 triple proves per-member
        # parity
        payload["population"] = {
            "members": len(statistics),
            "mode": statistics.mode,
            "shape": statistics.shape,
            "summary": statistics.summary(),
            "accuracy": {
                label: round(s.calc_accuracy(), 6)
                for label, s in statistics.items()
            },
        }
        payload["accuracy"] = round(statistics.calc_accuracy(), 6)
        # members/sec over the TRAIN stage — the member-axis rate the
        # sharded line is judged on against its single-device twin
        # (population_vmap from the same bench run, same machine)
        train_s = stages.get("train", {}).get("seconds", 0.0)
        if train_s > 0:
            payload["members_per_s"] = round(
                len(statistics) / train_s, 2
            )
    else:
        payload["accuracy"] = round(statistics.calc_accuracy(), 6)
    return payload


if __name__ == "__main__":
    from eeg_dataanalysispackage_tpu.utils import strict_json

    payload = main(sys.argv[1:])
    if payload:
        # strict JSON at the source: a degenerate confusion matrix
        # makes the seizure members' precision/f1 NaN, and a bare NaN
        # token breaks every strict consumer of the artifact —
        # non-finite floats serialize as null instead
        print(strict_json.dumps(payload))
    # drop this invocation's own scratch (synthetic session + cache);
    # caller-provided --data-dir/--cache-dir are the caller's to keep
    if _OWNED_TMP:
        import shutil

        shutil.rmtree(_OWNED_TMP, ignore_errors=True)
