"""Train a P300 target/non-target classifier, two ways.

Usage: python examples/train_p300.py [path/to/info.txt]
(defaults to the reference fixture if present)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_INFO = "/root/reference/test-data/infoTrain.txt"


def main() -> None:
    info = sys.argv[1] if len(sys.argv) > 1 else DEFAULT_INFO
    if not os.path.exists(info):
        sys.exit(f"info.txt not found: {info}")

    # --- way 1: the reference's query-string surface -----------------
    from eeg_dataanalysispackage_tpu.pipeline import builder

    stats = builder.PipelineBuilder(
        f"info_file={info}&fe=dwt-8-tpu&train_clf=logreg"
        "&config_num_iterations=100&config_step_size=1.0"
        "&config_mini_batch_fraction=1.0"
    ).execute()
    print("query-string pipeline:")
    print(stats)

    # --- way 2: the library API with the TPU fast path ---------------
    from eeg_dataanalysispackage_tpu.io import provider
    from eeg_dataanalysispackage_tpu.models import registry as clf_registry

    features, targets = provider.OfflineDataProvider(
        [info]
    ).load_features_device()
    clf = clf_registry.create("logreg")
    clf.fit(features, targets)
    print("fused device path:", clf.test_features(features, targets))


if __name__ == "__main__":
    main()
