"""Long-recording pipeline: time-sharded marker ingest + raw training.

Usage (runs anywhere — forces a virtual 8-device CPU mesh when no
multi-chip hardware is attached):

    python examples/sharded_long_recording.py

Demonstrates the framework's long-context story end to end on a
synthetic hour-scale recording:

1. the recording is staged time-sharded across the mesh as raw int16
   (half the wire bytes; scaling happens on device);
2. the host plans marker validity + the reference's order-dependent
   class-balance scan and assigns each kept epoch to the shard owning
   its window start (`parallel/sharded_ingest.py`);
3. every device cuts + featurizes its windows with the block-gather
   formulation; windows straddling a shard boundary read their tail
   from the right neighbor over a `ppermute` ring halo;
4. the resulting features train the logreg model, and for the
   steady-state (fixed-SOA) segment the fused raw-stream train step
   (`parallel/train.make_raw_train_step`) updates the MLP straight
   from int16 bytes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ensure_devices() -> None:
    """Force a virtual 8-device CPU mesh (default).

    Probing jax.device_count() would initialize the backend and make
    the overrides below no-ops, so the choice is env-driven instead:
    set EEG_EXAMPLE_REAL_DEVICES=1 to run on the session's real
    multi-chip backend."""
    if os.environ.get("EEG_EXAMPLE_REAL_DEVICES") == "1":
        return
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    _ensure_devices()

    import jax
    import numpy as np

    from eeg_dataanalysispackage_tpu.io.brainvision import Marker
    from eeg_dataanalysispackage_tpu.models import sgd
    from eeg_dataanalysispackage_tpu.parallel import (
        mesh as pmesh,
        sharded_ingest,
        train as ptrain,
    )

    n_dev = min(8, jax.device_count())
    tmesh = pmesh.make_mesh(n_dev, axes=(pmesh.TIME_AXIS,))
    rng = np.random.RandomState(0)

    # -- synthetic recording: n_dev x 64k samples (~8.5 min @ 1 kHz) --
    block = 65536
    T = n_dev * block
    dc = np.array([[1500], [-900], [400]], np.int16)
    raw = (rng.randint(-3000, 3000, size=(3, T)) + dc).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)

    # stimulus markers every ~800 samples with jitter; digits 1..9
    base = np.arange(200, T - 1000, 800)
    positions = base + rng.randint(-150, 150, size=base.shape)
    markers = [
        Marker(f"Mk{i}", "Stimulus", f"S  {1 + i % 9}", int(p))
        for i, p in enumerate(positions)
    ]

    # -- 1-3: plan on host, ingest across the mesh --------------------
    plan = sharded_ingest.plan_sharded_ingest(
        markers, guessed_number=4, n_samples=T, n_shards=n_dev,
        block=block,
    )
    extract = sharded_ingest.make_sharded_ingest(tmesh)
    staged = sharded_ingest.stage_recording_int16(raw, tmesh)
    feats = extract(staged, res, plan)
    print(
        f"{len(markers)} markers -> {feats.shape[0]} balanced epochs "
        f"featurized across {n_dev} time shards: {feats.shape}"
    )

    # -- 4a: classify the sharded-ingest features ---------------------
    w = sgd.train_linear(
        feats.astype(np.float32),
        plan.targets.astype(np.float32),
        sgd.SGDConfig(num_iterations=50),
    )
    margin = feats.astype(np.float32) @ np.asarray(w)
    acc = float(((margin > 0) == (plan.targets > 0.5)).mean())
    print(f"logreg on sharded-ingest features: train accuracy {acc:.2f}")

    # -- 4b: steady-state segment -> fused raw-stream training --------
    stride, first = 800, 200
    n_ep = min(512, (T - first - 8192) // stride)
    init_state, step = ptrain.make_raw_train_step(stride, n_ep)
    state = init_state(jax.random.PRNGKey(0))
    labels = (rng.rand(n_ep) > 0.5).astype(np.float32)
    import jax.numpy as jnp

    mask = jnp.ones((n_ep,), jnp.float32)
    for i in range(3):
        state, loss = step(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(labels), mask, first,
        )
        print(f"raw-stream train step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
