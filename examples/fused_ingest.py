"""Fused int16 ingest, three formulations.

Usage: python examples/fused_ingest.py

Generates a synthetic int16 multiplexed recording with stimulus
markers and produces 48-dim DWT feature vectors straight from the raw
stream (no host epoch tensors):

1. XLA gather formulation (`ops/device_ingest.py`) — dynamic-slice
   window gather + composed-cascade einsum;
2. Pallas kernel (`ops/ingest_pallas.py`) — windows cut in VMEM, one
   MXU contraction per tile (interpret mode off-TPU);
3. regular stimulus train (`make_regular_ingest_featurizer`) — fixed
   stimulus-onset asynchrony makes window formation a static reshape:
   one einsum, no gather.

All three agree to float32 tolerance; `docs/ingest_kernel.md` carries
the bytes-per-epoch roofline comparison.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax.numpy as jnp

    from eeg_dataanalysispackage_tpu.ops import (
        device_ingest,
        ingest_pallas,
    )

    rng = np.random.RandomState(0)
    n, stride = 256, 800
    S = 200 + n * stride + 1000
    raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)

    # 1. irregular markers through the XLA gather formulation
    positions = (200 + stride * np.arange(n)
                 + rng.randint(-150, 150, size=n)).astype(np.int64)
    cap = ((n + 63) // 64) * 64
    pos_pad = np.zeros(cap, np.int32)
    pos_pad[:n] = positions
    mask = np.zeros(cap, bool)
    mask[:n] = True
    featurizer = device_ingest.make_device_ingest_featurizer()
    feats_xla = np.asarray(
        featurizer(
            jnp.asarray(np.pad(raw, ((0, 0), (0, 900)))),
            jnp.asarray(res), jnp.asarray(pos_pad), jnp.asarray(mask),
        )
    )[:n]
    print(f"xla gather    : {feats_xla.shape}  "
          f"norm[0]={np.linalg.norm(feats_xla[0]):.6f}")

    # 2. same markers through the fused Pallas kernel
    feats_pl = np.asarray(
        ingest_pallas.ingest_features_pallas(raw, res, positions)
    )
    print(f"pallas kernel : {feats_pl.shape}  "
          f"max|Δ| vs xla = {np.abs(feats_pl - feats_xla).max():.2e}")

    # 3. regular stimulus train: no gather at all
    reg = device_ingest.make_regular_ingest_featurizer(stride, n)
    feats_reg = np.asarray(reg(jnp.asarray(raw), jnp.asarray(res), 200))
    print(f"regular train : {feats_reg.shape}  (static reshape + one einsum)")


if __name__ == "__main__":
    main()
