"""Continuous-EEG streaming feature extraction, two ways.

Usage: python examples/stream_continuous.py

Generates a synthetic 64-channel continuous recording and extracts
band-passed DWT features per 512-sample window (stride 256):

1. bounded-memory blocked streaming on one device — recordings of any
   length, O(block) memory, int16 shipped raw;
2. mesh-sharded (sequence-parallel) extraction — the time axis split
   over every available device with a ppermute halo exchange.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    from eeg_dataanalysispackage_tpu.parallel import (
        mesh as pmesh,
        streaming,
    )

    C, T = 64, 1 << 17  # ~2 minutes of 64ch @ 1 kHz
    rng = np.random.RandomState(0)
    raw = rng.randint(-3000, 3000, size=(C, T)).astype(np.int16)
    res = np.full(C, 0.1, np.float32)

    feats = streaming.blocked_features(
        raw, block=16384, resolutions=res
    )
    print(f"blocked streaming: {feats.shape} features from {C}ch x {T} samples")

    n_dev = jax.device_count()
    if T % n_dev == 0:
        mesh = pmesh.make_mesh(n_dev, axes=(pmesh.TIME_AXIS,))
        extract = streaming.make_streaming_extractor(
            mesh, window=512, stride=256
        )
        signal = raw.astype(np.float32) * res[:, None]
        sharded = extract(streaming.stage_recording(signal, mesh))
        print(
            f"mesh streaming over {n_dev} device(s): {sharded.shape} "
            "(last window//stride rows wrap the ring)"
        )


if __name__ == "__main__":
    main()
