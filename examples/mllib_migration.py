"""Migrating models between a Spark MLlib deployment and eeg-tpu.

Usage: python examples/mllib_migration.py

The reference persists trained classifiers with MLlib's own
``model.save(sc, path)`` (LogisticRegressionClassifier.java:144-152;
``"file://" + path`` for the tree family,
DecisionTreeClassifier.java:156-165): parquet + JSON-metadata
directories on the cluster filesystem. This example shows both
directions of the interchange (io/mllib_format.py):

1. IMPORT — a model directory exactly as a Spark 1.6 deployment
   wrote it loads drop-in through the standard ``load()`` seam (and
   therefore through ``load_clf=...&load_name=<dir>`` queries),
   predicting with MLlib's own semantics: f64 margins,
   strict-greater thresholds, Vote combining for forests.
2. EXPORT — a classifier trained here writes a format-1.0 directory
   a Spark cluster can load back, for staged migrations that keep
   the old serving path alive.

Runs on CPU as-is; only numpy/pyarrow are touched.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eeg_dataanalysispackage_tpu.io import mllib_format as mf
from eeg_dataanalysispackage_tpu.models.linear import (
    LogisticRegressionClassifier,
)
from eeg_dataanalysispackage_tpu.models.trees import RandomForestClassifier


def main() -> None:
    rng = np.random.RandomState(0)
    X = rng.randn(256, 48)
    y = (X @ rng.randn(48) + 0.2 > 0).astype(np.float64)
    work = tempfile.mkdtemp(prefix="mllib_migration_")

    # -- 1. import a deployment's GLM model directory ---------------
    # (stand-in for a dir rsynced off the reference cluster; the
    # bytes are identical to what LogisticRegressionModel.save wrote)
    legacy_dir = os.path.join(work, "legacy_logreg_model")
    legacy_w = rng.randn(48) * 0.5
    mf.write_glm(
        legacy_dir, mf.GLM_LOGREG, legacy_w, intercept=0.1, threshold=0.5
    )

    clf = LogisticRegressionClassifier()
    clf.load(legacy_dir)  # detects the directory layout
    pred = clf.predict(X)
    manual = ((X @ legacy_w + 0.1) > 0.0).astype(np.float64)
    assert np.array_equal(pred, manual)
    print(
        f"imported {os.path.basename(legacy_dir)}: "
        f"{int(pred.sum())}/{len(pred)} positive, "
        f"bit-equal to the JVM's double-margin predictions"
    )

    # -- 2. train here, export for the Spark serving path -----------
    rf = RandomForestClassifier()
    rf.set_config(
        {
            "config_max_depth": "4",
            "config_max_bins": "16",
            "config_min_instances_per_node": "1",
            "config_impurity": "gini",
            "config_num_trees": "10",
            "config_feature_subset": "sqrt",
        }
    )
    rf.fit(X, y)
    acc = float((rf.predict(X) == y).mean())

    # the production forest stores BINNED thresholds; export maps
    # each split back to its real-valued bin edge (exactly — see
    # DecisionTreeClassifier.export_mllib_dir) so the Spark-side
    # model is self-contained
    export_dir = os.path.join(work, "exported_rf_model")
    rf.export_mllib_dir(export_dir)

    # round-trip proof: the exported directory loads back and agrees
    rf2 = RandomForestClassifier()
    rf2.load(export_dir)
    agree = float((rf2.predict(X) == rf.predict(X)).mean())
    print(
        f"exported rf (train acc {acc:.2f}) -> {export_dir}; "
        f"round-trip prediction agreement {agree:.2f}"
    )


if __name__ == "__main__":
    main()
