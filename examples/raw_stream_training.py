"""Training straight from the int16 stream, with crash recovery.

Usage: python examples/raw_stream_training.py

The reference trains on host-materialized epochs (per-marker window
copies — OffLineDataProvider.java:200-265 — then Spark RDDs of
float[][]). This framework trains from the RAW int16 stream: one
jitted step fuses ingest -> DWT features -> MLP forward/backward ->
optimizer update, at int16 bytes/epoch with no host epochs. Three
steps of the family, plus the recovery story:

1. regular stimulus train (`make_raw_train_step`) — fixed
   stimulus-onset asynchrony, static window formation, no gather;
2. irregular markers (`make_irregular_train_step`) — block-gather
   fused ingest (tile-row gathers + the 128-variant operator bank);
3. crash + resume via the checkpoint manager: re-running after a
   simulated crash lands bit-identical to the uninterrupted run.

Runs on CPU as-is (the same program compiles for TPU; see
docs/ingest_kernel.md for the measured roofline numbers).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from eeg_dataanalysispackage_tpu.checkpoint import (
        CheckpointManager,
        run_resumable,
    )
    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    rng = np.random.RandomState(0)
    res = np.array([0.1, 0.1, 0.2], np.float32)

    # --- 1. regular stimulus train -----------------------------------
    n, stride, first = 512, 800, 150
    S = 200 + n * stride + 8192
    raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
    labels = (rng.rand(n) > 0.5).astype(np.float32)
    init_state, step = ptrain.make_raw_train_step(stride, n)
    state = init_state(jax.random.PRNGKey(0))
    mask = jnp.ones((n,), jnp.float32)
    for i in range(3):
        state, loss = step(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(labels), mask, first,
        )
        print(f"regular raw-stream step {i}: loss {float(loss):.4f}")

    # --- 2. irregular markers ----------------------------------------
    cap = 512
    positions = np.sort(
        rng.choice(np.arange(200, S - 900), size=cap, replace=False)
    ).astype(np.int32)
    mask_irr = np.ones(cap, bool)
    labels_irr = (rng.rand(cap) > 0.5).astype(np.float32)
    init_irr, irr_step = ptrain.make_irregular_train_step()
    state_irr = init_irr(jax.random.PRNGKey(1))
    for i in range(3):
        state_irr, loss = irr_step(
            state_irr, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(positions), jnp.asarray(mask_irr),
            jnp.asarray(labels_irr),
        )
        print(f"irregular raw-stream step {i}: loss {float(loss):.4f}")

    # --- 3. crash + resume -------------------------------------------
    def batches():
        for k in range(6):
            r = np.random.RandomState(100 + k)
            pos = np.sort(
                r.choice(np.arange(200, S - 900), size=cap, replace=False)
            ).astype(np.int32)
            yield (
                jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
                jnp.asarray(mask_irr),
                jnp.asarray((r.rand(cap) > 0.5).astype(np.float32)),
            )

    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)

        def crashing(stop):
            for i, b in enumerate(batches()):
                if i == stop:
                    raise RuntimeError("simulated crash")
                yield b

        try:
            run_resumable(
                mgr, lambda: init_irr(jax.random.PRNGKey(2)), irr_step,
                crashing(4), save_every=2,
            )
        except RuntimeError:
            print(f"crashed at step 4; checkpoints: {mgr.all_steps()}")
        state_resumed, steps = run_resumable(
            mgr, lambda: init_irr(jax.random.PRNGKey(2)), irr_step,
            batches(), save_every=2,
        )
        print(f"resumed and finished at step {steps}")

    # uninterrupted reference run for the bit-identity claim
    with tempfile.TemporaryDirectory() as d:
        ref_state, _ = run_resumable(
            CheckpointManager(d), lambda: init_irr(jax.random.PRNGKey(2)),
            irr_step, batches(), save_every=2,
        )
    same = all(
        np.array_equal(
            np.asarray(state_resumed["params"][k]),
            np.asarray(ref_state["params"][k]),
        )
        for k in ref_state["params"]
    )
    print(f"resumed == uninterrupted (bit-identical params): {same}")
    assert same


if __name__ == "__main__":
    main()
