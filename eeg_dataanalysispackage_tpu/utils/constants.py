"""Experiment constants (reference: ``Utils/Const.java``).

Epoch geometry and experiment parameters the reference compiles in
(Const.java:61-72). Kept overridable per-call throughout this package;
these are the P300 guess-the-number defaults.
"""

PRESTIMULUS_SAMPLES = 100  # Const.PREESTIMULUS_VALUES
POSTSTIMULUS_SAMPLES = 750  # Const.POSTSTIMULUS_VALUES
SAMPLING_FQ = 1000  # Hz
USED_CHANNELS = 3  # Fz, Cz, Pz
GUESSED_NUMBERS = 9

CHANNEL_NAMES = ("fz", "cz", "pz")

VHDR_EXTENSION = ".vhdr"
VMRK_EXTENSION = ".vmrk"
EEG_EXTENSION = ".eeg"
