"""Persistent XLA compilation-cache wiring.

Fresh-chip compiles of the fused-ingest programs ran 10-14 minutes in
the r4 sweep (tools/sweep_results/r4/watch.log) — long enough to time
out bench variants and to dominate any short pipeline run — yet JAX's
persistent compilation cache ships disabled. This module is the one
place the package turns it on: resolve a cache directory (explicit
argument > ``EEG_TPU_COMPILE_CACHE_DIR`` > the standard
``JAX_COMPILATION_CACHE_DIR`` > a per-user scratch default), create
it, and point ``jax.config`` at it, so the second process compiling
the same program reads a serialized executable instead of re-running
the compiler.

Consumers: ``pipeline/builder.py`` enables it for every query run,
``bench.py``/``tools/ingest_bench.py`` for every bench child (the
bench defaults to the repo-local ``.jax_compile_cache`` scratch dir
so repeat runs are warm), and ``run.sh`` exports the directory so the
CLI inherits it. ``EEG_TPU_NO_COMPILE_CACHE=1`` opts out everywhere.

This module must stay importable without jax: the bench parent
process resolves the directory for its children but never touches a
backend itself.
"""

from __future__ import annotations

import os
from typing import Optional

#: explicit package-level override for the cache directory.
ENV_DIR = "EEG_TPU_COMPILE_CACHE_DIR"
#: the standard JAX variable — respected when already set.
ENV_JAX_DIR = "JAX_COMPILATION_CACHE_DIR"
#: set to "1" to disable persistent caching entirely.
ENV_DISABLE = "EEG_TPU_NO_COMPILE_CACHE"
#: minimum compile seconds worth persisting (JAX-standard variable).
ENV_MIN_COMPILE = "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"

#: don't persist trivial compiles: sub-second CPU test compiles would
#: only churn the cache; the compiles this exists for run minutes.
DEFAULT_MIN_COMPILE_SECS = 5.0


def default_cache_dir() -> str:
    """Per-user scratch default (XDG-style) for non-bench runs."""
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(root, "eeg-tpu", "jax-compile-cache")


def resolve_cache_dir(path: Optional[str] = None) -> Optional[str]:
    """The directory persistent caching should use, or None when
    disabled. Precedence: explicit ``path`` > ``EEG_TPU_COMPILE_CACHE_DIR``
    > ``JAX_COMPILATION_CACHE_DIR`` > the per-user default."""
    if os.environ.get(ENV_DISABLE) == "1":
        return None
    return (
        path
        or os.environ.get(ENV_DIR)
        or os.environ.get(ENV_JAX_DIR)
        or default_cache_dir()
    )


def prime_env(default_dir: Optional[str] = None) -> Optional[str]:
    """Resolve the cache dir and export it as environment for child
    processes / a not-yet-imported jax (the bench parent's path — it
    must configure children without importing jax itself). Returns
    the exported directory, or None when caching is disabled."""
    d = resolve_cache_dir(
        os.environ.get(ENV_DIR) or os.environ.get(ENV_JAX_DIR) or default_dir
    )
    if d is None:
        return None
    os.environ[ENV_JAX_DIR] = d
    os.environ.setdefault(ENV_MIN_COMPILE, str(DEFAULT_MIN_COMPILE_SECS))
    return d


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Turn the persistent compilation cache on for THIS process.

    Returns the active cache directory, or None when disabled or the
    directory cannot be created (an unwritable scratch dir must never
    kill a pipeline run — cache misses just degrade to plain
    compiles). Idempotent; safe before or after backend init."""
    d = resolve_cache_dir(path)
    if d is None:
        return None
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", d)
    try:
        min_secs = float(
            os.environ.get(ENV_MIN_COMPILE, DEFAULT_MIN_COMPILE_SECS)
        )
    except ValueError:
        min_secs = DEFAULT_MIN_COMPILE_SECS
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
    return d


def active_cache_dir() -> Optional[str]:
    """The directory this process's jax is actually configured with
    (ground truth for the bench's ``compile_cache`` payload field)."""
    import jax

    return jax.config.jax_compilation_cache_dir or None
