"""Strict-JSON serialization for bench/report artifacts.

``json.dumps`` happily emits bare ``NaN``/``Infinity`` tokens (a
Python extension, not JSON), and the seizure bench line proved the
failure mode for real: a degenerate confusion matrix makes
``precision``/``f1`` NaN, the artifact records them verbatim, and any
strict consumer downstream (``json.loads`` with default-rejecting
``parse_constant``, jq, a browser, BigQuery) chokes on the whole line
(BENCH_pr8.json's seizure members). Every artifact writer routes its
final ``dumps`` through here instead: non-finite floats serialize as
``null`` — the honest JSON spelling of "this metric has no value" —
and ``allow_nan=False`` backstops the sanitizer, so a non-finite
value can never reach the artifact unsanitized again (pinned in
tests/test_bench_contract.py).

Deliberately dependency-free (no jax, no numpy): ``bench.py``'s
parent process never imports jax (its resilience contract), and numpy
scalars arrive here already rounded to Python floats by the bench
children.
"""

from __future__ import annotations

import json
import math
from typing import Any


def sanitize(obj: Any) -> Any:
    """Deep-copy ``obj`` with every non-finite float replaced by
    ``None`` (dicts/lists/tuples recursed; tuples become lists, which
    is what JSON would do to them anyway)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize(v) for v in obj]
    return obj


def dumps(obj: Any, **kwargs: Any) -> str:
    """``json.dumps`` over the sanitized payload, with
    ``allow_nan=False`` so any non-finite value that somehow survives
    :func:`sanitize` raises here — at the writer, where the bug is —
    instead of poisoning the artifact for every consumer."""
    kwargs.setdefault("allow_nan", False)
    return json.dumps(sanitize(obj), **kwargs)
