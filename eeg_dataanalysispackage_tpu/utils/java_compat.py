"""Bit-exact java.util.Random + Collections.shuffle reproduction.

The reference shuffles epochs and targets with
``Collections.shuffle(list, new Random(1))`` before the 70/30 split
(PipelineBuilder.java:178-188); reproducing that split exactly
requires Java's 48-bit LCG and Fisher-Yates order, implemented here.
"""

from __future__ import annotations

import math
from typing import List, TypeVar

T = TypeVar("T")

_MULT = 0x5DEECE66D
_ADD = 0xB
_MASK = (1 << 48) - 1


class JavaRandom:
    """java.util.Random: 48-bit linear congruential generator."""

    def __init__(self, seed: int):
        self.seed = (seed ^ _MULT) & _MASK

    def _next(self, bits: int) -> int:
        self.seed = (self.seed * _MULT + _ADD) & _MASK
        r = self.seed >> (48 - bits)
        # Java returns a signed int for next(32); callers here only use
        # bits <= 31 so the value is already non-negative.
        return r

    def next_int32(self) -> int:
        """nextInt(): full signed 32-bit output."""
        r = self._next(32)
        return r - (1 << 32) if r >= (1 << 31) else r

    def next_int(self, bound: int) -> int:
        if bound <= 0:
            raise ValueError("bound must be positive")
        if (bound & -bound) == bound:  # power of two
            return (bound * self._next(31)) >> 31
        while True:
            bits = self._next(31)
            val = bits % bound
            if bits - val + (bound - 1) < (1 << 31):
                return val


def java_shuffle(items: List[T], seed: int) -> List[T]:
    """Collections.shuffle(list, new Random(seed)) — returns a new list.

    Fisher-Yates from the top: for i = n-1 .. 1, swap(i, rnd.nextInt(i+1)).
    """
    rnd = JavaRandom(seed)
    out = list(items)
    for i in range(len(out) - 1, 0, -1):
        j = rnd.next_int(i + 1)
        out[i], out[j] = out[j], out[i]
    return out


def java_shuffle_indices(n: int, seed: int) -> List[int]:
    """Permutation such that shuffled[k] = original[perm[k]]."""
    return java_shuffle(list(range(n)), seed)


def train_test_split_indices(n: int, seed: int = 1, train_frac: float = 0.7):
    """The reference's shuffle + subList split (PipelineBuilder.java:178-188).

    Returns (train_idx, test_idx) into the *original* epoch order.
    """
    perm = java_shuffle_indices(n, seed)
    cut = int(n * train_frac)
    return perm[:cut], perm[cut:]


def java_double_to_string(value: float) -> str:
    """``Double.toString(double)`` formatting (Double.java docs).

    Java's rules: decimal form for 1e-3 <= |d| < 1e7 (always at least
    one digit after the point), otherwise "computerized scientific
    notation" ``D.DDDE±X`` with an uppercase bare-sign exponent;
    specials are ``NaN`` / ``Infinity`` / ``-0.0``. Digits come from
    Python's shortest-roundtrip repr, which coincides with modern
    (JDK >= 19, Ryu) ``Double.toString`` digit selection; pre-19 JDKs
    occasionally emitted one extra digit (JDK-4511638), so parity
    there is parse-equal rather than byte-equal in those rare cases.
    """
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    sign = "-" if math.copysign(1.0, v) < 0 else ""
    a = abs(v)
    if a == 0.0:
        return sign + "0.0"
    r = repr(a)
    if "e" in r:
        mant, _, exp_s = r.partition("e")
        exp = int(exp_s)
    else:
        mant, exp = r, 0
    int_part, _, frac = mant.partition(".")
    digits = int_part + frac
    point = len(int_part) + exp  # decimal point position in ``digits``
    stripped = digits.lstrip("0")
    point -= len(digits) - len(stripped)
    digits = stripped.rstrip("0") or "0"
    if -3 < point <= 7:  # 1e-3 <= a < 1e7
        if point <= 0:
            return f"{sign}0.{'0' * -point}{digits}"
        if point >= len(digits):
            return f"{sign}{digits}{'0' * (point - len(digits))}.0"
        return f"{sign}{digits[:point]}.{digits[point:]}"
    return f"{sign}{digits[0]}.{digits[1:] or '0'}E{point - 1}"
