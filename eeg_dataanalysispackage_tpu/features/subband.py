"""Per-subband wavelet feature statistics (the seizure workload's fe=).

The P300 extractor (``features/wavelet.py``) keeps the *first 16 raw
DWT coefficients* — the reference's hard-coded ``dwt-8`` shape. The
epilepsy line this reproduction tracks builds features differently:
per decomposition **subband**, summary statistics — energy, mean,
standard deviation — of the detail/approximation coefficients
(wavelet-energy NN features, arXiv:1307.7897; DWT seizure prediction,
arXiv:2102.01647). This module is that family, selected through the
extended ``fe=`` grammar::

    fe=dwt-<family>:level=<L>[:stats=<s1>,<s2>,...]

e.g. ``fe=dwt-4:level=4:stats=energy,std``. ``family`` is the same
0..17 eegdsp wavelet registry index the plain ``dwt-<n>`` names use
(``ops/eegdsp_compat.py`` — index 8 is the golden-pinned 10-tap
Daubechies); ``level`` is the decomposition depth (the window must
support it: each level halves the length, and a level needs at least
``len(filter)`` samples); ``stats`` defaults to ``energy``.

Feature layout: channel-major, then subband (``[a_L, d_L, …, d_1]``
— approximation first, details coarsest-to-finest), then stat, with
the final vector L2-normalized by the same sequential fold the
reference's pipeline applies to its coefficients
(``ops/dwt_host.l2_normalize_seq``) — so feature magnitude is
comparable across window lengths and resolutions.

Everything is deterministic float64 on the host: the seizure path's
ground-truth feature definition, cached by content key
(``io/feature_cache``) so re-runs skip it.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from . import base
from ..ops import dwt_host, eegdsp_compat

#: the per-subband statistics the grammar accepts, in canonical order
STAT_NAMES = ("energy", "mean", "std")


class SubbandWaveletFeatures(base.FeatureExtraction):
    """DWT decomposition + per-subband statistics per channel."""

    def __init__(
        self,
        name: int = 8,
        level: int = 4,
        stats: Sequence[str] = ("energy",),
        channels: Tuple[int, ...] = (1, 2, 3),
    ):
        if not (0 <= int(name) <= 17):
            # the reference's WaveletTransform validation range
            raise ValueError("Wavelet Name must be >= 0 and <= 17")
        if int(level) < 1:
            raise ValueError(f"level must be >= 1, got {level}")
        stats = tuple(stats)
        if not stats:
            raise ValueError("stats set must not be empty")
        for s in stats:
            if s not in STAT_NAMES:
                raise ValueError(
                    f"unknown subband stat {s!r}; choose from "
                    f"{'/'.join(STAT_NAMES)}"
                )
        if len(set(stats)) != len(stats):
            raise ValueError(f"stats set repeats an entry: {stats}")
        self.name = int(name)
        self.level = int(level)
        self.stats = stats
        self.channels = tuple(channels)  # 1-based, like WaveletTransform

    # -- config identity (the feature-cache key component) -------------

    def cache_id(self) -> Tuple:
        """The FULL extractor config as a static tuple — wavelet
        family, decomposition level, stat set, channel selection. This
        is what the feature cache folds into its content key, so a
        ``dwt-8`` entry can never satisfy a
        ``dwt-4:level=4:stats=energy`` request (cross-config
        poisoning test, tests/test_seizure_pipeline.py)."""
        return (
            "dwt-subband", self.name, self.level, self.stats,
            self.channels,
        )

    @property
    def feature_dimension(self) -> int:
        # level details + the final approximation, per channel, per stat
        return len(self.channels) * (self.level + 1) * len(self.stats)

    # -- extraction ----------------------------------------------------

    def _decompose(self, signal: np.ndarray) -> list:
        """``[a_L, d_L, ..., d_1]`` subband arrays over the last axis
        — the SAME cascade the golden-pinned full transform runs
        (``ops/dwt_host.fwt_subbands``), depth-bounded; a window too
        short for the requested level refuses loudly."""
        h, g = eegdsp_compat.filter_pair(self.name)
        a, details = dwt_host.fwt_subbands(
            np.asarray(signal, dtype=np.float64), h, g,
            max_levels=self.level,
        )
        if len(details) < self.level:
            raise ValueError(
                f"window of {signal.shape[-1]} samples supports only "
                f"{len(details)} decomposition levels for wavelet "
                f"family {self.name} ({len(h)} taps); "
                f"level={self.level} requested"
            )
        return [a] + details[::-1]

    def extract_batch(self, epochs: np.ndarray) -> np.ndarray:
        x = np.asarray(epochs, dtype=np.float64)
        ch_idx = [c - 1 for c in self.channels]
        if ch_idx != list(range(x.shape[1])):
            x = x[:, ch_idx, :]
        bands = self._decompose(x)  # each (n, C, band_len)
        cols = []
        for band in bands:
            for stat in self.stats:
                if stat == "energy":
                    # the reference's sequential sum-of-squares fold
                    cols.append(dwt_host._seq_dot(band, band))
                elif stat == "mean":
                    cols.append(band.mean(axis=-1))
                else:  # std (population)
                    cols.append(band.std(axis=-1))
        # (n, C, bands*stats) -> channel-major flatten, band/stat inner
        stacked = np.stack(cols, axis=-1)  # (n, C, (L+1)*S)
        flat = stacked.reshape(x.shape[0], -1)
        return dwt_host.l2_normalize_seq(flat)

    # -- identity ------------------------------------------------------

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, SubbandWaveletFeatures)
            and self.cache_id() == other.cache_id()
        )

    def __hash__(self) -> int:
        return hash(self.cache_id())

    def __repr__(self) -> str:
        return (
            f"DWT-SUBBAND: FAMILY: {self.name} LEVEL: {self.level} "
            f"STATS: {','.join(self.stats)}"
        )
