"""``fe=`` plugin registry.

Parity with the reference's hard-coded switch
(PipelineBuilder.java:128-139): ``dwt-8`` builds
``WaveletTransform(8, 512, 175, 16)``. The TPU build adds
``dwt-8-tpu`` (same math, batched XLA backend) per BASELINE.json's
north star, plus a generic ``dwt-<n>`` family for the other registry
indices. Unknown names raise the reference's error message.
"""

from __future__ import annotations

import re
from typing import Callable, Dict

from . import base, wavelet

_REGISTRY: Dict[str, Callable[[], base.FeatureExtraction]] = {}


def register(name: str, factory: Callable[[], base.FeatureExtraction]) -> None:
    _REGISTRY[name] = factory


def create(name: str) -> base.FeatureExtraction:
    if name in _REGISTRY:
        return _REGISTRY[name]()
    m = re.fullmatch(
        r"dwt-(\d+)(-tpu-bf16|-tpu-compact-bf16|-tpu-compact|-tpu|-pallas)?",
        name,
    )
    if m:
        backend = {
            None: "host",
            "-tpu": "xla",
            "-tpu-bf16": "xla-bf16",
            "-tpu-compact": "xla-compact",
            "-tpu-compact-bf16": "xla-compact-bf16",
            "-pallas": "pallas",
        }[m.group(2)]
        return wavelet.WaveletTransform(name=int(m.group(1)), backend=backend)
    raise ValueError("Unsupported feature extraction argument")


register("dwt-8", lambda: wavelet.WaveletTransform(8, 512, 175, 16, backend="host"))
register(
    "dwt-8-tpu", lambda: wavelet.WaveletTransform(8, 512, 175, 16, backend="xla")
)
register(
    "dwt-8-pallas",
    lambda: wavelet.WaveletTransform(8, 512, 175, 16, backend="pallas"),
)
register(
    "dwt-8-tpu-compact",
    lambda: wavelet.WaveletTransform(
        8, 512, 175, 16, backend="xla-compact"
    ),
)
