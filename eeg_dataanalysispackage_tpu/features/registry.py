"""``fe=`` plugin registry.

Parity with the reference's hard-coded switch
(PipelineBuilder.java:128-139): ``dwt-8`` builds
``WaveletTransform(8, 512, 175, 16)``. The TPU build adds
``dwt-8-tpu`` (same math, batched XLA backend) per BASELINE.json's
north star, plus a generic ``dwt-<n>`` family for the other registry
indices. Unknown names raise the reference's error message.

Extended grammar (the seizure workload, docs/workloads.md): a plain
name may carry ``:``-separated options —

    dwt-<family>:level=<L>[:stats=<s1>,<s2>,...]

which selects :class:`features.subband.SubbandWaveletFeatures`
(pluggable wavelet family / decomposition level / per-subband
statistic set) instead of the raw-coefficient extractor. Plain names
(no ``:``) resolve exactly as before — the P300 surface is
byte-unchanged.
"""

from __future__ import annotations

import re
from typing import Callable, Dict

from . import base, wavelet

_REGISTRY: Dict[str, Callable[[], base.FeatureExtraction]] = {}


def register(name: str, factory: Callable[[], base.FeatureExtraction]) -> None:
    _REGISTRY[name] = factory


def _create_subband(base_name: str, opts: list) -> base.FeatureExtraction:
    """``dwt-<family>:level=<L>[:stats=...]`` -> SubbandWaveletFeatures.

    The options arrive verbatim: the query parser splits at the FIRST
    ``=`` only (pipeline/builder.get_query_map), so ``level=4`` and
    friends survive without the per-key re-extraction the truncating
    parser used to force."""
    from . import subband

    m = re.fullmatch(r"dwt-(\d+)", base_name)
    if m is None:
        raise ValueError(
            "subband options (level=/stats=) apply to the plain "
            f"dwt-<family> form, got {base_name!r}"
        )
    kwargs: Dict = {"name": int(m.group(1))}
    for opt in opts:
        key, sep, value = opt.partition("=")
        if not sep or not value:
            raise ValueError(
                f"malformed fe= option {opt!r}; expected level=<n> or "
                f"stats=<s1>,<s2>"
            )
        if key == "level":
            try:
                kwargs["level"] = int(value)
            except ValueError:
                raise ValueError(f"fe= level must be an integer, got {value!r}")
        elif key == "stats":
            kwargs["stats"] = tuple(s for s in value.split(",") if s)
        else:
            raise ValueError(
                f"unknown fe= option {key!r}; supported: level, stats"
            )
    return subband.SubbandWaveletFeatures(**kwargs)


def create(name: str) -> base.FeatureExtraction:
    base_name, sep, rest = name.partition(":")
    if sep:
        return _create_subband(base_name, rest.split(":"))
    if name in _REGISTRY:
        return _REGISTRY[name]()
    m = re.fullmatch(
        r"dwt-(\d+)(-tpu-bf16|-tpu-compact-bf16|-tpu-compact|-tpu|-pallas)?",
        name,
    )
    if m:
        backend = {
            None: "host",
            "-tpu": "xla",
            "-tpu-bf16": "xla-bf16",
            "-tpu-compact": "xla-compact",
            "-tpu-compact-bf16": "xla-compact-bf16",
            "-pallas": "pallas",
        }[m.group(2)]
        return wavelet.WaveletTransform(name=int(m.group(1)), backend=backend)
    raise ValueError("Unsupported feature extraction argument")


register("dwt-8", lambda: wavelet.WaveletTransform(8, 512, 175, 16, backend="host"))
register(
    "dwt-8-tpu", lambda: wavelet.WaveletTransform(8, 512, 175, 16, backend="xla")
)
register(
    "dwt-8-pallas",
    lambda: wavelet.WaveletTransform(8, 512, 175, 16, backend="pallas"),
)
register(
    "dwt-8-tpu-compact",
    lambda: wavelet.WaveletTransform(
        8, 512, 175, 16, backend="xla-compact"
    ),
)
