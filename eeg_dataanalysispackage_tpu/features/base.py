"""Feature-extraction plugin boundary.

The TPU-native equivalent of the reference's ``IFeatureExtraction``
seam (IFeatureExtraction.java:33-34): a feature extractor maps a batch
of epochs to a batch of fixed-size feature vectors. Unlike the
reference — which maps a per-epoch ``double[][] -> double[]`` closure
over RDD elements — the contract here is *batched*: extractors take
``(n, channels, samples)`` and return ``(n, feature_dim)`` so the
whole batch lowers to one XLA program instead of n kernel launches.
A per-epoch adapter is provided for reference-style call sites.
"""

from __future__ import annotations

import abc

import numpy as np


class FeatureExtraction(abc.ABC):
    """Batched feature extractor."""

    @abc.abstractmethod
    def extract_batch(self, epochs: np.ndarray) -> np.ndarray:
        """(n, channels, samples) -> (n, feature_dim)."""

    @property
    @abc.abstractmethod
    def feature_dimension(self) -> int:
        """Length of one feature vector (``getFeatureDimension``)."""

    def extract_features(self, epoch: np.ndarray) -> np.ndarray:
        """Single-epoch adapter matching the reference signature."""
        return np.asarray(self.extract_batch(np.asarray(epoch)[None]))[0]

    def cache_id(self) -> tuple:
        """The extractor's FULL static configuration as a hashable
        tuple — the component the content-addressed feature cache
        (io/feature_cache.py) folds into its key. Every config knob
        that changes the feature values MUST appear here; a backend
        choice that only changes where tolerance-identical numerics
        run must not (the degradation-ladder rung contract). Concrete
        extractors override; the default refuses rather than risk a
        cross-config cache hit."""
        raise NotImplementedError(
            f"{type(self).__name__} does not define a feature-cache "
            f"config identity"
        )
