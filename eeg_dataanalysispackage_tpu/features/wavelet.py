"""DWT feature extraction (the reference's ``fe=dwt-8``).

Parity surface of ``FeatureExtraction/WaveletTransform.java``: per
channel, take ``epoch[ch][skip : skip+epoch_size]``, run the eegdsp
FWT, keep the first ``feature_size`` coefficients, concatenate over
channels, L2-normalize the whole vector (WaveletTransform.java:108-141).
Constructor defaults and setter validation ranges mirror
WaveletTransform.java:47-87,160-212.

Two backends:

- ``backend='host'``  — numpy float64 with bit-exact reference
  accumulation order (``ops.dwt_host``); this is what ``fe=dwt-8``
  uses and what the golden-sum test pins.
- ``backend='xla'``   — the batched jitted implementation
  (``ops.dwt``), selected by ``fe=dwt-8-tpu``; float32 on TPU.
- ``backend='xla-bf16'`` — same program in bfloat16
  (``fe=dwt-8-tpu-bf16``): half the HBM bytes per epoch for ~2e-3
  absolute feature deviation; classification results on the
  reference fixture are unchanged (pinned by test). Use when
  throughput matters more than f32-level feature parity.
- ``backend='xla-compact'`` — compact-resident variant
  (``fe=dwt-8-tpu-compact``): the analysis window is sliced on the
  host, so the device-resident batch is (B, C, epoch_size) — honest
  6144 B/epoch instead of carrying the 488 dead columns the
  full-width layout reads to use 512 (WaveletTransform.java:127-130
  consumes only the window). Same math as 'xla' to float rounding
  of an identical contraction.
"""

from __future__ import annotations

import numpy as np

from . import base
from ..ops import dwt_host
from ..utils import constants


class WaveletTransform(base.FeatureExtraction):
    DOWN_SMPL_FACTOR = 1  # WaveletTransform.java:57 (unused, always 1)

    def __init__(
        self,
        name: int = 8,
        epoch_size: int = 512,
        skip_samples: int = 175,
        feature_size: int = 16,
        channels: tuple = (1, 2, 3),
        backend: str = "host",
    ):
        self._jit_cache = None
        self.set_wavelet_name(name)
        self.set_epoch_size(epoch_size)
        self.set_skip_samples(skip_samples)
        self.set_feature_size(feature_size)
        self.channels = tuple(channels)  # 1-based, WaveletTransform.java:47
        self.backend = backend  # property: assignment invalidates the cache

    @property
    def backend(self) -> str:
        return self._backend

    @backend.setter
    def backend(self, value: str) -> None:
        # the jitted extractor closure is backend/dtype-specific
        self._backend = value
        self._jit_cache = None

    # -- setters with the reference's validation ranges ---------------

    def set_wavelet_name(self, name: int) -> None:
        if 0 <= name <= 17:
            self.name = name
            self._jit_cache = None
        else:
            raise ValueError("Wavelet Name must be >= 0 and <= 17")

    def set_epoch_size(self, epoch_size: int) -> None:
        if 0 < epoch_size <= constants.POSTSTIMULUS_SAMPLES:
            self.epoch_size = epoch_size
            self._jit_cache = None
        else:
            raise ValueError(
                f"Epoch Size must be > 0 and <= {constants.POSTSTIMULUS_SAMPLES}"
            )

    def set_skip_samples(self, skip_samples: int) -> None:
        if 0 < skip_samples <= constants.POSTSTIMULUS_SAMPLES:
            self.skip_samples = skip_samples
            self._jit_cache = None
        else:
            raise ValueError(
                f"Skip Samples must be > 0 and <= {constants.POSTSTIMULUS_SAMPLES}"
            )

    def set_feature_size(self, feature_size: int) -> None:
        if 0 < feature_size <= 1024:
            self.feature_size = feature_size
            self._jit_cache = None
        else:
            raise ValueError("Feature Size must be > 0 and <= 1024")

    # -- extraction ----------------------------------------------------

    @property
    def feature_dimension(self) -> int:
        # WaveletTransform.java:149-152
        return self.feature_size * len(self.channels) // self.DOWN_SMPL_FACTOR

    def extract_batch(self, epochs: np.ndarray) -> np.ndarray:
        n_samples = np.asarray(epochs).shape[-1]
        if self.skip_samples + self.epoch_size > n_samples:
            # the Java reference fails loudly here (AIOOBE); don't let
            # numpy slicing silently truncate the analysis window
            raise ValueError(
                f"skip_samples ({self.skip_samples}) + epoch_size "
                f"({self.epoch_size}) exceeds the epoch length ({n_samples})"
            )
        if self.backend in ("xla-compact", "xla-compact-bf16"):
            import jax.numpy as jnp

            from ..ops import dwt as dwt_xla

            bf16 = self.backend == "xla-compact-bf16"
            if self._jit_cache is None:
                self._jit_cache = dwt_xla.make_compact_extractor(
                    wavelet_index=self.name,
                    epoch_size=self.epoch_size,
                    feature_size=self.feature_size,
                    dtype=jnp.bfloat16 if bf16 else jnp.float32,
                )
            # slice on the HOST and BEFORE any dtype copy: the
            # device-resident buffer (and the transfer) must be the
            # compact window, and converting the full-width array
            # first would copy the dead columns just to drop them
            x = np.asarray(epochs)
            ch_idx = [c - 1 for c in self.channels]
            if ch_idx != list(range(x.shape[1])):
                x = x[:, ch_idx, :]
            x = np.ascontiguousarray(
                x[:, :, self.skip_samples : self.skip_samples + self.epoch_size],
                dtype=np.float32,
            )
            if bf16:
                # host-side cast for the same residency reason (the
                # xla-bf16 backend's rule): 3072 B/epoch on device
                import ml_dtypes

                x = x.astype(ml_dtypes.bfloat16)
            return np.asarray(self._jit_cache(x), dtype=np.float32)
        if self.backend in ("xla", "xla-bf16"):
            import jax.numpy as jnp

            from ..ops import dwt as dwt_xla

            if self._jit_cache is None:
                self._jit_cache = dwt_xla.make_batched_extractor(
                    wavelet_index=self.name,
                    epoch_size=self.epoch_size,
                    skip_samples=self.skip_samples,
                    feature_size=self.feature_size,
                    channels=self.channels,
                    dtype=(
                        jnp.bfloat16
                        if self.backend == "xla-bf16"
                        else jnp.float32
                    ),
                )
            x = np.asarray(epochs)
            if self.backend == "xla-bf16":
                # convert on the host so the device-RESIDENT buffer
                # (and the transfer) is bf16 — casting inside the jit
                # would leave the dominant HBM read at full width
                import ml_dtypes

                x = x.astype(ml_dtypes.bfloat16)
            return np.asarray(self._jit_cache(x), dtype=np.float32)
        if self.backend == "pallas":
            from ..ops import dwt_pallas

            if self._jit_cache is None:
                self._jit_cache = dwt_pallas.make_batched_extractor_pallas(
                    wavelet_index=self.name,
                    epoch_size=self.epoch_size,
                    skip_samples=self.skip_samples,
                    feature_size=self.feature_size,
                )
            arr = np.asarray(epochs, np.float32)
            # same channel selection as the host/xla backends
            ch_idx = [c - 1 for c in self.channels]
            if ch_idx != list(range(arr.shape[1])):
                arr = arr[:, ch_idx, :]
            return np.asarray(self._jit_cache(arr))
        return self._extract_batch_host(np.asarray(epochs, dtype=np.float64))

    def _extract_batch_host(self, epochs: np.ndarray) -> np.ndarray:
        ch_idx = [c - 1 for c in self.channels]
        sl = epochs[:, ch_idx, self.skip_samples : self.skip_samples + self.epoch_size]
        coeffs = dwt_host.dwt_coefficients(sl, self.name, self.feature_size)
        flat = coeffs.reshape(epochs.shape[0], -1)
        return dwt_host.l2_normalize_seq(flat)

    def cache_id(self) -> tuple:
        """Full config identity for the feature cache: wavelet family,
        window geometry, coefficient count, channel set, and the
        PRECISION CLASS. The backend itself is deliberately absent —
        the host/xla/pallas f32-or-better backends compute the same
        features to rung tolerance (io/provider's ladder contract) —
        but the bf16 backends trade ~2e-3 absolute feature deviation
        for bandwidth (module docstring), far past that tolerance, so
        they key separately: a bf16 entry must never satisfy an
        f32-class request, or vice versa."""
        precision = "bf16" if "bf16" in self._backend else "f32"
        return (
            "dwt", self.name, self.epoch_size, self.skip_samples,
            self.feature_size, tuple(self.channels), precision,
        )

    # -- config equality (WaveletTransform.java:223-244) ---------------

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, WaveletTransform)
            and self.epoch_size == other.epoch_size
            and self.skip_samples == other.skip_samples
            and self.name == other.name
            and self.feature_size == other.feature_size
        )

    def __hash__(self) -> int:
        result = self.epoch_size
        for v in (self.skip_samples, self.name, self.feature_size):
            result = 31 * result + v
        return result

    def __repr__(self) -> str:
        return (
            f"DWT: EPOCH_SIZE: {self.epoch_size} FEATURE_SIZE: "
            f"{self.feature_size} WAVELETNAME: {self.name} "
            f"SKIP_SAMPLES: {self.skip_samples}"
        )
