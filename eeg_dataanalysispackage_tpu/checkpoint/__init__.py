"""Checkpoint / resume subsystem.

The reference's only persistence is whole-model save/load driven by
``save_clf``/``load_clf`` query params (MLlib ``model.save`` dirs,
DL4J ``ModelSerializer`` — SURVEY.md section 5 'Checkpoint / resume');
a crashed training run restarts from scratch. This module adds the
TPU-native equivalent plus what the reference lacks: step-numbered
checkpoints of the *full training state* (params + optimizer state)
with atomic writes, retention, and mid-run resume.

Two layers:

- :class:`CheckpointManager` — step-numbered pytree checkpoints
  (flax msgpack payload + JSON metadata, atomic tmp-dir rename,
  ``max_to_keep`` retention).
- :func:`run_resumable` — drives a jitted train step over batches,
  checkpointing every ``save_every`` steps and resuming from the
  latest step after interruption.
"""

from .manager import CheckpointManager, run_resumable

__all__ = ["CheckpointManager", "run_resumable"]
