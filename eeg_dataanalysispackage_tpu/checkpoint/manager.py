"""Step-numbered pytree checkpointing with atomic writes and resume.

Replaces (and extends) the reference's model persistence
(LogisticRegressionClassifier.java:144-152, DecisionTreeClassifier.java:157-165,
NeuralNetworkClassifier.java:171-187): instead of whole-model blobs
written once after training, any pytree — typically
``{"params": ..., "opt": ...}`` from ``parallel.train.make_train_step``
— can be saved per step and restored mid-run. Device arrays are pulled
to host before serialization, so sharded training states checkpoint
transparently; restore re-stages onto whatever sharding the template
carries.

Layout::

    <directory>/
      step_00000010/
        state.msgpack    flax.serialization payload
        metadata.json    {"step": 10, "extra": {...}}
      step_00000020/ ...

Writes go to a ``.tmp-<step>`` sibling first and are renamed into
place (atomic on posix), so a crash mid-write never corrupts the
latest checkpoint — the failure-recovery property SURVEY.md section 5
notes the reference lacks entirely.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

_STEP_RE = re.compile(r"^step_(\d{8})$")


def _fsync_directory(directory: str) -> bool:
    """fsync a directory fd, making a just-completed rename durable.

    Without it the data blocks are safe (the file fd was fsynced) but
    the *directory entry* may still live only in the page cache: a
    power loss right after a "successful" atomic write could replay as
    a zero-length (or missing) artifact. Best-effort — some platforms
    and filesystems refuse O_RDONLY directory fds; those callers keep
    the old (weaker) guarantee rather than failing the write. Returns
    False on refusal so durability-critical callers (the plan journal,
    whose lost terminal record a fleet peer would re-run) can count
    the degraded guarantee.
    """
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return False
    try:
        os.fsync(fd)
    except OSError:
        return False
    finally:
        os.close(fd)
    return True


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-safe small-file write: tmp sibling + ``os.replace``.

    The byte-level form of the checkpoint store's tmp-then-rename
    discipline, for single-file artifacts (run reports, metrics
    dumps): a crash mid-write leaves the previous content (or
    nothing), never a truncated file. The full durability recipe:
    fsync the tmp file (data blocks on disk), rename into place, then
    fsync the directory (the rename itself on disk) — so a crash
    right after this function returns can no longer surface the
    artifact as a zero-length file.
    """
    directory = os.path.dirname(path) or "."
    tmp = os.path.join(
        directory, f".tmp-{os.getpid()}-{os.path.basename(path)}"
    )
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_directory(directory)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def _to_host(tree):
    """Device arrays -> host numpy (gathers sharded arrays)."""
    return jax.tree_util.tree_map(np.asarray, tree)


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)
        self._recover()

    def _recover(self) -> None:
        """Clean up after a crash mid-save.

        ``.tmp-*`` dirs are partial writes — discarded. ``.old-<step>``
        dirs are displaced previous checkpoints: if the crash hit
        between the two renames of an overwrite, the final dir is
        missing and the old data is moved back; otherwise the
        overwrite completed and the old copy is deleted.
        """
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.startswith(".tmp-"):
                shutil.rmtree(path)
            elif name.startswith(".old-"):
                final = os.path.join(self.directory, "step_" + name[5:])
                if os.path.exists(final):
                    shutil.rmtree(path)
                else:
                    os.rename(path, final)

    # -- inventory -----------------------------------------------------

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(
                os.path.join(self.directory, name, "state.msgpack")
            ):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    # -- save / restore ------------------------------------------------

    def save(self, step: int, state: Any, extra: Optional[Dict] = None) -> str:
        """Atomically write ``state`` (any pytree) for ``step``."""
        final = self._step_dir(step)
        tmp = os.path.join(self.directory, f".tmp-{step:08d}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
                f.write(serialization.to_bytes(_to_host(state)))
            with open(os.path.join(tmp, "metadata.json"), "w") as f:
                json.dump({"step": step, "extra": extra or {}}, f)
            old = os.path.join(self.directory, f".old-{step:08d}")
            if os.path.exists(final):
                # displace rather than delete: a crash between these
                # renames is repaired by _recover(), so the previous
                # valid checkpoint is never lost
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(final, old)
            os.rename(tmp, final)
            if os.path.exists(old):
                shutil.rmtree(old)
        finally:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
        self._enforce_retention()
        return final

    def restore(self, template: Any, step: Optional[int] = None) -> Tuple[Any, Dict]:
        """Restore (state, metadata) for ``step`` (default: latest).

        ``template`` supplies the pytree structure (e.g. a fresh
        ``init_state(key)``); restored leaves adopt the template's
        sharding when it carries jax arrays.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        d = self._step_dir(step)
        with open(os.path.join(d, "state.msgpack"), "rb") as f:
            host_state = serialization.from_bytes(_to_host(template), f.read())
        with open(os.path.join(d, "metadata.json")) as f:
            metadata = json.load(f)

        def _restage(tpl, host):
            if isinstance(tpl, jax.Array):
                return jax.device_put(host, tpl.sharding)
            return host

        state = jax.tree_util.tree_map(_restage, template, host_state)
        return state, metadata

    def read_metadata(self, step: Optional[int] = None) -> Dict:
        """The ``metadata.json`` payload for ``step`` (default:
        latest) WITHOUT deserializing the state — callers that need a
        shape or a counter out of ``extra`` before they can build a
        restore template (the serving lifecycle's candidate buffers)
        read it here."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        with open(os.path.join(self._step_dir(step), "metadata.json")) as f:
            return json.load(f)

    def clear(self) -> None:
        """Delete every checkpoint under the directory.

        Called when the run the checkpoints protected has COMPLETED:
        they exist to survive a crash, and leaving them would make
        the next run under the same directory restore a finished
        trajectory and silently skip its own training
        (``run_resumable`` skips steps below ``latest_step``).
        """
        for step in self.all_steps():
            shutil.rmtree(self._step_dir(step))

    def _enforce_retention(self) -> None:
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        for step in steps[: max(0, len(steps) - self.max_to_keep)]:
            shutil.rmtree(self._step_dir(step))


def run_resumable(
    manager: CheckpointManager,
    init_state: Callable[[], Any],
    train_step: Callable,
    batches: Iterable,
    save_every: int = 10,
    on_step: Optional[Callable[[int, Any], None]] = None,
):
    """Drive ``train_step`` over ``batches`` with periodic checkpoints.

    ``batches`` yields argument tuples for
    ``train_step(state, *batch) -> (state, loss)``; steps already
    recorded under ``manager`` are skipped, so re-invoking after a
    crash continues from the latest checkpoint instead of step 0 (the
    recovery story the reference lacks — its failure policy is 'log
    and continue', SURVEY.md section 5).

    Returns (state, last_step).
    """
    latest = manager.latest_step()
    if latest is None:
        state, start = init_state(), 0
    else:
        state, _ = manager.restore(init_state(), step=latest)
        start = latest
    step = start
    for i, batch in enumerate(batches):
        if i < start:
            continue  # already trained in a previous incarnation
        state, loss = train_step(state, *batch)
        step = i + 1
        if on_step is not None:
            on_step(step, loss)
        if step % save_every == 0:
            manager.save(step, state, extra={"loss": float(loss)})
    if step > start and step % save_every != 0:
        manager.save(step, state)
    return state, step
