"""Observability: stage timers, metrics, profiler hooks, logging setup.

The reference's observability is log4j timestamps plus whatever the
Spark UI exposes (SURVEY.md section 5 'Tracing / profiling' — no
first-party tracing at all). This module is the TPU-native upgrade:

- :class:`StageTimer`  — wall-clock accumulation per pipeline stage
  (ingest / feature extraction / train / test), queryable and
  renderable, replacing "read the log4j timestamps";
- :class:`Metrics`     — process-wide counters/gauges with JSON export
  (the dropwizard-metrics equivalent that Spark dragged in);
- :func:`trace` / :func:`annotate` — ``jax.profiler`` hooks: one
  context manager around a run produces an XLA trace viewable in
  TensorBoard/Perfetto; ``annotate`` names host-side regions inside it;
- :func:`configure_logging` — timestamped console + optional rolling
  file handler; the log path comes from the ``LOGFILE_NAME`` env var,
  mirroring the reference's ``-Dlogfile.name`` system property
  (log4j.xml:23-31).
"""

from __future__ import annotations

import contextlib
import json
import logging
import logging.handlers
import os
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional


class StageTimer:
    """Accumulates wall time per named stage; reentrant-safe per name."""

    def __init__(self) -> None:
        self._totals: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                self._totals[name] += elapsed
                self._counts[name] += 1

    def total(self, name: str) -> float:
        return self._totals[name]

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {"seconds": self._totals[name], "count": self._counts[name]}
                for name in self._totals
            }

    def report(self) -> str:
        rows = sorted(self.as_dict().items(), key=lambda kv: -kv[1]["seconds"])
        width = max((len(n) for n, _ in rows), default=5)
        lines = [
            f"{name:<{width}}  {v['seconds']:9.4f}s  x{v['count']}"
            for name, v in rows
        ]
        return "\n".join(lines)


class Metrics:
    """Counters and gauges with JSON export."""

    def __init__(self) -> None:
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._lock = threading.Lock()

    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
            }

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


#: process-wide default registry (modules may also build their own)
metrics = Metrics()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler.trace`` around a region; no-op if unavailable.

    The produced trace covers device (XLA) activity and annotated host
    regions — open ``log_dir`` with TensorBoard's profile plugin or
    Perfetto.
    """
    try:
        import jax.profiler as jp
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        yield
        return
    jp.start_trace(log_dir)
    try:
        yield
    finally:
        jp.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named host-side region inside a profiler trace (TraceAnnotation)."""
    try:
        import jax.profiler as jp

        cm = jp.TraceAnnotation(name)
    except Exception:  # pragma: no cover
        yield
        return
    with cm:
        yield


def save_memory_profile(path: str) -> bool:
    """Snapshot live device-memory allocations to ``path`` (pprof
    format, ``jax.profiler.save_device_memory_profile``). Returns
    False when the backend does not support memory profiling instead
    of raising — callers treat it as best-effort observability.
    """
    try:
        import jax.profiler as jp

        jp.save_device_memory_profile(path)
        return True
    except Exception as e:
        logging.getLogger(__name__).warning(
            "device memory profile unavailable (%s): %s", path, e
        )
        return False


def configure_logging(
    level: int = logging.INFO,
    logfile: Optional[str] = None,
) -> None:
    """Console + optional daily-rolling file logging.

    ``logfile`` defaults to the ``LOGFILE_NAME`` env var, the analogue
    of the reference's ``-Dlogfile.name`` injection at spark-submit
    time (log4j.xml:23-31, README 'Deployment'); when neither is set,
    console only.
    """
    handlers: list = [logging.StreamHandler()]
    logfile = logfile or os.environ.get("LOGFILE_NAME")
    if logfile:
        os.makedirs(os.path.dirname(logfile) or ".", exist_ok=True)
        handlers.append(
            logging.handlers.TimedRotatingFileHandler(
                logfile, when="midnight", backupCount=7
            )
        )
    logging.basicConfig(
        level=level,
        format="%(asctime)s.%(msecs)03d %(levelname)s %(name)s - %(message)s",
        datefmt="%H:%M:%S",
        handlers=handlers,
        force=True,
    )
