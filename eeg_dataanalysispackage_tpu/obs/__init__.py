"""Observability: stage timers, metrics, profiler hooks, logging setup.

The reference's observability is log4j timestamps plus whatever the
Spark UI exposes (SURVEY.md section 5 'Tracing / profiling' — no
first-party tracing at all). This module is the TPU-native upgrade:

- :class:`StageTimer`  — wall-clock accumulation per pipeline stage
  (ingest / feature extraction / train / test), queryable and
  renderable, replacing "read the log4j timestamps";
- :class:`Metrics`     — process-wide counters/gauges with JSON export
  (the dropwizard-metrics equivalent that Spark dragged in);
- :func:`trace` / :func:`annotate` — ``jax.profiler`` hooks: one
  context manager around a run produces an XLA trace viewable in
  TensorBoard/Perfetto; ``annotate`` names host-side regions inside it;
- :func:`configure_logging` — timestamped console + optional rolling
  file handler; the log path comes from the ``LOGFILE_NAME`` env var,
  mirroring the reference's ``-Dlogfile.name`` system property
  (log4j.xml:23-31).
"""

from __future__ import annotations

import contextlib
import json
import logging
import logging.handlers
import os
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

from . import domain as _domain


class StageTimer:
    """Accumulates wall time per named stage; reentrant-safe per name.

    Tracks total/count plus min/max (mean derives) per stage — the
    shape the run report (obs/report.py) embeds, so a report diff can
    tell "one slow call" from "uniformly slower".
    """

    def __init__(self) -> None:
        self._totals: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self._mins: Dict[str, float] = {}
        self._maxs: Dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                self._totals[name] += elapsed
                self._counts[name] += 1
                if name not in self._mins or elapsed < self._mins[name]:
                    self._mins[name] = elapsed
                if name not in self._maxs or elapsed > self._maxs[name]:
                    self._maxs[name] = elapsed

    def total(self, name: str) -> float:
        # .get, not the defaultdict: probing a never-recorded stage
        # must not seed a zero-count row that as_dict would divide by
        return self._totals.get(name, 0.0)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "seconds": self._totals[name],
                    "count": self._counts[name],
                    "min_s": self._mins.get(name, 0.0),
                    "max_s": self._maxs.get(name, 0.0),
                    "mean_s": (
                        self._totals[name] / max(1, self._counts[name])
                    ),
                }
                for name in self._totals
            }

    def report(self) -> str:
        """Aligned per-stage table, slowest first (name ties broken
        alphabetically so identical timings render identically)."""
        rows = sorted(
            self.as_dict().items(),
            key=lambda kv: (-kv[1]["seconds"], kv[0]),
        )
        width = max((len(n) for n, _ in rows), default=5)
        cwidth = max(
            (len(str(v["count"])) for _, v in rows), default=1
        )
        lines = [
            f"{name:<{width}}  {v['seconds']:9.4f}s  "
            f"x{v['count']:<{cwidth}}  "
            f"mean {v['mean_s']:9.4f}s  min {v['min_s']:9.4f}s  "
            f"max {v['max_s']:9.4f}s"
            for name, v in rows
        ]
        return "\n".join(lines)


class Metrics:
    """Counters and gauges with JSON export.

    The process-wide :data:`metrics` instance is the default sink
    every subsystem counts into, which made per-run accounting
    impossible: counters leaked across fan-out legs and repeated
    ``execute()`` calls in one process. :meth:`scope` fixes the
    scoping — it registers a fresh child ``Metrics`` that receives a
    copy of every count/gauge for the duration of the ``with`` block
    (the pipeline builder opens one per run and hands it to the run
    report), while the global keeps accumulating as the default sink.
    :meth:`reset` zeroes an instance outright (test isolation,
    operator "start a fresh window").
    """

    def __init__(self) -> None:
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._scopes: list = []
        self._lock = threading.Lock()
        #: set on the process-wide default sink only: counts recorded
        #: there additionally mirror into the active RunDomain's
        #: per-plan child (obs/domain.py), so two concurrent plans'
        #: counters never cross — the scope() fan-out alone cannot
        #: tell the plans apart (it receives EVERY thread's counts)
        self._route_domains = False

    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value
            scopes = list(self._scopes)
        for scope in scopes:
            scope.count(name, value)
        if self._route_domains:
            d = _domain.current()
            if d is not None and d.metrics is not None:
                d.metrics.count(name, value)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value
            scopes = list(self._scopes)
        for scope in scopes:
            scope.gauge(name, value)
        if self._route_domains:
            d = _domain.current()
            if d is not None and d.metrics is not None:
                d.metrics.gauge(name, value)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
            }

    def reset(self) -> None:
        """Zero all counters and gauges (active scopes are kept)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()

    @contextlib.contextmanager
    def scope(self) -> Iterator["Metrics"]:
        """A per-run child registry: every count/gauge recorded on
        this instance while the block is open is mirrored into the
        yielded fresh ``Metrics`` — per-run numbers without giving up
        the process-wide default sink."""
        child = Metrics()
        with self._lock:
            self._scopes.append(child)
        try:
            yield child
        finally:
            with self._lock:
                self._scopes.remove(child)

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


#: process-wide default registry (modules may also build their own)
metrics = Metrics()
# only the default sink routes into per-plan domains: a domain's own
# child registry (or any other private Metrics) must not re-route,
# which would double-count
metrics._route_domains = True


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler.trace`` around a region; no-op if unavailable.

    The produced trace covers device (XLA) activity and annotated host
    regions — open ``log_dir`` with TensorBoard's profile plugin or
    Perfetto.
    """
    try:
        import jax.profiler as jp
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        yield
        return
    jp.start_trace(log_dir)
    try:
        yield
    finally:
        jp.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named host-side region inside a profiler trace (TraceAnnotation)."""
    try:
        import jax.profiler as jp

        cm = jp.TraceAnnotation(name)
    except Exception:  # pragma: no cover
        yield
        return
    with cm:
        yield


def save_memory_profile(path: str) -> bool:
    """Snapshot live device-memory allocations to ``path`` (pprof
    format, ``jax.profiler.save_device_memory_profile``). Returns
    False when the backend does not support memory profiling instead
    of raising — callers treat it as best-effort observability.
    """
    try:
        import jax.profiler as jp

        jp.save_device_memory_profile(path)
        return True
    except Exception as e:
        logging.getLogger(__name__).warning(
            "device memory profile unavailable (%s): %s", path, e
        )
        return False


def configure_logging(
    level: int = logging.INFO,
    logfile: Optional[str] = None,
) -> None:
    """Console + optional daily-rolling file logging.

    ``logfile`` defaults to the ``LOGFILE_NAME`` env var, the analogue
    of the reference's ``-Dlogfile.name`` injection at spark-submit
    time (log4j.xml:23-31, README 'Deployment'); when neither is set,
    console only.
    """
    handlers: list = [logging.StreamHandler()]
    logfile = logfile or os.environ.get("LOGFILE_NAME")
    if logfile:
        os.makedirs(os.path.dirname(logfile) or ".", exist_ok=True)
        handlers.append(
            logging.handlers.TimedRotatingFileHandler(
                logfile, when="midnight", backupCount=7
            )
        )
    logging.basicConfig(
        level=level,
        format="%(asctime)s.%(msecs)03d %(levelname)s %(name)s - %(message)s",
        datefmt="%H:%M:%S",
        handlers=handlers,
        force=True,
    )
