"""Structured span/event telemetry: the tracing half of ``obs``.

The reference's observability is log4j timestamps plus the Spark UI
(SURVEY.md §5 — no first-party tracing), and until now this build
stopped at process-global counters plus a wall-clock StageTimer whose
report died in a log line. This module adds the missing layer: a
thread-safe, zero-dependency **span recorder** — hierarchical spans
with ids / parent ids / monotonic timestamps / attributes, a
context-manager API, bounded in-memory retention, an optional JSONL
sink, and a ring buffer of recent *events* that the flight recorder
(obs/report.py) dumps when a run dies.

Design mirrors :mod:`obs.chaos`: one process-global active recorder,
installed for the scope of a run (``recording(...)``), and module
-level :func:`span` / :func:`event` entry points that are a single
global-``None`` check when telemetry is off — instrumented code pays
nothing unless a run opted in (``report=`` / ``EEG_TPU_RUN_REPORT_DIR``).
Telemetry observes, never steers: enabling it leaves
ClassificationStatistics bit-identical (pinned in
tests/test_telemetry.py).

Span model:

- every span has ``id``, ``parent`` (span id or None for the root),
  ``name``, ``start``/``end`` (seconds since the recorder was
  created, ``time.perf_counter`` based), ``thread``, ``attrs``;
- nesting is tracked per thread (a thread-local stack), so the
  parallel-ingest pool's parse spans land as children of the run root
  rather than corrupting another thread's stack;
- *events* are point-in-time marks (``chaos.fired``,
  ``feature_cache.hit``, ``circuit.opened`` …) attached to the current
  span and retained in the recorder's bounded ring;
- when a recorder is active, every span also emits a
  ``jax.profiler.TraceAnnotation`` so host spans line up with XLA
  activity in a TensorBoard/Perfetto trace captured via
  ``trace_path=``.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from . import domain as _domain

#: finished spans kept in memory per recorder; beyond this, spans are
#: still counted (and written to the JSONL sink) but not retained
DEFAULT_MAX_SPANS = 10_000
#: recent events retained for the flight recorder
DEFAULT_RING_CAPACITY = 512
#: events attached per span before the span only counts them
_MAX_EVENTS_PER_SPAN = 64

#: per-replica trace sink directory: when set (and a plan carries a
#: trace id) every finished span also appends to
#: ``<dir>/trace-<segment>.jsonl`` — the durable cross-replica trace a
#: lease takeover CONTINUES under the original trace id
ENV_TRACE_DIR = "EEG_TPU_TRACE_DIR"

_SEGMENT_BAD = re.compile(r"[^a-zA-Z0-9._-]")


class SpanRecorder:
    """Hierarchical span/event recorder for one run. Thread-safe.

    ``jsonl_path`` appends one JSON line per finished span and per
    event (``{"kind": "span"|"event", ...}``) — the durable form of
    the trace; the in-memory lists are bounded working state for the
    run report.
    """

    def __init__(
        self,
        name: str = "run",
        jsonl_path: Optional[str] = None,
        max_spans: int = DEFAULT_MAX_SPANS,
        ring_capacity: int = DEFAULT_RING_CAPACITY,
    ):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._t0 = time.perf_counter()
        self.wall_start = time.time()
        self._local = threading.local()
        self._spans: List[Dict[str, Any]] = []
        self._dropped_spans = 0
        self._max_spans = int(max_spans)
        self._ring: "collections.deque" = collections.deque(
            maxlen=int(ring_capacity)
        )
        self._jsonl_path = jsonl_path
        self._jsonl_file = None
        self._jsonl_failed = False
        self._jsonl_closed = False
        # cross-replica trace context (set_trace); spans carry
        # trace_id/span_id/parent_id in the trace sink, ids made
        # globally unique by the segment prefix (the replica id)
        self.trace_id: Optional[str] = None
        self.trace_segment: Optional[str] = None
        self._trace_path: Optional[str] = None
        self._trace_file = None
        self._trace_failed = False
        # the root span is open for the recorder's whole life and
        # closed by finish(); orphan threads parent onto it
        self.root: Dict[str, Any] = {
            "id": next(self._ids),
            "parent": None,
            "name": name,
            "start": 0.0,
            "end": None,
            "thread": threading.current_thread().name,
            "attrs": {},
            "events": [],
        }

    # -- time ----------------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    # -- cross-replica trace context -----------------------------------

    def set_trace(
        self,
        trace_id: str,
        trace_dir: Optional[str] = None,
        segment: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Join this recorder to a distributed trace: all spans carry
        ``trace_id`` and segment-prefixed globally-unique span ids,
        and (with ``trace_dir``) append to ``trace-<segment>.jsonl``
        in it. The file is opened in APPEND mode — a replica's
        successive plans share one segment file, and a surviving
        replica's takeover segment lands next to the dead holder's
        (``plan_admin trace`` stitches them back into one tree).

        ``attrs`` (plan_id, takeover, ...) land on the root span and
        on a ``segment`` header line, so a stitcher knows the takeover
        boundary even when the dead holder never closed its root.
        """
        self.trace_id = str(trace_id)
        segment = segment or f"pid{os.getpid()}"
        self.trace_segment = _SEGMENT_BAD.sub("_", str(segment))
        self.root["attrs"].update(attrs)
        if trace_dir:
            self._trace_path = os.path.join(
                trace_dir, f"trace-{self.trace_segment}.jsonl"
            )
            self._trace_sink({
                "kind": "segment",
                "trace_id": self.trace_id,
                "segment": self.trace_segment,
                "root_span_id": self._span_id(self.root["id"]),
                "wall_start": self.wall_start,
                "attrs": dict(self.root["attrs"]),
            })

    def _span_id(self, local_id: Optional[int]) -> Optional[str]:
        if local_id is None:
            return None
        return f"{self.trace_segment}:{local_id}"

    def _trace_line(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "kind": "span",
            "trace_id": self.trace_id,
            "segment": self.trace_segment,
            "span_id": self._span_id(rec["id"]),
            "parent_id": self._span_id(rec["parent"]),
            "name": rec["name"],
            "wall_start": self.wall_start + rec["start"],
            "wall_end": (
                None if rec["end"] is None
                else self.wall_start + rec["end"]
            ),
            "thread": rec["thread"],
            "attrs": rec["attrs"],
        }

    def _trace_sink(self, line: Dict[str, Any]) -> None:
        if self._trace_path is None or self._trace_failed:
            return
        with self._lock:
            try:
                if self._trace_file is None:
                    os.makedirs(
                        os.path.dirname(self._trace_path) or ".",
                        exist_ok=True,
                    )
                    self._trace_file = open(self._trace_path, "a")
                self._trace_file.write(
                    json.dumps(line, sort_keys=True, default=str) + "\n"
                )
                self._trace_file.flush()
            except OSError:
                # a broken trace sink never kills the run it observes
                self._trace_failed = True
                self._trace_file = None

    # -- thread-local span stack ---------------------------------------

    def _stack(self) -> List[Dict[str, Any]]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current_span(self) -> Dict[str, Any]:
        stack = self._stack()
        return stack[-1] if stack else self.root

    # -- recording -----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Dict[str, Any]]:
        """Open a child of the calling thread's current span; the
        span closes (and is retained/sunk) when the block exits, with
        ``error`` recorded if the block raised."""
        stack = self._stack()
        rec = {
            "id": next(self._ids),
            "parent": self.current_span()["id"],
            "name": name,
            "start": self._now(),
            "end": None,
            "thread": threading.current_thread().name,
            "attrs": dict(attrs),
            "events": [],
        }
        stack.append(rec)
        try:
            with _annotation(name):
                yield rec
        except BaseException as e:
            rec["attrs"]["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            rec["end"] = self._now()
            stack.pop()
            self._finish_span(rec)

    def _finish_span(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._spans) < self._max_spans:
                self._spans.append(rec)
            else:
                self._dropped_spans += 1
        self._sink({"kind": "span", **_span_line(rec)})
        if self.trace_id is not None:
            self._trace_sink(self._trace_line(rec))

    def event(self, name: str, **attrs: Any) -> None:
        """Point-in-time mark on the current span; retained in the
        flight-recorder ring."""
        span = self.current_span()
        rec = {
            "t": self._now(),
            "span": span["id"],
            "span_name": span["name"],
            "name": name,
            "attrs": dict(attrs),
        }
        with self._lock:
            self._ring.append(rec)
            if len(span["events"]) < _MAX_EVENTS_PER_SPAN:
                span["events"].append(rec)
        self._sink({"kind": "event", **rec})

    def set_attr(self, name: str, value: Any) -> None:
        """Attach an attribute to the calling thread's current span."""
        self.current_span()["attrs"][name] = value

    def finish(self) -> None:
        """Close the root span and latch the JSONL sink closed — a
        straggler thread (e.g. a stranded prefetch producer) finishing
        a span later must not silently reopen the file."""
        if self.root["end"] is None:
            self.root["end"] = self._now()
            self._sink({"kind": "span", **_span_line(self.root)})
            if self.trace_id is not None:
                self._trace_sink(self._trace_line(self.root))
        with self._lock:
            self._jsonl_closed = True
            if self._jsonl_file is not None:
                try:
                    self._jsonl_file.close()
                except OSError:
                    pass
                self._jsonl_file = None
            if self._trace_file is not None:
                try:
                    self._trace_file.close()
                except OSError:
                    pass
                self._trace_file = None

    # -- introspection -------------------------------------------------

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def recent_events(self) -> List[Dict[str, Any]]:
        """The flight-recorder ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def summary(self) -> Dict[str, Any]:
        """Aggregate view for the run report: per-name count/total/
        min/max seconds plus retention accounting."""
        by_name: Dict[str, Dict[str, float]] = {}
        with self._lock:
            spans = list(self._spans)
            dropped = self._dropped_spans
        for s in spans:
            dur = (s["end"] if s["end"] is not None else self._now()) - s["start"]
            agg = by_name.setdefault(
                s["name"],
                {"count": 0, "seconds": 0.0, "min_s": dur, "max_s": dur},
            )
            agg["count"] += 1
            agg["seconds"] += dur
            agg["min_s"] = min(agg["min_s"], dur)
            agg["max_s"] = max(agg["max_s"], dur)
        for agg in by_name.values():
            agg["seconds"] = round(agg["seconds"], 6)
            agg["min_s"] = round(agg["min_s"], 6)
            agg["max_s"] = round(agg["max_s"], 6)
        return {
            "root": self.root["name"],
            "wall_start": self.wall_start,
            "span_count": len(spans) + dropped + 1,
            "dropped_spans": dropped,
            "by_name": dict(sorted(by_name.items())),
        }

    # -- JSONL sink ----------------------------------------------------

    def _sink(self, line: Dict[str, Any]) -> None:
        if self._jsonl_path is None or self._jsonl_failed:
            return
        with self._lock:
            if self._jsonl_closed:
                return
            try:
                if self._jsonl_file is None:
                    # "w", not "a": one recorder = one run = one trace
                    # file — repeated runs into a fixed report dir
                    # (EEG_TPU_RUN_REPORT_DIR) replace the trace the
                    # same way run_report.json is replaced
                    self._jsonl_file = open(self._jsonl_path, "w")
                self._jsonl_file.write(
                    json.dumps(line, sort_keys=True, default=str) + "\n"
                )
                self._jsonl_file.flush()
            except OSError:
                # a broken sink must never kill (or slow) the run it
                # observes — drop the sink, keep the in-memory trace
                self._jsonl_failed = True
                self._jsonl_file = None


def _span_line(rec: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: rec[k] for k in ("id", "parent", "name", "start", "end",
                               "thread", "attrs")}
    out["events"] = len(rec["events"])
    return out


@contextlib.contextmanager
def _annotation(name: str) -> Iterator[None]:
    """``jax.profiler.TraceAnnotation`` alongside the span, so host
    spans line up with XLA traces; best-effort."""
    try:
        import jax.profiler as jp

        cm = jp.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        yield
        return
    with cm:
        yield


# -- process-global active recorder (the obs.chaos pattern) -------------

_RECORDER: Optional[SpanRecorder] = None


def active_recorder() -> Optional[SpanRecorder]:
    """The recorder observing the CALLING thread: its run domain's
    recorder when the thread executes (or adopted) a scheduled plan
    with telemetry, else the process-global installation — so two
    concurrent plans' spans land in two traces, while the single-run
    ``recording(...)`` path behaves exactly as before.

    Telemetry-off cost is one thread-local read plus the global
    check (was: one global read before fault domains existed) —
    still O(1) and allocation-free, the contract hot-path
    instrumentation relies on."""
    d = _domain.current()
    if d is not None and d.recorder is not None:
        return d.recorder
    return _RECORDER


def install(recorder: SpanRecorder) -> SpanRecorder:
    global _RECORDER
    _RECORDER = recorder
    return recorder


def uninstall() -> None:
    global _RECORDER
    _RECORDER = None


@contextlib.contextmanager
def recording(recorder: SpanRecorder) -> Iterator[SpanRecorder]:
    """Scoped installation; restores whatever recorder was active
    before (nested runs keep their own traces)."""
    global _RECORDER
    previous = _RECORDER
    _RECORDER = recorder
    try:
        yield recorder
    finally:
        _RECORDER = previous
        recorder.finish()


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[Optional[Dict[str, Any]]]:
    """Module-level span entry point; yields the live span record (or
    None when telemetry is off — a thread-local read plus a global
    check and an empty context, the cheap-when-off contract
    instrumented code relies on)."""
    rec = active_recorder()
    if rec is None:
        yield None
        return
    with rec.span(name, **attrs) as s:
        yield s


def event(name: str, **attrs: Any) -> None:
    """Module-level event entry point; no-op without a recorder."""
    rec = active_recorder()
    if rec is not None:
        rec.event(name, **attrs)


def set_attr(name: str, value: Any) -> None:
    """Attach an attribute to the current span; no-op without a
    recorder."""
    rec = active_recorder()
    if rec is not None:
        rec.set_attr(name, value)
