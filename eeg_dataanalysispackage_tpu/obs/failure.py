"""Failure detection + elastic recovery for training runs.

The reference has no failure handling beyond "log and continue":
``loadData`` swallows every exception (OffLineDataProvider.java:95-97),
unloadable files are skipped (:157-161), ``Main`` prints stack traces
(Main.java:46-50), and a crashed training run restarts from scratch
(SURVEY.md section 5 "Failure detection / elastic recovery: None").
This module is the TPU-native upgrade, layered on the atomic
checkpoint store (``checkpoint.manager``):

- :func:`probe_devices` — active health check: a tiny jitted program
  is dispatched to every device and fetched; devices that error or
  exceed a deadline are reported failed (the liveness signal Spark got
  from executor heartbeats);
- :class:`DivergenceSentinel` — numeric failure detector over the loss
  stream: non-finite values or a sustained explosion relative to a
  rolling window raise :class:`TrainingDiverged` at the step that went
  bad rather than poisoning every parameter silently;
- :func:`elastic_train` — a bounded-restart driver around
  ``checkpoint.run_resumable``: on a transient failure it restores the
  latest checkpoint, re-probes device health, and replays only the
  un-checkpointed steps — the recovery story the reference lacks.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any, Callable, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


class TrainingDiverged(RuntimeError):
    """Raised by :class:`DivergenceSentinel` when the loss stream goes
    non-finite or explodes."""


class DeviceProbeResult:
    def __init__(self, healthy: List, failed: List[Tuple[Any, str]],
                 latencies_s: List[float]):
        self.healthy = healthy
        self.failed = failed
        self.latencies_s = latencies_s

    @property
    def all_healthy(self) -> bool:
        return not self.failed

    def __repr__(self) -> str:
        return (
            f"DeviceProbeResult(healthy={len(self.healthy)}, "
            f"failed={[(str(d), e) for d, e in self.failed]})"
        )


def _probe_one(dev) -> float:
    x = jax.device_put(jnp.arange(8, dtype=jnp.float32), dev)
    got = float(jnp.sum(x * 2.0).block_until_ready())
    if got != 56.0:
        raise RuntimeError(f"bad arithmetic: {got!r}")
    return got


def probe_devices(devices=None, deadline_s: float = 30.0) -> DeviceProbeResult:
    """Dispatch a trivial computation to every device and fetch it.

    A device is failed if the dispatch/fetch raises or does not finish
    within ``deadline_s`` (the blocking fetch runs on a worker thread
    so a wedged device cannot hang the probe itself), or if it returns
    the wrong answer (memory corruption surfaces as bad arithmetic
    long before a crash).
    """
    import concurrent.futures

    devices = list(devices if devices is not None else jax.devices())
    healthy, failed, latencies = [], [], []
    # one thread per device: a wedged fetch strands its thread, never
    # the probe — so no `with` block, whose exit would join the
    # stranded thread and hang anyway
    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=max(1, len(devices)), thread_name_prefix="eeg-tpu-probe"
    )
    try:
        futures = {dev: pool.submit(_probe_one, dev) for dev in devices}
        start = time.perf_counter()
        for dev, fut in futures.items():
            remaining = deadline_s - (time.perf_counter() - start)
            try:
                fut.result(timeout=max(0.0, remaining))
                latencies.append(time.perf_counter() - start)
                healthy.append(dev)
            except concurrent.futures.TimeoutError:
                fut.cancel()
                failed.append((dev, f"no response within {deadline_s:.0f}s"))
            except Exception as e:  # device loss surfaces as runtime errors
                failed.append((dev, f"{type(e).__name__}: {e}"))
    finally:
        pool.shutdown(wait=False, cancel_futures=True)
    if failed:
        logger.warning("device probe failures: %s", failed)
    return DeviceProbeResult(healthy, failed, latencies)


class DivergenceSentinel:
    """Loss-stream failure detector.

    ``check(step, loss)`` raises :class:`TrainingDiverged` when the
    loss is non-finite, or when it exceeds ``explode_factor`` times the
    rolling median of the last ``window`` finite losses for
    ``patience`` consecutive steps (a single spiky minibatch is not a
    failure; a sustained explosion is).
    """

    def __init__(
        self,
        window: int = 20,
        explode_factor: float = 1e3,
        patience: int = 3,
    ):
        if window < 1 or patience < 1:
            raise ValueError("window and patience must be >= 1")
        self.window = window
        self.explode_factor = explode_factor
        self.patience = patience
        self._history: deque = deque(maxlen=window)
        self._strikes = 0

    def reset(self) -> None:
        """Forget history — called when a run restarts from a
        checkpoint, so replayed steps are not double-counted."""
        self._history.clear()
        self._strikes = 0

    def check(self, step: int, loss) -> None:
        value = float(loss)
        if not np.isfinite(value):
            raise TrainingDiverged(
                f"non-finite loss {value!r} at step {step}"
            )
        if len(self._history) == self.window:
            ref = float(np.median(self._history))
            if ref > 0 and value > self.explode_factor * ref:
                self._strikes += 1
                if self._strikes >= self.patience:
                    raise TrainingDiverged(
                        f"loss exploded at step {step}: {value:.3e} > "
                        f"{self.explode_factor:.0e} × rolling median "
                        f"{ref:.3e} for {self._strikes} steps"
                    )
            else:
                self._strikes = 0
        self._history.append(value)


def elastic_train(
    manager,
    init_state: Callable[[], Any],
    train_step: Callable,
    make_batches: Callable[[], Iterable],
    max_restarts: int = 3,
    save_every: int = 10,
    sentinel: Optional[DivergenceSentinel] = None,
    on_step: Optional[Callable[[int, Any], None]] = None,
    probe_on_failure: bool = True,
):
    """Run to completion across transient failures.

    Each incarnation drives ``checkpoint.run_resumable`` (which skips
    steps already checkpointed under ``manager``). When ``train_step``
    (or the batch source) raises, the failure is logged, device health
    is re-probed, and the run restarts from the latest checkpoint — at
    most ``max_restarts`` times, so a deterministic fault (e.g. a
    divergence that replays identically) eventually surfaces instead of
    looping forever. ``make_batches`` must return a fresh pass over the
    same batch sequence on every call.

    Returns (state, last_step, restarts_used).
    """
    from ..checkpoint.manager import run_resumable

    def stepper(step: int, loss) -> None:
        if sentinel is not None:
            sentinel.check(step, loss)
        if on_step is not None:
            on_step(step, loss)

    restarts = 0
    while True:
        try:
            state, last = run_resumable(
                manager,
                init_state,
                train_step,
                make_batches(),
                save_every=save_every,
                on_step=stepper,
            )
            return state, last, restarts
        except TrainingDiverged:
            # deterministic under the replay contract (same batches,
            # same restored state -> same divergence): restarting would
            # replay to the identical failure, so surface it at once
            raise
        except Exception as e:
            from .. import obs

            restarts += 1
            obs.metrics.count("elastic.restarts")
            logger.error(
                "training incarnation failed (%s: %s); restart %d/%d "
                "from step %s",
                type(e).__name__,
                e,
                restarts,
                max_restarts,
                manager.latest_step() or 0,
            )
            if restarts > max_restarts:
                obs.metrics.count("elastic.exhausted")
                raise
            if probe_on_failure:
                probe = probe_devices()
                if not probe.all_healthy:
                    obs.metrics.count("elastic.unhealthy_abort")
                    # dead hardware won't heal by replaying onto it:
                    # fail fast with the probe evidence so the
                    # scheduler/operator reconfigures the device set
                    raise RuntimeError(
                        f"device(s) unhealthy after training failure, "
                        f"not restarting: {probe!r}"
                    ) from e
            if sentinel is not None:
                # replayed steps must not double-count in the rolling
                # window / strike counter
                sentinel.reset()
