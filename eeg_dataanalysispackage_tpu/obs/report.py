"""Per-run report artifacts + the failure flight recorder.

One pipeline ``execute()`` under ``report=<dir>`` (or
``EEG_TPU_RUN_REPORT_DIR``) produces **one atomic ``run_report.json``**
— the machine-readable record that previously died in log lines:
query + resolved env knobs, device/backend + the degradation rung
actually used, StageTimer totals (min/max/mean), the per-run metrics
snapshot, feature/plan/compile-cache attribution, the span-tree
summary (obs/events.py), and XLA compilation count/seconds captured
via ``jax.monitoring`` listeners.

When the run dies instead — an unhandled pipeline exception, a
``CircuitOpenError``, an exhausted elastic-restart budget — the same
telemetry dumps ``crash_report.json``: the recent-event ring (the
flight recorder), metrics, the active chaos plan with per-rule firing
counts, and the degradation history, so a chaos-run failure is a
diagnosable artifact instead of a stack trace.

Render or diff the artifacts with ``tools/obs_report.py``
(cold-vs-warm, degraded-vs-clean). Schema identifiers:
``eeg-tpu-run-report/v1`` and ``eeg-tpu-crash-report/v1``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import traceback
from typing import Any, Dict, List, Optional

from . import domain as _domain
from . import events

logger = logging.getLogger(__name__)

#: enables telemetry for every run in the process (a ``report=`` query
#: parameter overrides per run; ``report=false`` opts one run out)
ENV_REPORT_DIR = "EEG_TPU_RUN_REPORT_DIR"

RUN_SCHEMA = "eeg-tpu-run-report/v1"
CRASH_SCHEMA = "eeg-tpu-crash-report/v1"

#: env knobs echoed into the report when set — the run's resolved
#: configuration surface beyond the query string itself
_ENV_KNOBS = (
    "EEG_TPU_INGEST_WORKERS",
    "EEG_TPU_PREFETCH_DEPTH",
    "EEG_TPU_FEATURE_CACHE_DIR",
    "EEG_TPU_NO_FEATURE_CACHE",
    "EEG_TPU_COMPILE_CACHE_DIR",
    "EEG_TPU_NO_COMPILE_CACHE",
    "EEG_TPU_PLAN_CACHE_FILE",
    "EEG_TPU_CIRCUIT_THRESHOLD",
    "EEG_TPU_CIRCUIT_COOLDOWN",
    "EEG_TPU_FAULTS",
    "EEG_TPU_RUN_REPORT_DIR",
    "EEG_TPU_TRACE_DIR",
    "EEG_TPU_OVERLAP",
    "EEG_TPU_PRECISION",
    "EEG_TPU_BF16_GATE_TOL",
    "EEG_TPU_INT8_GATE_TOL",
    "EEG_TPU_MEGA_GATE_TOL",
    "EEG_TPU_SERVE_FLUSH_US",
    "EEG_TPU_DECODE_FORMULATION",
    "EEG_PALLAS_MODE",
    "JAX_PLATFORMS",
)


def resolve_report_dir(query_map: Dict[str, str]) -> Optional[str]:
    """Where this run's report artifacts go, or None (telemetry off).

    ``report=<dir>`` wins; ``report=true`` writes next to
    ``result_path`` (its directory, else the cwd); ``report=false``
    opts out even when ``EEG_TPU_RUN_REPORT_DIR`` is set; otherwise
    the env var decides. Any explicit ``report=`` value beats the env
    var — the query is the per-run override.
    """
    value = query_map.get("report", "")
    if value == "false":
        return None
    if value and value != "true":
        return value
    if value == "true":
        result_path = query_map.get("result_path", "")
        return os.path.dirname(result_path) or "."
    return os.environ.get(ENV_REPORT_DIR) or None


# -- XLA compilation accounting (jax.monitoring) -------------------------

_COMPILE_DURATION_PREFIX = "/jax/core/compile/"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_monitor_lock = threading.Lock()
_active_monitors: List["CompilationMonitor"] = []
_listener_registered = False


def _on_duration(event_name: str, duration: float, **_kwargs) -> None:
    if not event_name.startswith(_COMPILE_DURATION_PREFIX):
        return
    with _monitor_lock:
        monitors = list(_active_monitors)
    # per-plan attribution: XLA compiles fire on the dispatching
    # thread, which under the multi-tenant executor carries its
    # plan's fault domain — a monitor owned by plan A must not count
    # plan B's compiles into A's run report. Ownerless monitors
    # (solo runs, direct construction in tests) keep the pre-domain
    # fan-out: every event, byte-identically.
    pid = _domain.current_plan_id()
    for m in monitors:
        if m.owner_plan_id is None or m.owner_plan_id == pid:
            m._record(event_name, duration)


def _ensure_listener() -> bool:
    """Register ONE process-wide jax.monitoring listener that fans out
    to the active monitors — jax has no per-listener deregistration,
    so per-run registration would leak a listener per run."""
    global _listener_registered
    with _monitor_lock:
        if _listener_registered:
            return True
        try:
            import jax.monitoring as jm

            jm.register_event_duration_secs_listener(_on_duration)
        except Exception as e:  # pragma: no cover - jax is a hard dep
            logger.warning("jax.monitoring unavailable: %s", e)
            return False
        _listener_registered = True
        return True


class CompilationMonitor:
    """Counts XLA compilations and their seconds for one run scope.

    ``owner_plan_id`` is captured from the active fault domain at
    scope entry: under the multi-tenant executor each plan's monitor
    only records compiles dispatched from that plan's (adopted)
    threads. Entered outside any domain, the monitor is ownerless and
    records every compile — the solo-run behavior."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._durations: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self.owner_plan_id: Optional[str] = None
        self.available = _ensure_listener()

    def __enter__(self) -> "CompilationMonitor":
        self.owner_plan_id = _domain.current_plan_id()
        with _monitor_lock:
            _active_monitors.append(self)
        return self

    def __exit__(self, *exc) -> None:
        with _monitor_lock:
            if self in _active_monitors:
                _active_monitors.remove(self)

    def _record(self, event_name: str, duration: float) -> None:
        key = event_name[len(_COMPILE_DURATION_PREFIX):]
        with self._lock:
            self._durations[key] = self._durations.get(key, 0.0) + duration
            self._counts[key] = self._counts.get(key, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            backend_key = _BACKEND_COMPILE_EVENT[
                len(_COMPILE_DURATION_PREFIX):
            ]
            return {
                "available": self.available,
                "compilations": self._counts.get(backend_key, 0),
                "backend_compile_s": round(
                    self._durations.get(backend_key, 0.0), 6
                ),
                "phases": {
                    k: {
                        "count": self._counts[k],
                        "seconds": round(self._durations[k], 6),
                    }
                    for k in sorted(self._durations)
                },
            }


# -- the per-run telemetry bundle ----------------------------------------

class RunTelemetry:
    """Everything one reported run carries: the span recorder (with a
    JSONL sink next to the report), the compilation monitor, and the
    degradation history the builder appends to. Constructed only when
    a run opted in, so un-reported runs pay the module's no-op path.
    """

    def __init__(self, query: str, query_map: Dict[str, str],
                 directory: str):
        self.query = query
        self.query_map = dict(query_map)
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.recorder = events.SpanRecorder(
            name="run",
            jsonl_path=os.path.join(directory, "spans.jsonl"),
        )
        self.compilation = CompilationMonitor()
        #: the scheduler's plan id when the run executed under the
        #: multi-tenant PlanExecutor (scheduler/executor.py) — ties
        #: the artifact to its journal record and to the plan-tagged
        #: circuit evidence; None for direct single-query runs
        #: (schema-stable)
        self.plan_id: Optional[str] = None
        #: builder-appended: one entry per degradation-ladder step
        self.degradation: List[Dict[str, Any]] = []
        #: backend attribution: {"requested": ..., "landed": ...}
        self.backend: Dict[str, Any] = {}
        #: population-training attribution (models/population.py):
        #: member count, fold/seed/grid shape, mode, compiles
        #: recorded, per-member accuracy — one block for train_clf=
        #: populations, {"legs": {name: block}} for fan-out runs;
        #: None when the run trained no population
        self.population: Optional[Dict[str, Any]] = None
        #: serving attribution (serve/service.py stats block): request
        #: outcome counters (completed/shed/deadline-exceeded/failed),
        #: batch coalescing stats, latency percentiles, watchdog and
        #: drain state — one block for ``serve=true`` runs; None when
        #: the run served nothing. Multiplexed services
        #: (serve/multiplex.py) additionally carry a ``tenants``
        #: sub-block — per tenant: lane, swap generation, outcome
        #: counters (submitted/completed/shed/deadline-exceeded/
        #: failed/retries), latency p50/p99, lifecycle state — plus
        #: ``tenant_quota`` and ``resident_weight_bytes``
        #: (tools/obs_report.py renders and diffs it)
        self.serve: Optional[Dict[str, Any]] = None
        #: model-lifecycle attribution (serve/lifecycle.py): feedback
        #: and partial-fit counters, the candidate's shadow window,
        #: swap-gate decisions, swaps/rollbacks/drift events, and the
        #: checkpoint/promoted-artifact state — one block for
        #: ``adapt=true`` serve runs; None when the run had no
        #: lifecycle manager (the default, schema-stable)
        self.lifecycle: Optional[Dict[str, Any]] = None
        #: workload attribution (pipeline/builder.py ``task=`` modes):
        #: the seizure runs record their epoching geometry (window/
        #: stride/label_overlap), class balance, and cost knobs here;
        #: None for the default P300 workload
        self.workload: Optional[Dict[str, Any]] = None
        #: bf16 feature-path attribution: {"requested", "used",
        #: "gate": {max_abs_dev, tolerance, ok, rows_checked}} when
        #: the run asked for precision=bf16 — the auto-disable
        #: decision lives HERE, never only in a log line; None for
        #: f32 runs (the default, schema-stable)
        self.precision: Optional[Dict[str, Any]] = None
        #: whether the fused ingest ran the double-buffered
        #: ingest/compute overlap (io/staging.prefetch stage_fn path);
        #: None when the run never reached the fused ingest
        self.overlap: Optional[bool] = None
        #: multi-device mesh attribution ({"requested", "rung",
        #: "shape", "devices", "population": {...}, "error"}) when the
        #: run asked for devices=/mesh_axes= — the rung actually used
        #: (mesh | single_device), the mesh shape, and the population
        #: engine's per-device member counts live HERE, never only in
        #: a log line; None for unmeshed runs (the default,
        #: schema-stable). The builder shares the dict with its
        #: ``mesh_resolved`` attribute, so late updates (a population
        #: fallback) land in the written report.
        self.mesh: Optional[Dict[str, Any]] = None
        #: cross-tenant prefix-dedup attribution (scheduler/dedup.py):
        #: {"role": "leader"|"follower", "prefix_key", "rows", and
        #: leader build_seconds / follower leader_plan + bytes_saved +
        #: seconds_saved} — who led and who drafted lives HERE, never
        #: only in a log line; None when the run shared no prefix
        #: work (the default, schema-stable)
        self.dedup: Optional[Dict[str, Any]] = None
        #: networked-submission attribution (gateway/): {"via",
        #: "idempotency_key", "client"} when the plan arrived through
        #: the HTTP front door; None for in-process submissions
        self.gateway: Optional[Dict[str, Any]] = None
        #: replica-fleet attribution (gateway/fleet.py +
        #: scheduler/lease.py): {"replica": the executing replica's
        #: id, "takeover": True when a peer's lease-claimed journal
        #: record was re-run here} — which front door actually
        #: executed the plan lives HERE, never only in a log line;
        #: None outside a replica fleet (the default, schema-stable)
        self.fleet: Optional[Dict[str, Any]] = None
        #: distributed trace id (gateway-minted, journaled with the
        #: plan so a lease takeover CONTINUES the trace on the
        #: surviving replica); None for untraced runs (schema-stable)
        self.trace_id: Optional[str] = None

    @property
    def report_path(self) -> str:
        return os.path.join(self.directory, "run_report.json")

    @property
    def crash_path(self) -> str:
        return os.path.join(self.directory, "crash_report.json")

    # -- shared payload pieces -----------------------------------------

    def _common(self, timers, metrics) -> Dict[str, Any]:
        from ..io import circuit, feature_cache
        from ..ops import plan_cache
        from ..utils import compile_cache
        from . import chaos

        try:
            import jax

            devices = jax.devices()
            device = {
                "platform": devices[0].platform,
                "device_count": len(devices),
            }
        except Exception as e:  # pragma: no cover - defensive
            device = {"platform": "unknown", "error": str(e)}
        plan = chaos.active_plan()
        pstats = plan_cache.stats()
        return {
            "query": self.query,
            "query_map": self.query_map,
            "plan_id": self.plan_id,
            # the shared circuit-breaker state at report time: which
            # endpoints are open/half-open, the plan-tagged evidence,
            # and the contributing plan ids — so a run fast-failed by
            # a breaker ANOTHER tenant opened carries the opener's
            # identity in its own artifact (docs/resilience.md)
            "circuit": circuit.snapshot(),
            "env": {
                k: os.environ[k] for k in _ENV_KNOBS if k in os.environ
            },
            "device": device,
            "backend": dict(self.backend),
            "population": self.population,
            "serve": self.serve,
            "lifecycle": self.lifecycle,
            "workload": self.workload,
            "precision": self.precision,
            "overlap": self.overlap,
            "mesh": self.mesh,
            "dedup": self.dedup,
            "gateway": self.gateway,
            "fleet": self.fleet,
            "trace": None if self.trace_id is None else {
                "trace_id": self.trace_id,
                "segment": self.recorder.trace_segment,
            },
            "degradation": list(self.degradation),
            "stages": timers.as_dict() if timers is not None else {},
            "metrics": metrics.snapshot() if metrics is not None else {},
            "caches": {
                "feature_cache": feature_cache.stats(),
                "plan_cache": {
                    "hits": pstats["hits"], "misses": pstats["misses"],
                },
                "compile_cache_dir": compile_cache.active_cache_dir(),
            },
            "xla": self.compilation.snapshot(),
            "chaos": None if plan is None else {
                "spec": plan.spec,
                "seed": plan.seed,
                "rules": {
                    point: {"calls": rule.calls, "fired": rule.fired}
                    for point, rule in plan.rules.items()
                },
            },
        }

    # -- artifacts ------------------------------------------------------

    def write_report(self, statistics, timers, metrics,
                     wall_s: float) -> str:
        """The success artifact: one atomic ``run_report.json``."""
        import hashlib

        self.recorder.finish()
        payload = {
            "schema": RUN_SCHEMA,
            "outcome": "ok",
            "wall_s": round(wall_s, 6),
            **self._common(timers, metrics),
            "spans": self.recorder.summary(),
            "statistics_sha256": hashlib.sha256(
                str(statistics).encode()
            ).hexdigest(),
            "accuracy": _accuracy_of(statistics),
            "classification": _classification_of(statistics),
        }
        _atomic_json(self.report_path, payload)
        # a stale crash artifact from an earlier failed run into the
        # same directory must not sit next to a fresh outcome=ok
        # report looking like it belongs to this run
        try:
            os.unlink(self.crash_path)
        except OSError:
            pass
        logger.info("run report written: %s", self.report_path)
        return self.report_path

    def _fleet_context(self) -> Optional[Dict[str, Any]]:
        """Replica id + live lease state for a fleet plan's crash
        artifact; None outside a fleet (schema-stable)."""
        if not self.fleet:
            return None
        try:
            from ..scheduler import lease as lease_mod

            return {
                "replica": self.fleet.get("replica"),
                "takeover": bool(self.fleet.get("takeover")),
                # leased device ordinals, when the fleet's device
                # pool placed this plan (scheduler/placement.py):
                # a crash artifact names WHICH chips the mesh held
                "devices": self.fleet.get("devices"),
                "held_leases": lease_mod.active_held(),
                "lease_counters": lease_mod.stats(),
            }
        except Exception:  # the dump must never mask the real error
            return {"replica": self.fleet.get("replica")}

    def dump_crash(self, error: BaseException, timers, metrics) -> str:
        """The failure artifact: flight-recorder ring + run state."""
        self.recorder.finish()
        payload = {
            "schema": CRASH_SCHEMA,
            "outcome": "error",
            "error": {
                "type": type(error).__name__,
                "message": str(error),
                "traceback": traceback.format_exception(
                    type(error), error, error.__traceback__
                ),
            },
            **self._common(timers, metrics),
            "spans": self.recorder.summary(),
            "events": self.recorder.recent_events(),
            # fleet context: when the plan died on a fleet replica the
            # crash artifact names the replica, the leases it held at
            # death, and the process's lease counters — next to the
            # chaos/degradation evidence already here
            "fleet_context": self._fleet_context(),
        }
        try:
            _atomic_json(self.crash_path, payload)
            # mirror of write_report's cleanup: an earlier run's
            # outcome=ok report must not sit next to this crash
            # looking like it describes the run that just died
            try:
                os.unlink(self.report_path)
            except OSError:
                pass
        except OSError as e:  # the dump must never mask the real error
            logger.error("crash report write failed: %s", e)
            return ""
        logger.error(
            "crash report written: %s (%s: %s)",
            self.crash_path, type(error).__name__, error,
        )
        return self.crash_path


def _classification_of(statistics) -> Any:
    """The extended imbalanced-class metric block (models/stats.py
    ``extended_summary``) for runs that opted into it (the seizure
    workload); None for plain-report runs. Dict-shaped statistics
    (population / fan-out) report per-member blocks."""
    try:
        if hasattr(statistics, "items") and not hasattr(
            statistics, "extended_report"
        ):
            members = {
                name: _classification_of(s)
                for name, s in statistics.items()
            }
            if any(v is not None for v in members.values()):
                return members
            return None
        if getattr(statistics, "extended_report", False):
            summary = statistics.extended_summary()
            return {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in summary.items()
            }
        return None
    except Exception:  # pragma: no cover - defensive, like _accuracy_of
        return None


def _accuracy_of(statistics) -> Any:
    """Per-classifier accuracy for fan-out results, a scalar
    otherwise; best-effort (None if statistics are exotic)."""
    try:
        if hasattr(statistics, "items") and not hasattr(
            statistics, "calc_accuracy"
        ):
            return {
                name: round(s.calc_accuracy(), 6)
                for name, s in statistics.items()
            }
        return round(statistics.calc_accuracy(), 6)
    except Exception:
        return None


def _atomic_json(path: str, payload: Dict[str, Any]) -> None:
    from ..checkpoint.manager import atomic_write_text

    atomic_write_text(
        path, json.dumps(payload, indent=2, sort_keys=True, default=str)
        + "\n"
    )
