"""Per-plan fault domains: the ambient execution context of ONE plan.

The observability/chaos layers were built process-global — one active
chaos plan (obs/chaos.py), one active span recorder (obs/events.py),
and run-scoped metrics implemented as a global fan-out
(obs.Metrics.scope) — which is exactly right for the reference's shape
(one query, one process, PipelineBuilder.java:94-295) and exactly
wrong for a resident executor running N plans concurrently: plan A's
``faults=`` spec would fire inside plan B, A's chaos firings would
count into B's per-run metrics, and both runs' spans would interleave
in one trace.

A :class:`RunDomain` is the fix: one small record carrying everything
that must be *per plan* —

- ``plan_id``   — the scheduler's identity for the plan (tags circuit
  -breaker evidence, run reports, logs);
- ``chaos``     — the plan's own parsed ``FaultPlan`` (or None);
- ``recorder``  — the plan's own ``SpanRecorder`` (or None);
- ``metrics``   — the plan's own ``obs.Metrics`` child (or None);

installed on the executing thread with :func:`activate` and *adopted*
by every worker thread a plan spawns (the staging producer, the ingest
parse pool, the serving batcher/watchdog) via :func:`capture` +
:func:`adopt`. Resolution in chaos/events/metrics is domain-first with
the process-global singleton as the fallback, so every pre-domain call
site — tests installing a global plan around a run, a bare recorder —
behaves byte-identically; the domain only *adds* isolation when a plan
carries its own state.

This module deliberately imports nothing from the rest of the package
(thread-local plumbing only), so chaos/events/metrics can all consult
it without import cycles.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator, Optional


class RunDomain:
    """The ambient per-plan context; immutable after construction in
    spirit (the executor builds one per plan execution)."""

    __slots__ = ("plan_id", "chaos", "recorder", "metrics")

    def __init__(
        self,
        plan_id: Optional[str] = None,
        chaos: Optional[Any] = None,
        recorder: Optional[Any] = None,
        metrics: Optional[Any] = None,
    ):
        self.plan_id = plan_id
        self.chaos = chaos
        self.recorder = recorder
        self.metrics = metrics

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"RunDomain(plan_id={self.plan_id!r}, "
            f"chaos={'on' if self.chaos is not None else 'off'}, "
            f"recorder={'on' if self.recorder is not None else 'off'}, "
            f"metrics={'on' if self.metrics is not None else 'off'})"
        )


_TLS = threading.local()


def current() -> Optional[RunDomain]:
    """The calling thread's innermost active domain, or None."""
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return None
    return stack[-1]


def current_plan_id() -> Optional[str]:
    """The active domain's plan id, or None — the tag circuit-breaker
    evidence and log lines use to attribute a failure to its tenant."""
    d = current()
    return None if d is None else d.plan_id


@contextlib.contextmanager
def activate(domain: Optional[RunDomain]) -> Iterator[Optional[RunDomain]]:
    """Install ``domain`` as the calling thread's ambient context for
    the block; nests (the innermost domain wins). ``None`` is a no-op
    so call sites can thread an optional domain without branching —
    which is also what lets worker threads *adopt* a captured domain
    unconditionally (:func:`capture` returns None outside any domain).
    """
    if domain is None:
        yield None
        return
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = []
        _TLS.stack = stack
    stack.append(domain)
    try:
        yield domain
    finally:
        stack.pop()


def capture() -> Optional[RunDomain]:
    """The domain a to-be-spawned worker thread should adopt: the
    spawner's current domain (None outside any plan). Call on the
    PARENT thread, hand the result to the child, and wrap the child's
    body in :func:`adopt`."""
    return current()


#: adoption is installation — a separate name only so thread bodies
#: read as what they are ("adopt the spawner's domain"), and so a
#: future divergence (e.g. read-only adoption) has a seam
adopt = activate
