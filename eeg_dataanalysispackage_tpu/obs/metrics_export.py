"""Fleet-grade metrics exposition: deterministic fixed-bucket latency
histograms and a Prometheus-text-format renderer over ``obs.metrics``
counters.

Two design rules make cross-replica aggregation lossless:

1. **Fixed buckets, integer counts, no reservoirs.** Every
   :class:`LatencyHistogram` in the process (and in every replica of a
   fleet) shares the same bucket bounds, so merging N replicas'
   histograms is exact element-wise integer addition — the fleet p99
   computed from the merged histogram is precisely the histogram-p99
   of the union of observations, something a sampling reservoir can
   never promise. The observation sum is kept in integer microseconds
   for the same reason: merge order cannot change a single bit.
2. **Deterministic text.** :func:`render` emits series sorted by
   (metric name, label set) with a fixed float format, so two scrapes
   of identical state are byte-identical — the property the golden
   exposition pin in the tests and the ``fleet_top`` differ rely on.

The renderer speaks the Prometheus text exposition format (v0.0.4):
``*_total`` counters, ``*_bucket{le=...}`` / ``*_sum`` / ``*_count``
histogram series, label values escaped per the spec (backslash,
double-quote, newline). Stdlib only, like the rest of ``obs/``.
"""

from __future__ import annotations

import bisect
import re
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

#: Shared latency bucket upper bounds, milliseconds. The +Inf bucket is
#: implicit (``counts`` carries one extra slot). Chosen to straddle the
#: serve path's observed range: sub-ms cache hits through multi-second
#: cold compiles.
BUCKET_BOUNDS_MS: Tuple[float, ...] = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0,
)

#: Prometheus content type for the /metrics endpoint.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class LatencyHistogram:
    """Bounded fixed-bucket latency histogram, mergeable by exact
    integer addition.

    Not thread-safe by itself — callers that observe from multiple
    threads hold their own lock (serve/batcher.py observes under its
    counters lock). ``sum`` is kept in integer microseconds so merges
    are exact; the exposition surface converts to milliseconds.
    """

    __slots__ = ("bounds", "counts", "count", "sum_us")

    def __init__(self, bounds: Sequence[float] = BUCKET_BOUNDS_MS):
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum_us = 0

    def observe(self, latency_ms: float) -> None:
        """Record one observation (milliseconds)."""
        ms = float(latency_ms)
        # le-buckets: an observation exactly on a bound lands in it
        self.counts[bisect.bisect_left(self.bounds, ms)] += 1
        self.count += 1
        self.sum_us += int(round(ms * 1000.0))

    @property
    def sum_ms(self) -> float:
        return self.sum_us / 1000.0

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other`` into this histogram in place — exact integer
        addition, the lossless cross-replica aggregation path."""
        if other.bounds != self.bounds:
            raise ValueError(
                f"cannot merge histograms with different bounds: "
                f"{self.bounds} vs {other.bounds}"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum_us += other.sum_us
        return self

    def snapshot(self) -> dict:
        """JSON-safe state (strict-JSON artifacts, /stats blocks)."""
        return {
            "bounds_ms": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "sum_ms": round(self.sum_ms, 3),
        }

    @classmethod
    def from_snapshot(cls, snap: Mapping) -> "LatencyHistogram":
        h = cls(snap["bounds_ms"])
        counts = [int(c) for c in snap["counts"]]
        if len(counts) != len(h.counts):
            raise ValueError("snapshot counts do not match bounds")
        h.counts = counts
        h.count = int(snap.get("count", sum(counts)))
        # sum_ms round-trips through the artifact at ms resolution
        h.sum_us = int(round(float(snap.get("sum_ms", 0.0)) * 1000.0))
        return h

    def attainment(self, objective_ms: float) -> float:
        """Fraction of observations at or under ``objective_ms``
        (resolved to the smallest bucket bound >= the objective — the
        histogram's conservative answer). 1.0 with no observations."""
        if self.count == 0:
            return 1.0
        idx = bisect.bisect_left(self.bounds, float(objective_ms))
        if idx >= len(self.bounds):
            return 1.0  # objective beyond the last finite bound
        return sum(self.counts[: idx + 1]) / self.count

    def quantile(self, q: float) -> Optional[float]:
        """Histogram quantile: the upper bound of the bucket where the
        cumulative count first reaches ``q`` of the total (None when
        empty; the last finite bound stands in for +Inf)."""
        if self.count == 0:
            return None
        target = q / 100.0 * self.count if q > 1.0 else q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target and c:
                if i < len(self.bounds):
                    return self.bounds[i]
                return self.bounds[-1]
        return self.bounds[-1]


def merge_all(
    hists: Iterable[LatencyHistogram],
) -> Optional[LatencyHistogram]:
    """Merge an iterable of histograms into a fresh one (None when
    empty) — the fleet aggregator's reduce step."""
    out: Optional[LatencyHistogram] = None
    for h in hists:
        if out is None:
            out = LatencyHistogram(h.bounds)
        out.merge(h)
    return out


def slo_block(
    hist: Optional[LatencyHistogram],
    requests: Mapping[str, float],
    objective_ms: float,
    availability_target: float,
) -> dict:
    """The per-tenant / per-service SLO verdict, computed from the
    deterministic histogram plus the outcome counters.

    - ``availability`` — completed / (completed + shed + failed +
      deadline_exceeded); 1.0 with no finished requests.
    - ``latency_attainment`` — fraction of completed requests within
      the latency objective (histogram-resolved).
    - ``error_budget_burn`` — observed bad fraction (the worse of the
      two objectives) over the allowed fraction ``1 - target``; > 1.0
      means the budget is burning faster than it accrues.
    """
    completed = float(requests.get("completed", 0) or 0)
    bad = sum(
        float(requests.get(k, 0) or 0)
        for k in ("shed", "failed", "deadline_exceeded")
    )
    total = completed + bad
    availability = 1.0 if total == 0 else completed / total
    attainment = hist.attainment(objective_ms) if hist else 1.0
    budget = max(1e-9, 1.0 - float(availability_target))
    burn = (1.0 - min(availability, attainment)) / budget
    return {
        "objective_ms": float(objective_ms),
        "availability_target": float(availability_target),
        "availability": round(availability, 6),
        "latency_attainment": round(attainment, 6),
        "error_budget_burn": round(burn, 4),
        "ok": burn <= 1.0,
        "requests_observed": int(total),
    }


# -- Prometheus text exposition ---------------------------------------

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(name: str, prefix: str = "eeg_tpu") -> str:
    """Counter/gauge name -> a legal Prometheus metric name
    (``scheduler.completed`` -> ``eeg_tpu_scheduler_completed``)."""
    base = _NAME_BAD.sub("_", name.strip())
    full = f"{prefix}_{base}" if prefix else base
    if full and full[0].isdigit():
        full = "_" + full
    return full


def escape_label_value(value: str) -> str:
    """Label-value escaping per the exposition format: backslash,
    double quote, newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value: float) -> str:
    """Deterministic number rendering: integers without a fractional
    part, floats via repr (shortest round-trip)."""
    f = float(value)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render(
    counters: Optional[Mapping[str, float]] = None,
    histograms: Optional[
        Sequence[Tuple[str, Mapping[str, str], LatencyHistogram]]
    ] = None,
    gauges: Optional[Mapping[str, float]] = None,
    info: Optional[Mapping[str, str]] = None,
    prefix: str = "eeg_tpu",
) -> str:
    """Render one deterministic exposition document.

    ``counters`` maps dotted names to values (``*_total`` series);
    ``histograms`` is a sequence of (dotted name, labels, histogram);
    ``info`` becomes a ``<prefix>_build_info`` gauge with the mapping
    as labels (the replica-identity series). Output is sorted by
    (metric name, label set) and ends with a newline.
    """
    out: List[str] = []
    if info:
        name = metric_name("build_info", prefix)
        out.append(f"# TYPE {name} gauge")
        out.append(f"{name}{_labels({k: str(v) for k, v in info.items()})} 1")
    for raw in sorted(counters or {}):
        name = metric_name(raw, prefix) + "_total"
        out.append(f"# TYPE {name} counter")
        out.append(f"{name} {_fmt((counters or {})[raw])}")
    for raw in sorted(gauges or {}):
        name = metric_name(raw, prefix)
        out.append(f"# TYPE {name} gauge")
        out.append(f"{name} {_fmt((gauges or {})[raw])}")
    seen_types = set()
    for raw, labels, hist in sorted(
        histograms or (),
        key=lambda t: (t[0], sorted((t[1] or {}).items())),
    ):
        name = metric_name(raw, prefix)
        if name not in seen_types:
            seen_types.add(name)
            out.append(f"# TYPE {name} histogram")
        base = dict(labels or {})
        cum = 0
        for i, bound in enumerate(hist.bounds):
            cum += hist.counts[i]
            le = {**base, "le": _fmt(bound)}
            out.append(f"{name}_bucket{_labels(le)} {cum}")
        cum += hist.counts[-1]
        out.append(f"{name}_bucket{_labels({**base, 'le': '+Inf'})} {cum}")
        out.append(f"{name}_sum{_labels(base)} {_fmt(round(hist.sum_ms, 3))}")
        out.append(f"{name}_count{_labels(base)} {hist.count}")
    return "\n".join(out) + "\n"


# -- scrape-side parser (fleet_top, bench assertions) ------------------

_SERIES = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_LABEL = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)"')


def _unescape(value: str) -> str:
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Parse an exposition document back into
    ``{metric_name: [(labels, value), ...]}`` — the scrape half of the
    round trip ``fleet_top`` and the bench assertions use. Comment and
    blank lines are skipped; +Inf parses to ``float('inf')``."""
    series: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES.match(line)
        if not m:
            continue
        labels = {
            lm.group("k"): _unescape(lm.group("v"))
            for lm in _LABEL.finditer(m.group("labels") or "")
        }
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        series.setdefault(m.group("name"), []).append((labels, value))
    return series


def histogram_from_series(
    series: Dict[str, List[Tuple[Dict[str, str], float]]],
    name: str,
    match: Optional[Mapping[str, str]] = None,
) -> Optional[LatencyHistogram]:
    """Rebuild a :class:`LatencyHistogram` from parsed ``_bucket`` /
    ``_sum`` / ``_count`` series (optionally narrowed to label values
    in ``match``) — exact, because the buckets are fixed and integer."""
    want = dict(match or {})

    def keep(labels: Dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in want.items())

    buckets = [
        (labels, v)
        for labels, v in series.get(name + "_bucket", [])
        if keep(labels)
    ]
    if not buckets:
        return None
    finite = sorted(
        {
            float(labels["le"])
            for labels, _ in buckets
            if labels.get("le") not in (None, "+Inf")
        }
    )
    hist = LatencyHistogram(finite or BUCKET_BOUNDS_MS)
    cum = {}
    for labels, v in buckets:
        le = labels.get("le")
        cum[float("inf") if le == "+Inf" else float(le)] = int(v)
    prev = 0
    for i, bound in enumerate(hist.bounds):
        c = cum.get(bound, prev)
        hist.counts[i] = c - prev
        prev = c
    hist.counts[-1] = cum.get(float("inf"), prev) - prev
    hist.count = sum(hist.counts)
    for labels, v in series.get(name + "_sum", []):
        if keep(labels):
            hist.sum_us = int(round(float(v) * 1000.0))
            break
    return hist
