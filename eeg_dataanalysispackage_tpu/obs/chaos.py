"""Deterministic fault injection: a seedable, process-global fault plan.

The reference's failure story is "log and continue" (``loadData``
swallows every exception, OffLineDataProvider.java:95-97) and its test
suite never exercises a failure path at all. This module is the chaos
half of the resilience story: named injection points threaded through
the I/O and device layers fire *deterministically* from a parsed fault
plan, so the retry/degradation/elastic-restart machinery is provable —
a chaos run under a fixed spec+seed replays bit-identically.

Spec grammar (query param ``faults=`` / env ``EEG_TPU_FAULTS``)::

    spec    := entry (';' entry)*
    entry   := 'seed=' int            -- plan seed (default 0)
             | point ':' directive
    point   := dotted name, e.g. remote.request, ingest.fused,
               staging.producer, device.step
    directive :=
        'p=' float                    -- fire each call with prob. p
                                         (seeded; deterministic)
        'once@' n                     -- fire exactly once, on the
                                         n-th call of the point
        'err@' n                      -- alias of once@n (reads better
                                         for step-indexed errors)
        'every@' n                    -- fire on every n-th call

Example: ``remote.request:p=0.2;ingest.fused:once@1;device.step:err@7``.

Injection points call :func:`maybe_fire`; with no plan installed the
call is a single global-None check — zero overhead, nothing recorded.
When a plan decides to fire, the point raises (``ChaosInjectedError``
by default, or the exception type the site passes so the fault lands
inside the site's existing retry contract) and the firing is counted
in ``obs.metrics`` under ``chaos.fired.<point>``.

Known points (the contract between specs and the codebase):

==================  ====================================================
``remote.request``  one HTTP request attempt (io/remote.py) — fires a
                    retryable ``RemoteIOError``, exercising
                    retry/backoff and the circuit breaker
``staging.producer``  one staged batch in the prefetch producer thread
                    (io/staging.py) — surfaces at the consumer
``ingest.fused``    one ``load_features_device`` backend attempt
                    (io/provider.py) — exercises the degradation ladder
``device.step``     one host-level train-step call (parallel/train.py
                    wrappers and the elastic chunk drivers in models/)
``serve.request``   one admitted serving request inside the batcher
                    (serve/batcher.py) — the request is retried or
                    failed with evidence, never silently dropped
``serve.batch``     one micro-batch execution of the serving
                    program (serve/batcher.py) — exercises the
                    deadline-aware batch retry path
``serve.adapt``     one partial-fit chunk of the serving lifecycle's
                    adapter (serve/lifecycle.py) — the chunk retries
                    (bounded) then drops, counted; the request path
                    is untouched
``serve.swap``      one promotion attempt of a staged candidate
                    (serve/lifecycle.py) — a failed swap leaves the
                    live model untouched and the candidate retained
                    (the gate retries after the next batch)
``scheduler.plan``  one execution attempt of a submitted plan inside
                    the multi-tenant executor (scheduler/runtime.py) —
                    the executor's per-plan retry budget absorbs it
``scheduler.journal``  one write-ahead journal write
                    (scheduler/journal.py) — the journal retries once,
                    then degrades to unjournaled (counted) rather than
                    failing the plan it records
``fleet.lease``     one lease-claim attempt (scheduler/lease.py) —
                    injected as ``OSError`` so it lands in the claim's
                    own degraded path: a failed claim is simply not a
                    claim (counted ``fleet.lease_claim_failures``);
                    the fleet scan loop retries next round
``fleet.heartbeat`` one lease heartbeat touch (scheduler/lease.py) —
                    injected as ``OSError``: the beat is skipped
                    (counted), the lease ages toward breakability —
                    exactly what a wedged holder would look like
==================  ====================================================

Fault domains: a plan executed by the multi-tenant scheduler carries
its ``faults=`` spec in its own :class:`obs.domain.RunDomain`, so
:func:`active_plan` (and therefore every injection point) resolves the
*calling thread's plan's* fault plan first and falls back to the
process-global installation only outside any domain — plan A's chaos
cannot fire inside plan B (tests/test_scheduler.py). Worker threads a
plan spawns adopt its domain (io/staging, io/provider, serve/batcher),
so injection points on those threads stay inside the right domain.
"""

from __future__ import annotations

import logging
import os
import random
import re
import threading
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

from . import domain as _domain

logger = logging.getLogger(__name__)

#: env var consulted by the pipeline when no ``faults=`` query param
ENV_SPEC = "EEG_TPU_FAULTS"


class ChaosInjectedError(RuntimeError):
    """The default exception raised by a firing injection point."""


class FaultSpecError(ValueError):
    """A ``faults=`` spec string does not parse."""


_DIRECTIVE_RE = re.compile(
    r"^(?:p=(?P<p>[0-9.eE+-]+)|(?P<mode>once|err|every)@(?P<n>\d+))$"
)


class FaultRule:
    """One ``point:directive`` entry; thread-safe call accounting."""

    def __init__(self, point: str, mode: str, value: float):
        self.point = point
        self.mode = mode  # "p" | "once" | "every"
        self.value = value
        self.calls = 0
        self.fired = 0

    def should_fire(self, seed: int) -> bool:
        self.calls += 1
        if self.mode == "p":
            # seeded per (seed, point, call): same spec+seed replays
            # the identical firing sequence in any process
            rng = random.Random(f"{seed}:{self.point}:{self.calls}")
            hit = rng.random() < self.value
        elif self.mode == "once":
            hit = self.calls == int(self.value)
        else:  # every
            hit = self.calls % int(self.value) == 0
        if hit:
            self.fired += 1
        return hit

    def __repr__(self) -> str:
        tag = {"p": f"p={self.value}", "once": f"once@{int(self.value)}",
               "every": f"every@{int(self.value)}"}[self.mode]
        return (
            f"FaultRule({self.point}:{tag}, calls={self.calls}, "
            f"fired={self.fired})"
        )


class FaultPlan:
    """A parsed spec: rules keyed by injection point, plus the seed."""

    def __init__(self, rules: Dict[str, FaultRule], seed: int = 0,
                 spec: str = ""):
        self.rules = rules
        self.seed = seed
        self.spec = spec
        self._lock = threading.Lock()

    def should_fire(self, point: str) -> bool:
        rule = self.rules.get(point)
        if rule is None:
            return False
        with self._lock:
            return rule.should_fire(self.seed)

    def __repr__(self) -> str:
        return f"FaultPlan(seed={self.seed}, {list(self.rules.values())})"


def parse_fault_spec(spec: str, seed: int = 0) -> FaultPlan:
    """``faults=`` string -> :class:`FaultPlan` (see module grammar)."""
    rules: Dict[str, FaultRule] = {}
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            try:
                seed = int(entry[len("seed="):])
            except ValueError as e:
                raise FaultSpecError(f"bad seed in {entry!r}") from e
            continue
        point, sep, directive = entry.partition(":")
        if not sep or not point:
            raise FaultSpecError(
                f"fault entry {entry!r} is not 'point:directive' "
                f"(e.g. 'remote.request:p=0.2')"
            )
        m = _DIRECTIVE_RE.match(directive.strip())
        if m is None:
            raise FaultSpecError(
                f"bad directive {directive!r} for point {point!r}; "
                f"expected p=<float>, once@<n>, err@<n>, or every@<n>"
            )
        if m.group("p") is not None:
            try:
                p = float(m.group("p"))
            except ValueError as e:
                raise FaultSpecError(
                    f"bad probability in {entry!r}"
                ) from e
            if not 0.0 <= p <= 1.0:
                raise FaultSpecError(
                    f"probability {p} out of [0, 1] in {entry!r}"
                )
            rule = FaultRule(point.strip(), "p", p)
        else:
            n = int(m.group("n"))
            if n < 1:
                raise FaultSpecError(f"call index must be >= 1 in {entry!r}")
            mode = "every" if m.group("mode") == "every" else "once"
            rule = FaultRule(point.strip(), mode, float(n))
        rules[rule.point] = rule
    return FaultPlan(rules, seed=seed, spec=spec)


#: the process-global active plan; None = chaos off (the hot-path
#: no-op check every injection point performs)
_PLAN: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    """The fault plan governing the CALLING thread: its run domain's
    plan when the thread executes (or adopted) a scheduled plan that
    carries one, else the process-global installation. A domain
    without a chaos plan of its own does not shield the global — a
    test installing ``chaos.faults(...)`` around a plain pipeline run
    keeps injecting exactly as before. Chaos-off cost is one
    thread-local read plus the global check."""
    d = _domain.current()
    if d is not None and d.chaos is not None:
        return d.chaos
    return _PLAN


def install(spec_or_plan, seed: int = 0) -> FaultPlan:
    """Activate a fault plan process-wide; returns it."""
    global _PLAN
    plan = (
        spec_or_plan
        if isinstance(spec_or_plan, FaultPlan)
        else parse_fault_spec(spec_or_plan, seed=seed)
    )
    _PLAN = plan
    logger.warning("chaos fault plan installed: %r", plan)
    return plan


def uninstall() -> None:
    global _PLAN
    _PLAN = None


@contextmanager
def faults(spec: str, seed: int = 0) -> Iterator[FaultPlan]:
    """Scoped installation; restores whatever plan was active before."""
    global _PLAN
    previous = _PLAN
    plan = install(spec, seed=seed)
    try:
        yield plan
    finally:
        _PLAN = previous


def plan_from_env() -> Optional[str]:
    """The ``EEG_TPU_FAULTS`` spec string, or None when unset/empty."""
    return os.environ.get(ENV_SPEC) or None


def maybe_fire(point: str, exc_type: type = ChaosInjectedError) -> None:
    """The injection-point call. No plan installed -> immediate return
    (one thread-local read + the global check — the cheap-when-off
    contract). When the plan's
    rule for ``point`` fires, the firing is counted in ``obs.metrics``
    (``chaos.fired.<point>``) and ``exc_type`` is raised — sites pass
    the exception class their retry/degradation machinery already
    handles (e.g. ``RemoteIOError`` for ``remote.request``).
    """
    plan = active_plan()
    if plan is None:
        return
    if plan.should_fire(point):
        from .. import obs
        from . import events

        rule = plan.rules[point]
        obs.metrics.count(f"chaos.fired.{point}")
        # telemetry: the firing annotates the enclosing span and lands
        # in the flight-recorder ring, so a crash report carries the
        # exact injection that killed the run
        events.event(
            "chaos.fired", point=point, call=rule.calls,
            firing=rule.fired,
        )
        logger.warning(
            "chaos: firing %s (call %d, firing %d)",
            point, rule.calls, rule.fired,
        )
        raise exc_type(
            f"chaos: injected fault at {point} (call {rule.calls})"
        )
