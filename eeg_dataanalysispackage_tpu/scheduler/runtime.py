"""``execute_plan``: one validated plan, executed in its own fault
domain.

This is the run-orchestration half that used to live inline in
``PipelineBuilder.execute`` — persistent compile cache, chaos plan,
telemetry, per-run metrics, the crash flight recorder, the report
write — lifted out so the multi-tenant executor and the legacy
single-query entry point share ONE code path (the parity contract:
``PipelineBuilder.execute`` is now a thin shim over
``ExecutionPlan.parse`` + this function, and every statistic it
produced before the split it produces after, byte-identical).

The per-plan **fault domain** (obs/domain.py) is what changed shape:
the chaos plan, the span recorder, and the per-run metrics child are
no longer process-global installations but fields of a
:class:`~eeg_dataanalysispackage_tpu.obs.domain.RunDomain` activated
on the executing thread and adopted by every worker thread the plan
spawns. Two plans running concurrently therefore cannot see each
other's ``faults=`` spec, cannot count into each other's metrics
scope, and write two disjoint span trees and ``run_report.json``
artifacts — the fault-isolation pin in tests/test_scheduler.py.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Optional

from .. import obs
from ..obs import chaos, domain as run_domain

logger = logging.getLogger(__name__)

#: "no fault plan was passed — resolve one from the plan/env" (the
#: executor passes an explicit plan, possibly None, so retries share
#: one plan and its call accounting across attempts)
_RESOLVE = object()


def execute_plan(
    plan,
    builder,
    plan_id: Optional[str] = None,
    fault_plan=_RESOLVE,
    default_report_dir: Optional[str] = None,
    gateway: Optional[dict] = None,
    fleet: Optional[dict] = None,
    trace_id: Optional[str] = None,
    placement=None,
):
    """Run ``plan`` through ``builder`` inside a fresh fault domain;
    returns the statistics (and leaves the builder's per-run
    attributes — timers, telemetry, run_metrics, degradation history,
    precision/overlap/mesh resolution — populated exactly as the
    monolithic ``execute`` did).

    ``fault_plan`` — the parsed chaos plan governing this execution.
    Defaults to resolving ``plan.faults`` (or ``EEG_TPU_FAULTS``)
    fresh; the executor resolves once per submission and passes it in,
    so a retried plan keeps ONE set of rule call counters (a
    ``once@N`` fault absorbed by attempt 1 stays absorbed, a ``p=``
    stream keeps advancing instead of deterministically re-firing).

    ``default_report_dir`` — where the run's telemetry goes when the
    query itself didn't say (the executor assigns each plan its own
    directory under its report root); an explicit ``report=`` in the
    query — including ``report=false`` — always wins.

    ``gateway`` — networked-submission attribution (the HTTP front
    door's {"via", "idempotency_key", "client"} block) echoed into
    run_report.json, so an artifact names how its plan arrived.

    ``fleet`` — replica-fleet attribution (gateway/fleet.py's
    {"replica", "takeover"} block, plus the process's lease counters
    at execution time) echoed into run_report.json, so an artifact
    names WHICH replica executed its plan and whether by takeover.

    ``trace_id`` — the distributed trace this execution belongs to
    (gateway-minted, journaled in the plan meta so a takeover on a
    surviving replica CONTINUES the original trace). With
    ``EEG_TPU_TRACE_DIR`` set, spans additionally append to the
    per-replica trace sink — even when run reports are off, so a
    fleet's trace plane works without the per-plan report tree.

    ``placement`` — leased device ordinals granted by the fleet's
    device pool (scheduler/placement.py). When set, the builder's
    mesh is built from exactly these ``jax.devices()`` ordinals
    instead of a ``[:n]`` prefix slice, so concurrent plans on one
    host run on DISJOINT chips. Degradation unchanged: if the leased
    subset cannot build a mesh, the existing
    mesh→single-device→host ladder applies.
    """
    query_map = plan.query_map
    logger.info("query: %s", query_map)

    # persistent XLA compilation cache before any device work:
    # fresh-chip compiles of the fused variants ran 10-14 min in the
    # r4 sweep, and a repeat run of the same query must read the
    # serialized executable instead (utils/compile_cache)
    from ..utils import compile_cache

    cache_dir = compile_cache.enable_persistent_cache()
    if cache_dir:
        logger.info("persistent compile cache: %s", cache_dir)

    if fault_plan is _RESOLVE:
        spec = plan.faults or chaos.plan_from_env()
        fault_plan = (
            chaos.parse_fault_spec(spec, seed=plan.faults_seed)
            if spec
            else None
        )

    # structured run telemetry (obs/events.py + obs/report.py): the
    # report dir resolves from the query (report= / result_path /
    # EEG_TPU_RUN_REPORT_DIR) exactly as before; the executor's
    # per-plan default fills in only when the query said nothing.
    from ..obs import report as run_report

    builder.telemetry = None
    builder.degradation_history = []
    builder.precision_resolved = None
    builder.overlap_resolved = None
    builder.mesh_resolved = None
    builder.dedup_resolved = None
    builder.placement_devices = (
        tuple(placement) if placement else None
    )
    # fresh per run, like the metrics scope below: a reused builder
    # must not report run 1's stage seconds under run 2
    builder.timers = obs.StageTimer()
    report_dir = run_report.resolve_report_dir(query_map)
    if (
        report_dir is not None
        and plan_id is not None
        and not query_map.get("report", "")
    ):
        # the dir came from EEG_TPU_RUN_REPORT_DIR (no report= in the
        # query) and this is an executor-identified plan: N concurrent
        # tenants resolving the ambient env var to ONE directory would
        # clobber each other's run_report.json/spans.jsonl (last
        # atomic write wins) — each gets its plan's subdirectory, the
        # same per-plan tree an executor report root builds. A solo
        # run (no plan id) keeps the env dir itself, byte-identically.
        report_dir = os.path.join(report_dir, plan_id)
    if (
        report_dir is None
        and default_report_dir
        and query_map.get("report", "") != "false"
    ):
        report_dir = default_report_dir
    if report_dir:
        try:
            builder.telemetry = run_report.RunTelemetry(
                plan.query, query_map, report_dir
            )
            builder.telemetry.plan_id = plan_id
            builder.telemetry.gateway = gateway
            builder.telemetry.fleet = fleet
            builder.telemetry.trace_id = trace_id
            # the builder appends rung drops as they happen; the
            # report reads this shared list
            builder.telemetry.degradation = builder.degradation_history
        except OSError as e:
            logger.warning(
                "run telemetry unavailable (%s: %s); running "
                "unreported", type(e).__name__, e,
            )
    telemetry = builder.telemetry
    comp_scope = (
        telemetry.compilation
        if telemetry is not None
        else contextlib.nullcontext()
    )

    # the distributed-trace sink is independent of run reports: a
    # gateway-minted trace id plus EEG_TPU_TRACE_DIR turns on span
    # recording even for an unreported plan (bounded standalone
    # recorder), so the fleet's trace plane works without the
    # per-plan report tree. A takeover re-submits with the journaled
    # trace id, so the surviving replica's segment CONTINUES the
    # original trace.
    recorder = None if telemetry is None else telemetry.recorder
    standalone_recorder = None
    if trace_id:
        from ..obs import events
        trace_dir = os.environ.get(events.ENV_TRACE_DIR)
        if trace_dir:
            if recorder is None:
                recorder = standalone_recorder = events.SpanRecorder(
                    name="plan", max_spans=512
                )
            recorder.set_trace(
                trace_id,
                trace_dir=trace_dir,
                segment=(fleet or {}).get("replica")
                or (gateway or {}).get("replica"),
                plan_id=plan_id,
                takeover=bool((fleet or {}).get("takeover")),
            )

    # the plan's fault domain: chaos spec, span recorder, and metrics
    # child all scoped to THIS plan's threads (worker threads adopt it
    # — io/staging, io/provider, serve/batcher)
    run_metrics = obs.Metrics()
    domain = run_domain.RunDomain(
        plan_id=plan_id,
        chaos=fault_plan,
        recorder=recorder,
        metrics=run_metrics,
    )
    builder.run_metrics = run_metrics

    start = time.perf_counter()
    try:
        return _run_in_domain(
            plan, builder, domain, comp_scope, telemetry,
            run_metrics, start,
        )
    finally:
        if standalone_recorder is not None:
            # close the report-less trace segment (flushes the root
            # span to the trace sink); telemetry-backed recorders are
            # finished by the report writer as before
            standalone_recorder.finish()


def _run_in_domain(
    plan, builder, domain, comp_scope, telemetry, run_metrics, start,
):
    with run_domain.activate(domain), comp_scope:
        try:
            # the scheduler's own injection point: one execution
            # attempt of a submitted plan (fires only when the
            # governing fault plan carries a scheduler.plan rule; the
            # executor's per-plan retry budget absorbs it)
            chaos.maybe_fire("scheduler.plan")
            # net-new observability: trace_path=<dir> wraps the run
            # in a jax.profiler trace (device + annotated host
            # activity), viewable in TensorBoard/Perfetto
            if plan.trace_path:
                with obs.trace(plan.trace_path):
                    statistics = builder._execute(plan)
            else:
                statistics = builder._execute(plan)
        except Exception as e:
            # flight recorder: dumped INSIDE the fault domain so the
            # crash artifact carries the active chaos plan with its
            # per-rule firing counts — and this plan's counters only
            if telemetry is not None:
                telemetry.dump_crash(e, builder.timers, run_metrics)
            raise
        if telemetry is not None:
            # written inside the domain too, so a SUCCESSFUL chaos
            # run's report still records the plan's per-rule
            # call/firing accounting; and guarded — a telemetry write
            # failure must never fail the run it observed
            try:
                telemetry.write_report(
                    statistics, builder.timers, run_metrics,
                    wall_s=time.perf_counter() - start,
                )
            except OSError as e:
                logger.error("run report write failed: %s", e)
    return statistics
