"""Cross-tenant plan-prefix dedup: common-subplan elimination over
the ``ExecutionPlan`` IR.

The workload mix this engine serves (cost-sensitive seizure detection,
P300 classification sweeps) makes repeated ingest+featurize prefixes
across tenants the dominant shared cost: ten tenants tuning classifier
knobs over the same recordings re-read and re-featurize the same bytes
ten times. The content-addressed feature cache (io/feature_cache.py)
already collapses the *store* — and its single-flight guard collapses
concurrent rebuilds of one entry — but every tenant still pays the
read+digest pass that derives the content key. This module lifts the
same idea one level up, to the plan itself: a plan's
:meth:`~eeg_dataanalysispackage_tpu.pipeline.plan.ExecutionPlan.prefix_key`
names its ingest+featurize half from the TYPED FIELDS ALONE (no I/O),
so two tenants whose plans share a canonical prefix can share one
in-memory ``(features, targets)`` build without either of them
touching the filesystem twice.

Protocol (mirrors the feature cache's :class:`~..io.feature_cache.BuildSlot`,
but value-carrying):

- the first plan to :meth:`PrefixRegistry.acquire` a key becomes the
  **leader**: it computes the prefix exactly as an undeduped run would
  (read, digest, feature-cache lookup, degradation ladder) and
  :meth:`~PrefixClaim.publish`-es the result;
- a concurrent plan acquiring the same key is a **follower**: it
  blocks — honouring the ambient deadline scope
  (:func:`~..io.deadline.cond_wait`) — until the leader publishes,
  then reuses the published arrays (marked read-only: no tenant can
  mutate another's prefix) and skips its entire ingest+featurize
  stage;
- a leader that FAILS (ladder exhausted, chaos it could not absorb)
  :meth:`~PrefixClaim.abandon`-s the entry; the first waiting follower
  is promoted to leader and computes its own prefix — chaos in the
  leader's fault domain can cost a follower time, never correctness
  (tests/test_dedup.py pins the fallback and the byte-identical
  statistics).

Isolation semantics are unchanged: the registry shares *values*, never
fault domains. Attribution (who led, who drafted behind them, bytes
and seconds saved) lands in each plan's OWN domain metrics
(``dedup.lead`` / ``dedup.hit`` / ``dedup.bytes_saved``) and in each
plan's ``run_report.json`` ``dedup`` block (obs/report.py).

Staleness contract: entries are keyed on the plan, not on file bytes
(keying on bytes would require the very read pass dedup exists to
skip), so the registry assumes input files are immutable for the life
of the process — the same assumption the resident serving engine makes
about its loaded classifier. Entries are bounded by an LRU capacity
(``EEG_TPU_PREFIX_CACHE_ENTRIES``, default 8); restart the process or
pass ``dedup=false`` / ``EEG_TPU_NO_PREFIX_DEDUP=1`` for mutable
inputs.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

#: set to "1" to disable prefix dedup process-wide
ENV_DISABLE = "EEG_TPU_NO_PREFIX_DEDUP"
#: LRU capacity of READY entries (building entries are never evicted)
ENV_CAPACITY = "EEG_TPU_PREFIX_CACHE_ENTRIES"

_DEFAULT_CAPACITY = 8

_BUILDING = "building"
_READY = "ready"


def _freeze(value: Any) -> None:
    """Mark every numpy array inside ``value`` read-only, recursively:
    published prefixes are shared across fault domains, and a tenant
    mutating a shared array would corrupt its neighbours silently."""
    if isinstance(value, np.ndarray):
        try:
            value.flags.writeable = False
        except ValueError:  # pragma: no cover - views of foreign buffers
            pass
    elif isinstance(value, (tuple, list)):
        for item in value:
            _freeze(item)


def _nbytes(value: Any) -> int:
    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, (tuple, list)):
        return sum(_nbytes(item) for item in value)
    return 0


class _Entry:
    __slots__ = ("state", "leader_plan", "value", "meta",
                 "build_seconds", "stored_at")

    def __init__(self, leader_plan: Optional[str]):
        self.state = _BUILDING
        self.leader_plan = leader_plan
        self.value: Any = None
        self.meta: Dict[str, Any] = {}
        self.build_seconds = 0.0
        self.stored_at = 0.0


class PrefixClaim:
    """One plan's stake in one prefix build.

    ``role`` is ``"leader"`` (compute, then :meth:`publish` — or let
    :meth:`settle` abandon on the error path) or ``"follower"``
    (``value``/``meta`` already populated from the leader's build).
    ``waited`` reports whether this claim blocked behind another
    tenant; ``leader_failed`` whether it was promoted after an
    abandon. :meth:`settle` is idempotent and belongs in a
    ``finally``: a leader that died without publishing or abandoning
    would block every follower until their deadlines."""

    __slots__ = ("registry", "key", "role", "plan_id", "value", "meta",
                 "leader_plan", "build_seconds", "bytes_saved",
                 "waited", "leader_failed", "_settled", "_started")

    def __init__(self, registry, key, role, plan_id, waited=False,
                 leader_failed=False):
        self.registry = registry
        self.key = key
        self.role = role
        self.plan_id = plan_id
        self.value: Any = None
        self.meta: Dict[str, Any] = {}
        self.leader_plan: Optional[str] = plan_id
        self.build_seconds = 0.0
        self.bytes_saved = 0
        self.waited = waited
        self.leader_failed = leader_failed
        self._settled = False
        self._started = time.perf_counter()

    def publish(self, value: Any,
                meta: Optional[Dict[str, Any]] = None) -> None:
        """Leader only: hand the computed prefix to the registry and
        wake every follower. The build time recorded is acquire-to-
        publish — the seconds a follower is credited with saving."""
        if self._settled or self.role != "leader":
            return
        self._settled = True
        self.build_seconds = time.perf_counter() - self._started
        self.registry._publish(
            self.key, value, meta or {}, self.plan_id,
            self.build_seconds,
        )

    def abandon(self) -> None:
        """Leader only: the build failed — release the entry so the
        first waiting follower is promoted to leader."""
        if self._settled or self.role != "leader":
            return
        self._settled = True
        self.registry._abandon(self.key)

    def settle(self) -> None:
        """Idempotent cleanup for ``finally`` blocks: an unpublished
        leader abandons; everything else is a no-op."""
        self.abandon()


class PrefixRegistry:
    """In-memory, process-local map of prefix key -> one computed
    ``(features, targets)``-shaped value, with single-flight build
    semantics and leader/follower attribution."""

    def __init__(self, capacity: Optional[int] = None):
        self._capacity = capacity
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._entries: "Dict[str, _Entry]" = {}
        #: insertion-ordered READY keys for LRU eviction
        self._leads = 0
        self._hits = 0
        self._leader_failures = 0
        self._evictions = 0

    def _cap(self) -> int:
        if self._capacity is not None:
            return self._capacity
        try:
            return max(1, int(
                os.environ.get(ENV_CAPACITY, _DEFAULT_CAPACITY)
            ))
        except ValueError:
            return _DEFAULT_CAPACITY

    # -- the acquire protocol -------------------------------------------

    def acquire(self, key: str,
                plan_id: Optional[str] = None) -> PrefixClaim:
        """Leader or follower claim for ``key``; blocks (deadline-
        aware) while another tenant is building it. Counts land in the
        CALLING thread's fault domain — acquire runs on the plan's own
        worker thread, so attribution is per-plan by construction."""
        from .. import obs
        from ..io import deadline as deadline_mod
        from ..obs import events

        waited = False
        with self._cond:
            while True:
                entry = self._entries.get(key)
                if entry is None:
                    entry = _Entry(plan_id)
                    self._entries[key] = entry
                    self._leads += 1
                    if waited:
                        self._leader_failures += 1
                    break
                if entry.state == _READY:
                    claim = PrefixClaim(
                        self, key, "follower", plan_id, waited=waited
                    )
                    claim.value = entry.value
                    claim.meta = dict(entry.meta)
                    claim.leader_plan = entry.leader_plan
                    claim.build_seconds = entry.build_seconds
                    claim.bytes_saved = _nbytes(entry.value)
                    entry.stored_at = time.monotonic()  # LRU touch
                    self._hits += 1
                    obs.metrics.count("dedup.hit")
                    obs.metrics.count(
                        "dedup.bytes_saved", claim.bytes_saved
                    )
                    events.event(
                        "dedup.hit", prefix=key,
                        leader=entry.leader_plan or "",
                        bytes_saved=claim.bytes_saved,
                    )
                    return claim
                # BUILDING: wait for the leader to publish or abandon
                waited = True
                obs.metrics.count("dedup.wait")
                deadline_mod.cond_wait(
                    self._cond,
                    lambda: self._entries.get(key) is not entry
                    or entry.state != _BUILDING,
                    f"prefix-dedup wait for {key}",
                )
        # out of the lock: leader bookkeeping
        obs.metrics.count("dedup.lead")
        if waited:
            # promoted after an abandon — the fallback the isolation
            # contract requires (the follower computes its own prefix)
            obs.metrics.count("dedup.leader_failed")
            events.event("dedup.leader_failed", prefix=key)
        events.event("dedup.lead", prefix=key)
        return PrefixClaim(
            self, key, "leader", plan_id, waited=waited,
            leader_failed=waited,
        )

    def _publish(self, key, value, meta, plan_id, build_seconds):
        from .. import obs
        from ..obs import events

        _freeze(value)
        with self._cond:
            entry = self._entries.get(key)
            if entry is None or entry.state != _BUILDING:
                return  # abandoned meanwhile (shouldn't happen)
            entry.state = _READY
            entry.value = value
            entry.meta = dict(meta)
            entry.leader_plan = plan_id
            entry.build_seconds = build_seconds
            entry.stored_at = time.monotonic()
            self._evict_locked()
            self._cond.notify_all()
        obs.metrics.count("dedup.publish")
        events.event(
            "dedup.publish", prefix=key,
            build_s=round(build_seconds, 4),
        )

    def _abandon(self, key):
        with self._cond:
            entry = self._entries.get(key)
            if entry is not None and entry.state == _BUILDING:
                del self._entries[key]
            self._cond.notify_all()

    def _evict_locked(self):
        ready = [
            (e.stored_at, k) for k, e in self._entries.items()
            if e.state == _READY
        ]
        cap = self._cap()
        if len(ready) <= cap:
            return
        ready.sort()
        for _, k in ready[: len(ready) - cap]:
            del self._entries[k]
            self._evictions += 1

    # -- introspection ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Process-wide dedup attribution — the bench's ``dedup``
        payload (hit ratio = follows / all acquisitions)."""
        with self._lock:
            total = self._leads + self._hits
            return {
                "leads": self._leads,
                "hits": self._hits,
                "leader_failures": self._leader_failures,
                "evictions": self._evictions,
                "entries": len(self._entries),
                "hit_ratio": (
                    round(self._hits / total, 6) if total else 0.0
                ),
            }

    def reset(self) -> None:
        """Drop entries and zero the counters (test/bench phase
        isolation). Never call with builds in flight."""
        with self._cond:
            self._entries.clear()
            self._leads = self._hits = 0
            self._leader_failures = self._evictions = 0
            self._cond.notify_all()


_registry = PrefixRegistry()


def registry() -> PrefixRegistry:
    return _registry


def stats() -> Dict[str, Any]:
    return _registry.stats()


def reset() -> None:
    _registry.reset()


def eligible(plan) -> bool:
    """Whether ``plan`` participates in prefix dedup: opted in
    (``dedup=`` defaults true, ``EEG_TPU_NO_PREFIX_DEDUP=1`` wins),
    batch mode (serving never materializes the batch prefix), and on a
    path that produces an in-memory feature matrix — the fused P300
    modes and every seizure run (host subband features ARE that
    workload's path). The host P300 path (``fe=dwt-8``) loads an epoch
    batch instead and is not deduped."""
    if plan is None or os.environ.get(ENV_DISABLE) == "1":
        return False
    if not getattr(plan, "dedup", True) or plan.serve:
        return False
    if plan.task == "seizure":
        return True
    return bool(plan.fused)


def acquire_for(plan) -> Optional[PrefixClaim]:
    """The builder-facing entry: a claim for the plan's prefix, or
    None when the plan is ineligible. The claim's attribution rides
    the ambient fault domain's plan id — and dedup is scoped to
    domain-bearing (executor/gateway-driven) runs ONLY: a solo
    ``PipelineBuilder`` run claims nothing, so its feature-cache
    hit/miss counters and read-exactly-once pins stay byte-identical
    to every pre-gateway release (cross-tenant sharing needs tenants)."""
    from ..obs import domain as run_domain

    if not eligible(plan):
        return None
    plan_id = run_domain.current_plan_id()
    if plan_id is None:
        return None
    key = plan.prefix_key()
    if key is None:
        return None
    return _registry.acquire(key, plan_id)
