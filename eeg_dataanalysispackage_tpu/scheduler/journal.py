"""Write-ahead plan journal: the crash-only half of the executor.

The reference driver is one query → one process
(PipelineBuilder.java:94-295): a crash loses the run and nobody
notices, because nobody submitted more than one. A resident executor
running ten plans owes its callers a different contract — the process
dying mid-batch must lose *nothing*: on restart every unfinished plan
resumes, every finished plan's record survives, and nothing runs
twice.

The journal is deliberately boring, because boring is what survives
``kill -9``:

- one JSON file per plan (``plan-<id>.json``) under the journal
  directory — no index file to corrupt, no compaction, directory scan
  IS recovery;
- every write goes through the checkpoint store's atomic
  tmp+``os.replace``+fsync discipline
  (``checkpoint.manager.atomic_write_bytes``), so a file is always
  either the previous record or the new one, never a truncation;
- two durable states: ``submitted`` (written BEFORE execution starts
  — the write-ahead half) and a terminal ``completed``/``failed``
  (written after, carrying the statistics text and its sha256). A
  crash between them leaves ``submitted``, which is exactly the
  signal recovery needs: re-execute. The pipeline underneath is
  deterministic (every stage is pinned bit-identical across reruns),
  so a resumed plan's statistics are byte-identical to an
  uninterrupted twin — and an elastic plan (``elastic=true`` +
  ``checkpoint_path=``) re-enters through its own training
  checkpoints, resuming mid-scan instead of from step 0.

Completion records are exactly-once by construction: recovery skips
every terminal record without touching it (the file's content and
mtime survive recovery byte-identical), so a completed plan is never
re-run and never re-recorded.

Chaos: every journal write passes the ``scheduler.journal`` injection
point (obs/chaos.py grammar). A failing write retries once, then
**degrades to unjournaled** — counted (``scheduler.journal_write_failed``)
and logged, never raised: the journal records the run, it must not be
able to kill it. The cost is honest: a plan whose *completion* write
was lost re-runs on recovery (at-least-once, still byte-identical); a
plan whose *submission* write was lost is invisible to recovery.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SCHEMA = "eeg-tpu-plan-journal/v1"

SUBMITTED = "submitted"
COMPLETED = "completed"
FAILED = "failed"


class PlanJournal:
    """One directory of per-plan journal records."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, plan_id: str) -> str:
        return os.path.join(self.directory, f"plan-{plan_id}.json")

    # -- writes ----------------------------------------------------------

    def _write(self, plan_id: str, payload: Dict[str, Any]) -> bool:
        """One atomic record write through the chaos point; True when
        the record landed. A journal failure degrades the guarantee,
        never the plan (see module docstring)."""
        from .. import obs
        from ..checkpoint.manager import _fsync_directory, atomic_write_text
        from ..obs import chaos, events

        payload = {"schema": SCHEMA, **payload}
        text = json.dumps(payload, indent=2, sort_keys=True) + "\n"
        last_error: Optional[Exception] = None
        for attempt in (1, 2):
            try:
                chaos.maybe_fire("scheduler.journal")
                atomic_write_text(self._path(plan_id), text)
                # the rename itself must be on disk before a caller
                # (or a fleet peer scanning this directory) may rely
                # on the record: a host crash that replays the rename
                # away would resurface a terminal plan as 'submitted'
                # and a surviving replica would re-run it. Counted,
                # never raised — platforms that refuse directory fds
                # keep the page-cache guarantee (atomic_write_bytes's
                # own best-effort fsync already tried once; this
                # second, journal-owned call is what makes the refusal
                # observable).
                if not _fsync_directory(self.directory):
                    obs.metrics.count("scheduler.journal_dir_fsync_failed")
                return True
            except Exception as e:
                last_error = e
        obs.metrics.count("scheduler.journal_write_failed")
        events.event(
            "scheduler.journal_write_failed",
            plan=plan_id,
            error=f"{type(last_error).__name__}: {last_error}",
        )
        logger.error(
            "plan journal write failed for %s (%s: %s); continuing "
            "unjournaled — a crash before completion will re-run this "
            "plan (or lose its completion record)",
            plan_id, type(last_error).__name__, last_error,
        )
        return False

    def record_submitted(
        self, plan_id: str, query: str,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """The write-ahead record: MUST land before execution starts
        for the plan to be recoverable."""
        return self._write(plan_id, {
            "plan_id": plan_id,
            "state": SUBMITTED,
            "query": query,
            "submitted_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "meta": meta or {},
        })

    def record_completed(
        self, plan_id: str, query: str, statistics_text: str,
        attempts: int = 1,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """The exactly-once completion record."""
        return self._write(plan_id, {
            "plan_id": plan_id,
            "state": COMPLETED,
            "query": query,
            "completed_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "attempts": attempts,
            "statistics": statistics_text,
            "statistics_sha256": hashlib.sha256(
                statistics_text.encode()
            ).hexdigest(),
            "meta": meta or {},
        })

    def record_failed(
        self, plan_id: str, query: str, error: str,
        attempts: int = 1,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Terminal failure (retry budget exhausted / deadline spent):
        recovery does NOT re-run it — a deterministic failure would
        fail identically, and the record carries the evidence. ``meta``
        carries the same submission metadata as the other records
        (notably the idempotency key, so a keyed re-submit of a failed
        plan replays the journaled outcome instead of re-running a
        deterministic failure; the shed branch deliberately omits the
        key — backpressure must stay retryable)."""
        return self._write(plan_id, {
            "plan_id": plan_id,
            "state": FAILED,
            "query": query,
            "failed_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "attempts": attempts,
            "error": error,
            "meta": meta or {},
        })

    # -- pod-assist records ----------------------------------------------

    ASSIST_SCHEMA = "eeg-tpu-pod-assist/v1"

    def _assist_path(self, plan_id: str) -> str:
        return os.path.join(self.directory, f"podassist-{plan_id}.json")

    def record_assist(
        self, plan_id: str,
        coordinator: str,
        processes: int,
        holder: str,
        pid: int,
        start_token: str,
        query: str,
    ) -> bool:
        """Publish a pod-assist request: the coordinator replica has
        won a ``processes=N`` plan and needs N-1 worker processes at
        ``coordinator``. Lives beside the plan records in the shared
        journal dir (the ``podassist-`` prefix keeps it invisible to
        :meth:`entries`' ``plan-*.json`` scan); peers claim per-slot
        ``assist:`` leases before spawning so each worker rank has
        exactly one parent. The holder's pid+start_token ride along so
        a peer can tell a live request from one whose coordinator was
        SIGKILLed (and clear the latter)."""
        from ..checkpoint.manager import atomic_write_text

        payload = {
            "schema": self.ASSIST_SCHEMA,
            "plan_id": plan_id,
            "coordinator": coordinator,
            "processes": int(processes),
            "holder": holder,
            "pid": int(pid),
            "start_token": start_token,
            "query": query,
            "since": time.time(),
        }
        try:
            atomic_write_text(
                self._assist_path(plan_id),
                json.dumps(payload, indent=2, sort_keys=True) + "\n",
            )
            return True
        except Exception as e:
            logger.warning(
                "pod-assist record write failed for %s (%s: %s); "
                "the pod degrades to the inline ladder",
                plan_id, type(e).__name__, e,
            )
            return False

    def assist_entries(self) -> List[Dict[str, Any]]:
        """All live pod-assist requests, oldest first. Unparseable
        records are skipped (not quarantined — an assist record is
        advisory: worst case the pod degrades, never a lost plan)."""
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return []
        out: List[Dict[str, Any]] = []
        for name in names:
            if not (
                name.startswith("podassist-") and name.endswith(".json")
            ):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    rec = json.load(f)
                rec["plan_id"]  # noqa: B018 — shape check
            except Exception:
                continue
            out.append(rec)
        out.sort(key=lambda r: r.get("since", 0.0))
        return out

    def clear_assist(self, plan_id: str) -> None:
        """Withdraw a pod-assist request (pod assembled, degraded, or
        its coordinator is provably dead)."""
        try:
            os.unlink(self._assist_path(plan_id))
        except OSError:
            pass

    # -- reads -----------------------------------------------------------

    def _quarantine(self, path: str, error: Exception) -> None:
        """Move an unparseable record aside as ``<name>.corrupt`` and
        count it. A truncated/garbled record (a half-write by some
        non-atomic foreign writer, a disk error) must never wedge a
        scan — under a replica fleet EVERY replica runs the same scan
        loop over the shared directory, so one bad file raising would
        take the whole fleet's claim loop down at once. Quarantining
        (not deleting) keeps the bytes for diagnosis, and renaming off
        the ``.json`` suffix makes the next scan skip it for free."""
        from .. import obs
        from ..obs import events

        obs.metrics.count("scheduler.journal_corrupt")
        events.event(
            "scheduler.journal_corrupt",
            path=path,
            error=f"{type(error).__name__}: {error}",
        )
        try:
            os.replace(path, path + ".corrupt")
            logger.error(
                "quarantined corrupt journal record %s -> %s.corrupt "
                "(%s: %s)", path, path, type(error).__name__, error,
            )
        except OSError as move_error:
            logger.error(
                "corrupt journal record %s (%s: %s) could not be "
                "quarantined (%s); skipping it",
                path, type(error).__name__, error, move_error,
            )

    def entries(self) -> List[Dict[str, Any]]:
        """Every readable record, sorted by plan id (submission order
        — executor ids are zero-padded counters). An unparseable file
        is quarantined to ``plan-<id>.json.corrupt`` and counted
        (``scheduler.journal_corrupt``), never a crash: recovery and
        the fleet scan loop must survive a journal a crash half-wrote
        by some OTHER writer (atomic writes make this impossible for
        our own)."""
        out = []
        try:
            # numeric-aware sort: executor ids are zero-padded to 4
            # digits, but a journal past 9999 submissions grows a
            # digit and 'plan-p10000' would sort lexicographically
            # before 'plan-p9999'
            def _order(name: str):
                stem = name[len("plan-"):-len(".json")]
                if stem.startswith("p") and stem[1:].isdigit():
                    return (0, int(stem[1:]), name)
                return (1, 0, name)

            names = sorted(os.listdir(self.directory), key=_order)
        except OSError:
            return []
        for name in names:
            if not (name.startswith("plan-") and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except ValueError as e:
                self._quarantine(path, e)
            except OSError as e:
                logger.warning(
                    "skipping unreadable journal record %s (%s: %s)",
                    path, type(e).__name__, e,
                )
        return out

    def unfinished(self) -> List[Dict[str, Any]]:
        """The records recovery re-executes: submitted, never
        terminal."""
        return [e for e in self.entries() if e.get("state") == SUBMITTED]

    def entry(self, plan_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(plan_id)) as f:
                return json.load(f)
        except ValueError as e:
            self._quarantine(self._path(plan_id), e)
            return None
        except OSError:
            return None
