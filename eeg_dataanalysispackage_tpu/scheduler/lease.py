"""Plan leases: the fleet's cross-process claiming primitive.

N gateway replicas over ONE shared journal directory (gateway/fleet.py)
need exactly one answer to "who executes plan p0007?" — the journal
record itself cannot say, because any replica may scan it. The answer
is a lease file beside the record::

    <journal_dir>/plan-<id>.lease     "<holder-id>\\n<pid>\\n<start-token>\\n"

taken with the same cross-process ``O_CREAT|O_EXCL`` single-flight the
feature cache's :class:`~eeg_dataanalysispackage_tpu.io.feature_cache.BuildSlot`
proved (PR 13): creation is the claim, the file's **content** names the
holder, and its **mtime is the heartbeat** — the holding replica
touches it periodically, so a fresh mtime means a live owner even when
the observer cannot see the owner's pid.

The rules that make this safe where the cache's lock (which only
ever saved redundant work) did not have to be:

- **Break only the provably dead.** A stale lease is broken ONLY when
  its heartbeat age exceeds ``EEG_TPU_LEASE_TIMEOUT_S`` *and* the
  recorded holder no longer exists. Holder-death is pid liveness
  (``os.kill(pid, 0)`` → ``ProcessLookupError``) hardened against pid
  reuse: the lease records the holder pid's *start token*
  (``/proc/<pid>/stat`` starttime), so an unrelated live process that
  recycled a dead holder's pid still reads as dead — without the
  token, a recycled pid would strand the plan forever (heartbeats
  never resume, but the pid test never fails). A live-but-slow holder
  keeps its claim: a double execution costs more than a late one
  (statistics stay byte-identical either way — the pipeline is
  deterministic — but the journal's exactly-once completion story
  should not depend on it).
- **Break atomically.** Two replicas observing the same stale lease
  must not interleave as A-unlink, A-create, B-unlink(-A's-fresh-
  lease!), B-create — that is two holders and a double execution. The
  break is therefore (1) serialized through a ``<lease>.breaking``
  guard (the same O_EXCL single-flight), with staleness re-read UNDER
  the guard, and (2) executed as an atomic *capture*: ``os.rename`` to
  a breaker-unique name moves exactly one inode to exactly one
  breaker, and the captured bytes are verified to be the observed
  stale record before they are dropped. See
  :meth:`LeaseDir._break_stale`.
- **Unlink only your own lease** (the ``BuildSlot.release`` rule): a
  holder that outlived the stale age may have had its lease broken and
  re-taken by a peer whose id is now in the file — deleting that live
  lease would invite a third executor.

The same file primitive also serializes idempotency-key registration
across the fleet (``key-<hash>.lease`` via :func:`key_claim_id`): two
replicas receiving the same previously-unseen key concurrently would
otherwise each mint their own plan for it (scheduler/executor.py).
Two more claim families ride the identical protocol (same break-only-
provably-dead and atomic-break-guard discipline, same heartbeat
thread): **device leases** (``device-<ordinal>.lease`` via the
``device:<ordinal>`` claim name — scheduler/placement.py's shared
device pool, one file per claimable ordinal) and **pod-assist worker
slots** (``assist-<plan>-<k>.lease`` via ``assist:<plan>:<k>`` — a
peer replica's claim on worker slot ``k`` of a coordinator's pod,
gateway/fleet.py).

Chaos points: ``fleet.lease`` fires inside one claim attempt and
``fleet.heartbeat`` inside one heartbeat touch (both injected as
``OSError`` so they land in the code's own degraded paths: a failed
claim is simply not a claim, a failed beat is a skipped beat — both
counted, neither fatal).

Process-wide counters (:func:`stats`) feed the bench's ``fleet`` block
and ``obs.metrics`` (``fleet.*``); per-replica attribution lands in
``run_report.json`` via the executor's ``fleet`` meta.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: seconds a lease's heartbeat may go un-touched before it is
#: *eligible* for breaking (the holder must ALSO be provably dead)
ENV_LEASE_TIMEOUT = "EEG_TPU_LEASE_TIMEOUT_S"
_DEFAULT_LEASE_TIMEOUT_S = 30.0

#: sentinel from :meth:`LeaseDir.try_claim`: a live foreign replica
#: holds the plan — the caller must not execute it
FOREIGN_HELD = object()

_lock = threading.Lock()
_claims = 0
_takeovers = 0
_breaks = 0
_heartbeats = 0
_heartbeat_failures = 0
_claim_failures = 0
#: O_EXCL claim attempts LOST to a live foreign holder (every
#: FOREIGN_HELD return) — the lockstep-scan contention signal the
#: per-replica scan jitter (gateway/fleet.py) exists to reduce
_claim_losses = 0
_device_claims = 0
_device_claim_losses = 0
_device_releases = 0


def lease_timeout() -> float:
    value = os.environ.get(ENV_LEASE_TIMEOUT)
    if not value:
        return _DEFAULT_LEASE_TIMEOUT_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.0fs",
            ENV_LEASE_TIMEOUT, value, _DEFAULT_LEASE_TIMEOUT_S,
        )
        return _DEFAULT_LEASE_TIMEOUT_S


def stats() -> Dict[str, int]:
    """Process-wide lease counters — the bench/e2e ``fleet`` payload
    field (schema-stable zeros when no fleet ever ran)."""
    with _lock:
        return {
            "claims": _claims,
            "takeovers": _takeovers,
            "breaks": _breaks,
            "heartbeats": _heartbeats,
            "heartbeat_failures": _heartbeat_failures,
            "claim_failures": _claim_failures,
            "claim_losses": _claim_losses,
            "device_claims": _device_claims,
            "device_claim_losses": _device_claim_losses,
            "device_releases": _device_releases,
        }


def reset_stats() -> None:
    """Zero the counters (test/bench isolation)."""
    global _claims, _takeovers, _breaks
    global _heartbeats, _heartbeat_failures, _claim_failures
    global _claim_losses, _device_claims, _device_claim_losses
    global _device_releases
    with _lock:
        _claims = _takeovers = _breaks = 0
        _heartbeats = _heartbeat_failures = _claim_failures = 0
        _claim_losses = _device_claims = 0
        _device_claim_losses = _device_releases = 0


#: the replica's live LeaseDir, registered by gateway/fleet.py so the
#: crash flight recorder (obs/report.py) can name the leases the
#: process held when a plan died — observation only, weakly referenced
_active_dir = None


def set_active(lease_dir: "LeaseDir") -> None:
    import weakref

    global _active_dir
    _active_dir = weakref.ref(lease_dir)


def active_held() -> List[str]:
    """Plan ids of the leases the process's registered LeaseDir holds
    right now; [] when no fleet replica runs in this process."""
    ld = _active_dir() if _active_dir is not None else None
    if ld is None:
        return []
    return sorted(l.plan_id for l in ld.held_leases())


def _count(name: str) -> None:
    from .. import obs

    global _claims, _takeovers, _breaks
    global _heartbeats, _heartbeat_failures, _claim_failures
    global _claim_losses, _device_claims, _device_claim_losses
    global _device_releases
    with _lock:
        if name == "claims":
            _claims += 1
        elif name == "takeovers":
            _takeovers += 1
        elif name == "breaks":
            _breaks += 1
        elif name == "heartbeats":
            _heartbeats += 1
        elif name == "heartbeat_failures":
            _heartbeat_failures += 1
        elif name == "claim_failures":
            _claim_failures += 1
        elif name == "claim_losses":
            _claim_losses += 1
        elif name == "device_claims":
            _device_claims += 1
        elif name == "device_claim_losses":
            _device_claim_losses += 1
        elif name == "device_releases":
            _device_releases += 1
    obs.metrics.count(f"fleet.lease_{name}")


def key_claim_id(idempotency_key: str) -> str:
    """The lease name for an idempotency key's fleet-wide registration
    claim (``key-<hash>.lease``): the executor serializes minting a
    plan for a previously-unseen key through it, so two replicas
    racing one new key register exactly one plan. Hashed — key
    contents never land in a filename."""
    digest = hashlib.sha256(idempotency_key.encode()).hexdigest()[:16]
    return f"key:{digest}"


def _pid_start_token(pid: int) -> Optional[str]:
    """The pid's kernel start time (``/proc/<pid>/stat`` field 22) —
    a (pid, token) pair survives pid reuse, which bare pid liveness
    does not. None when unreadable (no procfs, pid gone)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            # comm (field 2) may contain spaces and parens: the fixed
            # fields start after the LAST ')'
            fields = f.read().rsplit(b")", 1)[1].split()
        return fields[19].decode()
    except (OSError, IndexError, ValueError, UnicodeDecodeError):
        return None


def _holder_dead(pid: Optional[int], token: str = "") -> bool:
    """True only when the recorded holder PROVABLY no longer exists:
    its pid is gone, or the pid is alive but wearing a different start
    token (an unrelated process recycled it — without this check a
    reused pid would make the lease unbreakable forever). Unknown,
    unparseable, or permission-denied pids read as alive: breaking a
    lease on uncertainty is the one mistake this module must not
    make."""
    if pid is None:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except OSError:
        return False
    if token:
        current = _pid_start_token(pid)
        if current is not None and current != token:
            return True
    return False


class PlanLease:
    """One owned lease. Heartbeat from the holding replica's beat
    thread; release exactly once when the plan reaches a terminal
    journal record (or when a draining replica hands the plan back)."""

    __slots__ = ("plan_id", "path", "holder", "acquired_at", "_released")

    def __init__(self, plan_id: str, path: str, holder: str):
        self.plan_id = plan_id
        self.path = path
        self.holder = holder
        self.acquired_at = time.time()
        self._released = False

    def heartbeat(self) -> bool:
        """Touch the lease mtime; False (counted) when the beat could
        not land — the lease then ages toward breakability, which is
        the honest signal a wedged holder should emit."""
        from ..obs import chaos

        if self._released:
            return False
        try:
            chaos.maybe_fire("fleet.heartbeat", OSError)
            os.utime(self.path, None)
        except OSError as e:
            _count("heartbeat_failures")
            logger.warning(
                "lease heartbeat failed for %s (%s: %s)",
                self.plan_id, type(e).__name__, e,
            )
            return False
        _count("heartbeats")
        return True

    def release(self) -> None:
        """Unlink only OUR lease (the ``BuildSlot.release`` rule): a
        lease broken and re-taken by a peer carries the peer's id now
        — deleting it would invite a third executor."""
        if self._released:
            return
        self._released = True
        try:
            with open(self.path) as f:
                owner = f.readline().strip()
            if owner == self.holder:
                os.unlink(self.path)
        except OSError:
            pass

    @property
    def released(self) -> bool:
        return self._released


class LeaseDir:
    """The lease files of one shared journal directory, as seen (and
    held) by one replica."""

    def __init__(self, directory: str, holder: str):
        self.directory = directory
        self.holder = holder
        self._held: Dict[str, PlanLease] = {}
        self._held_lock = threading.Lock()

    def _path(self, name: str) -> str:
        if name.startswith("key:"):
            # an idempotency-key registration claim (key_claim_id) —
            # never scanned as a plan lease
            return os.path.join(
                self.directory, f"key-{name[len('key:'):]}.lease"
            )
        if name.startswith("device:"):
            # a device-pool ordinal claim (scheduler/placement.py)
            return os.path.join(
                self.directory, f"device-{name[len('device:'):]}.lease"
            )
        if name.startswith("assist:"):
            # a pod-assist worker-slot claim (gateway/fleet.py):
            # assist:<plan_id>:<slot> -> assist-<plan_id>-<slot>.lease
            stem = name[len("assist:"):].replace(":", "-")
            return os.path.join(self.directory, f"assist-{stem}.lease")
        return os.path.join(self.directory, f"plan-{name}.lease")

    # -- claiming --------------------------------------------------------

    def _try_create(self, path: str) -> Optional[bool]:
        """O_EXCL create with our holder id + pid + start token:
        True = claimed, False = a holder exists, None = locking
        unavailable here (unwritable dir, chaos)."""
        from ..obs import chaos

        try:
            chaos.maybe_fire("fleet.lease", OSError)
            os.makedirs(self.directory, exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return None
        token = _pid_start_token(os.getpid()) or ""
        try:
            os.write(
                fd, f"{self.holder}\n{os.getpid()}\n{token}\n".encode()
            )
        finally:
            os.close(fd)
        return True

    @staticmethod
    def _read_id_file(
        path: str,
    ) -> Optional[Tuple[str, Optional[int], str]]:
        """(holder, pid, start-token) from a lease/guard file; None
        when unreadable."""
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return None
        holder = lines[0].strip() if lines else ""
        pid: Optional[int] = None
        if len(lines) > 1:
            try:
                pid = int(lines[1].strip())
            except ValueError:
                pid = None
        token = lines[2].strip() if len(lines) > 2 else ""
        return holder, pid, token

    def _break_stale(self, plan_id: str, path: str) -> Optional[bool]:
        """Break ONE observed-stale lease ATOMICALLY. Returns True
        when this replica won the break (the stale file is gone; the
        caller now races for the vacant claim), False when a peer owns
        the break or the lease turned out live under re-read (stand
        down: FOREIGN_HELD), None when locking was unavailable.

        Two replicas observing the same stale lease must not
        interleave as A-unlink, A-create, B-unlink(-A's-fresh-lease!),
        B-create — both would then hold "their own" lease and
        double-execute. Two layers prevent it:

        - a **break guard** (``<lease>.breaking``, the same O_EXCL
          single-flight): one breaker works a given lease at a time,
          and staleness is re-read UNDER the guard. A guard whose
          creator died mid-break (or wedged past the lease timeout —
          guards carry no heartbeat, so age is time since creation) is
          itself captured-and-dropped atomically, then the break
          retried;
        - the removal is an **atomic capture**: ``os.rename`` to a
          breaker-unique name hands exactly one inode to exactly one
          breaker, and the captured bytes are verified to BE the
          observed stale record before being dropped. A capture that
          grabbed a fresh lease instead (possible only when the guard
          itself was stale-broken concurrently) is republished with
          ``os.link``, which cannot clobber any newer claim.
        """
        from ..obs import events

        guard = path + ".breaking"
        took_guard = self._try_create(guard)
        if took_guard is False:
            ids = self._read_id_file(guard)
            try:
                age = time.time() - os.path.getmtime(guard)
            except OSError:
                return False
            if ids is None or not (
                _holder_dead(ids[1], ids[2]) or age > lease_timeout()
            ):
                # a live breaker owns the takeover
                return False
            trash = f"{guard}.{self.holder}.{os.getpid()}"
            try:
                os.rename(guard, trash)
                os.unlink(trash)
            except OSError:
                return False
            took_guard = self._try_create(guard)
        if took_guard is not True:
            return None if took_guard is None else False
        try:
            info = self.holder_info(plan_id)
            if info is None:
                # released while the guard was taken: nothing to
                # break, the claim path is already vacant
                return True
            if not info["stale"]:
                # the holder resumed, or a faster breaker already
                # re-created a fresh lease here
                return False
            captured = f"{path}.broken.{self.holder}.{os.getpid()}"
            try:
                os.rename(path, captured)
            except OSError:
                return False
            got = self._read_id_file(captured)
            if got is not None and (
                got[0] != info["holder"] or got[1] != info["pid"]
            ):
                # the rename grabbed a FRESH lease (only reachable
                # when our guard was concurrently stale-broken):
                # republish it — os.link refuses to clobber a claim
                # that landed at the path meanwhile
                try:
                    os.link(captured, path)
                except OSError:
                    pass
                try:
                    os.unlink(captured)
                except OSError:
                    pass
                return False
            try:
                os.unlink(captured)
            except OSError:
                pass
            _count("breaks")
            events.event(
                "fleet.lease_break", plan=plan_id,
                holder=info["holder"], age_s=round(info["age_s"], 3),
            )
            logger.warning(
                "broke stale lease for %s (holder %s pid %s dead, "
                "heartbeat %.1fs old > %.0fs timeout)",
                plan_id, info["holder"], info["pid"],
                info["age_s"], lease_timeout(),
            )
            return True
        finally:
            ids = self._read_id_file(guard)
            if (
                ids is not None
                and ids[0] == self.holder
                and ids[1] == os.getpid()
            ):
                try:
                    os.unlink(guard)
                except OSError:
                    pass

    def try_claim(self, plan_id: str, takeover: bool = False):
        """One non-blocking claim attempt. Returns the owned
        :class:`PlanLease`; :data:`FOREIGN_HELD` when another replica
        holds the plan (live, or dead-but-not-yet-breakable); or None
        when locking is unavailable (the claim failed without telling
        us anything about ownership — counted, retry next scan).

        ``takeover=True`` marks a claim of another replica's journal
        record (the fleet scan loop) for the counters; a stale lease is
        broken first — only past :func:`lease_timeout` AND only when
        the recorded holder is provably dead, atomically
        (:meth:`_break_stale`), so racing breakers never produce two
        holders.

        Every FOREIGN_HELD return is additionally counted as a
        **claim loss** (``claim_losses``, or ``device_claim_losses``
        for ``device:`` claims): an O_EXCL attempt a peer won. The
        per-replica scan jitter (gateway/fleet.py) exists to shrink
        this number — N replicas scanning in lockstep all race the
        same fresh record and N-1 lose every round."""
        device = plan_id.startswith("device:")
        loss = "device_claim_losses" if device else "claim_losses"
        path = self._path(plan_id)
        with self._held_lock:
            held = self._held.get(plan_id)
        if held is not None and not held.released:
            return held
        created = self._try_create(path)
        if created is False:
            info = self.holder_info(plan_id)
            if info is not None and info["holder"] == self.holder:
                # OUR lease, raced from two of our own threads (a
                # keyed re-submit racing the scan loop): hand back the
                # held object rather than reading ourselves as foreign
                with self._held_lock:
                    held = self._held.get(plan_id)
                if held is not None and not held.released:
                    return held
            if info is None:
                # released between the create and the read: one retry
                created = self._try_create(path)
            elif info["stale"]:
                broke = self._break_stale(plan_id, path)
                if broke is True:
                    created = self._try_create(path)
                elif broke is None:
                    _count("claim_failures")
                    return None
                else:
                    # a racing breaker owns the takeover (or the
                    # holder turned out live under the guard)
                    _count(loss)
                    return FOREIGN_HELD
            else:
                _count(loss)
                return FOREIGN_HELD
        if created is not True:
            if created is False:
                _count(loss)
                return FOREIGN_HELD
            _count("claim_failures")
            return None
        lease = PlanLease(plan_id, path, self.holder)
        with self._held_lock:
            self._held[plan_id] = lease
        _count("device_claims" if device else "claims")
        if takeover and not device:
            _count("takeovers")
        return lease

    # -- the holder's surface --------------------------------------------

    def held(self, plan_id: str) -> Optional[PlanLease]:
        with self._held_lock:
            lease = self._held.get(plan_id)
        return None if lease is None or lease.released else lease

    def held_leases(self) -> List[PlanLease]:
        with self._held_lock:
            return [l for l in self._held.values() if not l.released]

    def held_plan_leases(self) -> List[PlanLease]:
        """Held PLAN leases only — the gateway's ``fleet.held_leases``
        gauge keeps its pre-placement meaning (plans this replica is
        executing), with device/assist/key claims filtered out."""
        return [
            l for l in self.held_leases() if ":" not in l.plan_id
        ]

    def held_device_ordinals(self) -> List[int]:
        """Device-pool ordinals this replica holds right now (the
        ``fleet.devices_held`` gauge)."""
        out = []
        for l in self.held_leases():
            if l.plan_id.startswith("device:"):
                try:
                    out.append(int(l.plan_id[len("device:"):]))
                except ValueError:
                    continue
        return sorted(out)

    def heartbeat_all(self) -> int:
        """One beat across every held lease; returns beats landed."""
        return sum(1 for l in self.held_leases() if l.heartbeat())

    def release(self, plan_id: str) -> None:
        with self._held_lock:
            lease = self._held.pop(plan_id, None)
        if lease is not None:
            lease.release()

    def release_all(self) -> None:
        with self._held_lock:
            leases = list(self._held.values())
            self._held.clear()
        for lease in leases:
            lease.release()

    # -- observation (any replica, plan_admin) ---------------------------

    def holder_info(self, plan_id: str) -> Optional[Dict[str, Any]]:
        """Who holds ``plan_id`` — {holder, pid, age_s, pid_dead,
        stale}; None when unleased. ``pid_dead`` folds in the start
        token: a recycled pid reads as dead (see
        :func:`_holder_dead`)."""
        path = self._path(plan_id)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return None
        ids = self._read_id_file(path)
        if ids is None:
            return None
        holder, pid, token = ids
        age_s = max(0.0, time.time() - mtime)
        dead = _holder_dead(pid, token)
        return {
            "plan_id": plan_id,
            "holder": holder,
            "pid": pid,
            "age_s": age_s,
            "pid_dead": dead,
            "stale": age_s > lease_timeout() and dead,
        }

    def scan(self) -> List[Dict[str, Any]]:
        """Every lease in the directory (plan_admin's fleet view)."""
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        out = []
        for name in names:
            if not (name.startswith("plan-") and name.endswith(".lease")):
                continue
            info = self.holder_info(name[len("plan-"):-len(".lease")])
            if info is not None:
                out.append(info)
        return out
