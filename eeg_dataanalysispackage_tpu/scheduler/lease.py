"""Plan leases: the fleet's cross-process claiming primitive.

N gateway replicas over ONE shared journal directory (gateway/fleet.py)
need exactly one answer to "who executes plan p0007?" — the journal
record itself cannot say, because any replica may scan it. The answer
is a lease file beside the record::

    <journal_dir>/plan-<id>.lease     "<holder-id>\\n<pid>\\n"

taken with the same cross-process ``O_CREAT|O_EXCL`` single-flight the
feature cache's :class:`~eeg_dataanalysispackage_tpu.io.feature_cache.BuildSlot`
proved (PR 13): creation is the claim, the file's **content** names the
holder, and its **mtime is the heartbeat** — the holding replica
touches it periodically, so a fresh mtime means a live owner even when
the observer cannot see the owner's pid.

The two rules that make this safe where the cache's lock (which only
ever saved redundant work) did not have to be:

- **Break only the provably dead.** A stale lease is broken ONLY when
  its heartbeat age exceeds ``EEG_TPU_LEASE_TIMEOUT_S`` *and* the
  recorded holder pid no longer exists (``os.kill(pid, 0)`` →
  ``ProcessLookupError``). A live-but-slow holder keeps its claim: a
  double execution costs more than a late one (statistics stay
  byte-identical either way — the pipeline is deterministic — but the
  journal's exactly-once completion story should not depend on it).
- **Unlink only your own lease** (the ``BuildSlot.release`` rule): a
  holder that outlived the stale age may have had its lease broken and
  re-taken by a peer whose id is now in the file — deleting that live
  lease would invite a third executor.

Chaos points: ``fleet.lease`` fires inside one claim attempt and
``fleet.heartbeat`` inside one heartbeat touch (both injected as
``OSError`` so they land in the code's own degraded paths: a failed
claim is simply not a claim, a failed beat is a skipped beat — both
counted, neither fatal).

Process-wide counters (:func:`stats`) feed the bench's ``fleet`` block
and ``obs.metrics`` (``fleet.*``); per-replica attribution lands in
``run_report.json`` via the executor's ``fleet`` meta.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

#: seconds a lease's heartbeat may go un-touched before it is
#: *eligible* for breaking (the holder must ALSO be provably dead)
ENV_LEASE_TIMEOUT = "EEG_TPU_LEASE_TIMEOUT_S"
_DEFAULT_LEASE_TIMEOUT_S = 30.0

#: sentinel from :meth:`LeaseDir.try_claim`: a live foreign replica
#: holds the plan — the caller must not execute it
FOREIGN_HELD = object()

_lock = threading.Lock()
_claims = 0
_takeovers = 0
_breaks = 0
_heartbeats = 0
_heartbeat_failures = 0
_claim_failures = 0


def lease_timeout() -> float:
    value = os.environ.get(ENV_LEASE_TIMEOUT)
    if not value:
        return _DEFAULT_LEASE_TIMEOUT_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.0fs",
            ENV_LEASE_TIMEOUT, value, _DEFAULT_LEASE_TIMEOUT_S,
        )
        return _DEFAULT_LEASE_TIMEOUT_S


def stats() -> Dict[str, int]:
    """Process-wide lease counters — the bench/e2e ``fleet`` payload
    field (schema-stable zeros when no fleet ever ran)."""
    with _lock:
        return {
            "claims": _claims,
            "takeovers": _takeovers,
            "breaks": _breaks,
            "heartbeats": _heartbeats,
            "heartbeat_failures": _heartbeat_failures,
            "claim_failures": _claim_failures,
        }


def reset_stats() -> None:
    """Zero the counters (test/bench isolation)."""
    global _claims, _takeovers, _breaks
    global _heartbeats, _heartbeat_failures, _claim_failures
    with _lock:
        _claims = _takeovers = _breaks = 0
        _heartbeats = _heartbeat_failures = _claim_failures = 0


def _count(name: str) -> None:
    from .. import obs

    global _claims, _takeovers, _breaks
    global _heartbeats, _heartbeat_failures, _claim_failures
    with _lock:
        if name == "claims":
            _claims += 1
        elif name == "takeovers":
            _takeovers += 1
        elif name == "breaks":
            _breaks += 1
        elif name == "heartbeats":
            _heartbeats += 1
        elif name == "heartbeat_failures":
            _heartbeat_failures += 1
        elif name == "claim_failures":
            _claim_failures += 1
    obs.metrics.count(f"fleet.lease_{name}")


def _pid_dead(pid: Optional[int]) -> bool:
    """True only when the pid PROVABLY no longer exists. Unknown,
    unparseable, or permission-denied pids read as alive: breaking a
    lease on uncertainty is the one mistake this module must not
    make."""
    if pid is None:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except OSError:
        return False
    return False


class PlanLease:
    """One owned lease. Heartbeat from the holding replica's beat
    thread; release exactly once when the plan reaches a terminal
    journal record (or when a draining replica hands the plan back)."""

    __slots__ = ("plan_id", "path", "holder", "acquired_at", "_released")

    def __init__(self, plan_id: str, path: str, holder: str):
        self.plan_id = plan_id
        self.path = path
        self.holder = holder
        self.acquired_at = time.time()
        self._released = False

    def heartbeat(self) -> bool:
        """Touch the lease mtime; False (counted) when the beat could
        not land — the lease then ages toward breakability, which is
        the honest signal a wedged holder should emit."""
        from ..obs import chaos

        if self._released:
            return False
        try:
            chaos.maybe_fire("fleet.heartbeat", OSError)
            os.utime(self.path, None)
        except OSError as e:
            _count("heartbeat_failures")
            logger.warning(
                "lease heartbeat failed for %s (%s: %s)",
                self.plan_id, type(e).__name__, e,
            )
            return False
        _count("heartbeats")
        return True

    def release(self) -> None:
        """Unlink only OUR lease (the ``BuildSlot.release`` rule): a
        lease broken and re-taken by a peer carries the peer's id now
        — deleting it would invite a third executor."""
        if self._released:
            return
        self._released = True
        try:
            with open(self.path) as f:
                owner = f.readline().strip()
            if owner == self.holder:
                os.unlink(self.path)
        except OSError:
            pass

    @property
    def released(self) -> bool:
        return self._released


class LeaseDir:
    """The lease files of one shared journal directory, as seen (and
    held) by one replica."""

    def __init__(self, directory: str, holder: str):
        self.directory = directory
        self.holder = holder
        self._held: Dict[str, PlanLease] = {}
        self._held_lock = threading.Lock()

    def _path(self, plan_id: str) -> str:
        return os.path.join(self.directory, f"plan-{plan_id}.lease")

    # -- claiming --------------------------------------------------------

    def _try_create(self, path: str) -> Optional[bool]:
        """O_EXCL create with our holder id + pid: True = claimed,
        False = a holder exists, None = locking unavailable here
        (unwritable dir, chaos)."""
        from ..obs import chaos

        try:
            chaos.maybe_fire("fleet.lease", OSError)
            os.makedirs(self.directory, exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return None
        try:
            os.write(fd, f"{self.holder}\n{os.getpid()}\n".encode())
        finally:
            os.close(fd)
        return True

    def try_claim(self, plan_id: str, takeover: bool = False):
        """One non-blocking claim attempt. Returns the owned
        :class:`PlanLease`; :data:`FOREIGN_HELD` when another replica
        holds the plan (live, or dead-but-not-yet-breakable); or None
        when locking is unavailable (the claim failed without telling
        us anything about ownership — counted, retry next scan).

        ``takeover=True`` marks a claim of another replica's journal
        record (the fleet scan loop) for the counters; a stale lease is
        broken first — only past :func:`lease_timeout` AND only when
        the recorded holder pid is provably dead."""
        path = self._path(plan_id)
        with self._held_lock:
            held = self._held.get(plan_id)
        if held is not None and not held.released:
            return held
        created = self._try_create(path)
        if created is False:
            info = self.holder_info(plan_id)
            if info is not None and info["holder"] == self.holder:
                # OUR lease, raced from two of our own threads (a
                # keyed re-submit racing the scan loop): hand back the
                # held object rather than reading ourselves as foreign
                with self._held_lock:
                    held = self._held.get(plan_id)
                if held is not None and not held.released:
                    return held
            if info is None:
                # released between the create and the read: one retry
                created = self._try_create(path)
            elif info["stale"]:
                _count("breaks")
                from ..obs import events

                events.event(
                    "fleet.lease_break", plan=plan_id,
                    holder=info["holder"], age_s=round(info["age_s"], 3),
                )
                logger.warning(
                    "breaking stale lease for %s (holder %s pid %s "
                    "dead, heartbeat %.1fs old > %.0fs timeout)",
                    plan_id, info["holder"], info["pid"],
                    info["age_s"], lease_timeout(),
                )
                try:
                    os.unlink(path)
                except OSError:
                    pass
                created = self._try_create(path)
            else:
                return FOREIGN_HELD
        if created is not True:
            if created is False:
                return FOREIGN_HELD
            _count("claim_failures")
            return None
        lease = PlanLease(plan_id, path, self.holder)
        with self._held_lock:
            self._held[plan_id] = lease
        _count("claims")
        if takeover:
            _count("takeovers")
        return lease

    # -- the holder's surface --------------------------------------------

    def held(self, plan_id: str) -> Optional[PlanLease]:
        with self._held_lock:
            lease = self._held.get(plan_id)
        return None if lease is None or lease.released else lease

    def held_leases(self) -> List[PlanLease]:
        with self._held_lock:
            return [l for l in self._held.values() if not l.released]

    def heartbeat_all(self) -> int:
        """One beat across every held lease; returns beats landed."""
        return sum(1 for l in self.held_leases() if l.heartbeat())

    def release(self, plan_id: str) -> None:
        with self._held_lock:
            lease = self._held.pop(plan_id, None)
        if lease is not None:
            lease.release()

    def release_all(self) -> None:
        with self._held_lock:
            leases = list(self._held.values())
            self._held.clear()
        for lease in leases:
            lease.release()

    # -- observation (any replica, plan_admin) ---------------------------

    def holder_info(self, plan_id: str) -> Optional[Dict[str, Any]]:
        """Who holds ``plan_id`` — {holder, pid, age_s, pid_dead,
        stale}; None when unleased."""
        path = self._path(plan_id)
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return None
        holder = lines[0].strip() if lines else ""
        pid: Optional[int] = None
        if len(lines) > 1:
            try:
                pid = int(lines[1].strip())
            except ValueError:
                pid = None
        age_s = max(0.0, time.time() - mtime)
        dead = _pid_dead(pid)
        return {
            "plan_id": plan_id,
            "holder": holder,
            "pid": pid,
            "age_s": age_s,
            "pid_dead": dead,
            "stale": age_s > lease_timeout() and dead,
        }

    def scan(self) -> List[Dict[str, Any]]:
        """Every lease in the directory (plan_admin's fleet view)."""
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        out = []
        for name in names:
            if not (name.startswith("plan-") and name.endswith(".lease")):
                continue
            info = self.holder_info(name[len("plan-"):-len(".lease")])
            if info is not None:
                out.append(info)
        return out
