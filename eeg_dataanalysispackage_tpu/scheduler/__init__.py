"""Multi-tenant plan scheduling: the execution half of the
``ExecutionPlan`` IR split (ROADMAP item 5).

- :mod:`scheduler.runtime`  — ``execute_plan``: one plan executed
  inside its own fault domain (chaos plan, metrics scope, span root,
  degradation state, ``run_report.json`` — all per plan);
- :mod:`scheduler.journal`  — the write-ahead plan journal that makes
  the executor crash-only (``kill -9`` mid-batch, restart, resume);
- :mod:`scheduler.executor` — the resident :class:`PlanExecutor`:
  bounded admission with shed-with-evidence, N worker threads over
  the shared plan/feature/compile caches, per-plan deadlines and
  retry budgets, idempotency-keyed submission, cancel-if-queued, and
  :meth:`PlanExecutor.recover`;
- :mod:`scheduler.dedup`    — cross-tenant plan-prefix dedup: two
  tenants whose plans share a canonical ingest+featurize prefix
  (``ExecutionPlan.prefix_key``) compute it once, with per-plan
  leader/follower attribution;
- :mod:`scheduler.lease`    — the fleet's cross-process plan-claiming
  primitive: ``plan-<id>.lease`` files beside the journal records
  (O_EXCL claim, heartbeat mtime, break-only-the-provably-dead), so
  N gateway replicas over ONE journal directory execute each plan
  exactly once (gateway/fleet.py).

The HTTP front door over all of this lives in ``gateway/``.

See docs/architecture.md for the IR schema, the executor lifecycle,
the dedup semantics, and the crash-recovery contract.
"""

from .dedup import PrefixClaim, PrefixRegistry  # noqa: F401
from .executor import (  # noqa: F401
    IdempotencyConflictError,
    PlanCancelledError,
    PlanExecutor,
    PlanFailedError,
    PlanHandle,
    PlanOwnedElsewhereError,
    PlanResult,
    PlanShedError,
)
from .journal import PlanJournal  # noqa: F401
from .lease import LeaseDir, PlanLease  # noqa: F401
from .runtime import execute_plan  # noqa: F401
