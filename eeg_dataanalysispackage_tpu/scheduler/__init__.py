"""Multi-tenant plan scheduling: the execution half of the
``ExecutionPlan`` IR split (ROADMAP item 5).

- :mod:`scheduler.runtime`  — ``execute_plan``: one plan executed
  inside its own fault domain (chaos plan, metrics scope, span root,
  degradation state, ``run_report.json`` — all per plan);
- :mod:`scheduler.journal`  — the write-ahead plan journal that makes
  the executor crash-only (``kill -9`` mid-batch, restart, resume);
- :mod:`scheduler.executor` — the resident :class:`PlanExecutor`:
  bounded admission with shed-with-evidence, N worker threads over
  the shared plan/feature/compile caches, per-plan deadlines and
  retry budgets, and :meth:`PlanExecutor.recover`.

See docs/architecture.md for the IR schema, the executor lifecycle,
and the crash-recovery contract.
"""

from .executor import (  # noqa: F401
    PlanExecutor,
    PlanFailedError,
    PlanHandle,
    PlanResult,
    PlanShedError,
)
from .journal import PlanJournal  # noqa: F401
from .runtime import execute_plan  # noqa: F401
