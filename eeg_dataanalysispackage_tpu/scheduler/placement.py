"""Device-aware fleet placement: the shared device pool.

PR 17's replica fleet made N gateways share one journal, but every
replica still executed on whatever devices its process happened to
see — two replicas running two 4-device plans on one 8-device host
silently time-share the same chips. This module turns the host's
ordinals into a claimable pool using the exact lease protocol plans
already ride (scheduler/lease.py): one ``device-<ordinal>.lease``
file per ordinal beside the journal, O_CREAT|O_EXCL creation as the
claim, mtime heartbeats from the holder's beat thread, break only the
provably dead, break atomically. A replica that wants to run a plan
claims the plan's whole footprint (ExecutionPlan.device_footprint())
**all-or-nothing** — partial holds are released immediately, so two
replicas' gangs can never deadlock each other holding half a pool
each.

Gang scheduling with backfill lives in the executor's worker loop
(scheduler/executor.py): a plan whose footprint cannot be satisfied
right now goes back to the queue's tail — its journal record stays
``submitted``, its plan lease stays held — while smaller plans
backfill past it on the ordinals that ARE free. Starvation is bounded
by an age-based promotion: every unsatisfied footprint is advertised
as a ``waiting-<plan_id>.json`` record in the lease directory, and
once the oldest waiting plan (fleet-wide — every replica reads the
same directory) has waited past ``EEG_TPU_GANG_PROMOTION_S``, no
replica grants ANY other plan new ordinals until the promoted gang
fits. Freed devices then drain toward the gang instead of leaking to
a stream of small jobs.

Exemptions, deliberately: serve plans (resident services — an
exclusive ordinal held forever would starve the pool; admission
control bounds them elsewhere) and pod plans with ``processes>1``
(they are routed through pod-assist — gateway/fleet.py — and their
worker processes manage their own devices). Both run unplaced, which
is also the global degradation path: a pool that cannot claim
(unwritable directory, chaos) or a footprint larger than the pool
degrades to today's unplaced execution, where the builder's existing
mesh -> single-device -> host ladder applies unchanged.

Counters ride :func:`lease.stats` (``device_claims`` /
``device_claim_losses`` / ``device_releases``) and ``obs.metrics``
(``placement.*``); the waiting records are the operator surface
``fleet_top`` and ``plan_admin fleet`` render.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import lease as lease_mod

logger = logging.getLogger(__name__)

#: pool size: unset/""/"0" = placement off; "auto" = len(jax.devices())
#: at replica start; an integer = exactly that many ordinals
ENV_DEVICE_POOL = "EEG_TPU_DEVICE_POOL"
#: seconds the fleet's oldest waiting footprint may starve before it
#: is promoted (no replica grants any OTHER plan new ordinals)
ENV_GANG_PROMOTION = "EEG_TPU_GANG_PROMOTION_S"
_DEFAULT_PROMOTION_S = 5.0

#: sentinel from :meth:`DevicePool.admit`: run WITHOUT a grant — the
#: plan is exempt (serve/pod), its footprint exceeds the pool, or the
#: pool itself is degraded. The builder's existing availability
#: ladder governs from there.
UNPLACED = object()

_POOL_MARKER = "device-pool.json"
_MARKER_SCHEMA = "eeg-tpu-device-pool/v1"
_WAIT_SCHEMA = "eeg-tpu-placement-wait/v1"


def promotion_age() -> float:
    value = os.environ.get(ENV_GANG_PROMOTION)
    if not value:
        return _DEFAULT_PROMOTION_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.1fs",
            ENV_GANG_PROMOTION, value, _DEFAULT_PROMOTION_S,
        )
        return _DEFAULT_PROMOTION_S


def _wait_path(directory: str, plan_id: str) -> str:
    return os.path.join(directory, f"waiting-{plan_id}.json")


def waiting_entries(
    directory: str, clear_dead: bool = False,
) -> List[Dict[str, Any]]:
    """Every valid waiting record in ``directory``, oldest first.
    A record whose advertising process is provably dead (pid + start
    token, the lease module's liveness test) is skipped — and unlinked
    when ``clear_dead`` (a SIGKILLed replica's waiting gang must not
    promote forever and block the whole fleet; the plan itself is
    re-run via its stale plan lease and re-advertises under the
    survivor's identity)."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    out = []
    for name in names:
        if not (name.startswith("waiting-") and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                entry = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(entry, dict) or "plan_id" not in entry:
            continue
        pid = entry.get("pid")
        if pid is not None and lease_mod._holder_dead(
            pid, entry.get("start_token", "")
        ):
            if clear_dead:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            continue
        out.append(entry)
    out.sort(key=lambda e: (e.get("since", 0.0), e.get("plan_id", "")))
    return out


def device_table(directory: str) -> List[Dict[str, Any]]:
    """Observer view of the device leases in ``directory`` — one row
    per held ordinal ({ordinal, holder, pid, age_s, pid_dead, stale}),
    read exactly as ``plan_admin``/``fleet_top`` read plan leases."""
    observer = lease_mod.LeaseDir(directory, holder="placement-observer")
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    out = []
    for name in names:
        if not (name.startswith("device-") and name.endswith(".lease")):
            continue
        stem = name[len("device-"):-len(".lease")]
        if not stem.isdigit():
            continue
        info = observer.holder_info(f"device:{stem}")
        if info is not None:
            info["ordinal"] = int(stem)
            out.append(info)
    out.sort(key=lambda r: r["ordinal"])
    return out


def pool_size_marker(directory: str) -> Optional[int]:
    """The advertised pool size, or None when no pool ever ran here."""
    try:
        with open(os.path.join(directory, _POOL_MARKER)) as f:
            marker = json.load(f)
        return int(marker["size"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


class DeviceGrant:
    """One plan's granted device set: the leased ordinals its mesh is
    built from. Released exactly once, when the plan's execution ends
    (terminal record or attempt ladder exit)."""

    __slots__ = ("plan_id", "ordinals", "_pool", "_released")

    def __init__(self, plan_id: str, ordinals: Tuple[int, ...], pool):
        self.plan_id = plan_id
        self.ordinals = tuple(ordinals)
        self._pool = pool
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._pool._release_ordinals(self.ordinals)

    def __repr__(self) -> str:
        return (
            f"DeviceGrant(plan={self.plan_id}, "
            f"ordinals={list(self.ordinals)})"
        )


class DevicePool:
    """One replica's handle on the shared device pool.

    Cross-process exclusivity is the lease file (O_EXCL create wins);
    in-process exclusivity is ``_lock`` + the granted set — required
    because ``LeaseDir.try_claim`` deliberately hands a lease this
    process already holds back to a second caller (the plan-lease
    re-claim path), which for device ordinals would be a double
    grant."""

    def __init__(self, leases: lease_mod.LeaseDir, size: int):
        if size < 1:
            raise ValueError(f"device pool size must be >= 1, got {size}")
        self.leases = leases
        self.size = int(size)
        self._lock = threading.Lock()
        #: ordinals granted to plans in THIS process right now
        self._granted: set = set()
        self._write_marker()

    @classmethod
    def from_env(
        cls, leases: lease_mod.LeaseDir,
    ) -> Optional["DevicePool"]:
        """Build the pool from ``EEG_TPU_DEVICE_POOL`` — None when
        placement is off (unset/empty/0, the default: PR 17 fleet
        behavior byte-unchanged)."""
        value = (os.environ.get(ENV_DEVICE_POOL) or "").strip()
        if not value or value == "0":
            return None
        if value.lower() == "auto":
            try:
                import jax

                size = len(jax.devices())
            except Exception as e:
                logger.warning(
                    "EEG_TPU_DEVICE_POOL=auto but jax.devices() failed "
                    "(%s: %s); placement disabled",
                    type(e).__name__, e,
                )
                return None
        else:
            try:
                size = int(value)
            except ValueError:
                logger.warning(
                    "unparseable %s=%r; placement disabled",
                    ENV_DEVICE_POOL, value,
                )
                return None
            if size < 1:
                return None
        return cls(leases, size)

    def _write_marker(self) -> None:
        """Advertise the pool size beside the lease files so offline
        observers (fleet_top, plan_admin) can compute the free count.
        Best-effort: a marker that cannot land degrades the view, not
        the pool."""
        from ..checkpoint.manager import atomic_write_text

        try:
            atomic_write_text(
                os.path.join(self.leases.directory, _POOL_MARKER),
                json.dumps({
                    "schema": _MARKER_SCHEMA,
                    "size": self.size,
                    "holder": self.leases.holder,
                    "pid": os.getpid(),
                }, sort_keys=True) + "\n",
            )
        except OSError as e:
            logger.warning(
                "device-pool marker write failed (%s: %s)",
                type(e).__name__, e,
            )

    # -- the scheduling surface ------------------------------------------

    def admit(self, plan_id: str, footprint: Dict[str, Any]):
        """One placement attempt for ``plan_id``. Returns a
        :class:`DeviceGrant` (run on these ordinals), ``None`` (wait:
        the footprint cannot be satisfied now — the caller requeues
        the plan and smaller plans backfill past it), or
        :data:`UNPLACED` (run without a grant: exempt class,
        footprint larger than the pool, or pool degraded)."""
        from .. import obs

        if footprint.get("memory_class") == "serve":
            obs.metrics.count("placement.exempt")
            return UNPLACED
        if footprint.get("hosts", 1) > 1:
            # pod plans route through pod-assist; their processes own
            # their devices
            obs.metrics.count("placement.exempt")
            return UNPLACED
        need = int(footprint.get("devices", 1))
        if need == 0:
            need = self.size
        if need > self.size:
            obs.metrics.count("placement.unsatisfiable")
            logger.warning(
                "plan %s wants %d devices but the pool holds %d; "
                "running unplaced (the mesh ladder degrades it)",
                plan_id, need, self.size,
            )
            self.clear_waiting(plan_id)
            return UNPLACED
        with self._lock:
            promoted = self.promoted()
            if promoted is not None and promoted["plan_id"] != plan_id:
                # a starved gang owns every ordinal that frees up
                # until it fits — do not even try to claim
                self._note_waiting(plan_id, footprint)
                obs.metrics.count("placement.promotion_blocked")
                return None
            claimed: List[int] = []
            for ordinal in range(self.size):
                if len(claimed) == need:
                    break
                if ordinal in self._granted:
                    continue
                got = self.leases.try_claim(f"device:{ordinal}")
                if isinstance(got, lease_mod.PlanLease):
                    claimed.append(ordinal)
            if len(claimed) < need:
                # all-or-nothing: holding a partial gang would
                # deadlock against a peer holding the complement
                for ordinal in claimed:
                    self.leases.release(f"device:{ordinal}")
                    lease_mod._count("device_releases")
                self._note_waiting(plan_id, footprint)
                obs.metrics.count("placement.waits")
                return None
            self._granted.update(claimed)
        self.clear_waiting(plan_id)
        obs.metrics.count("placement.grants")
        if promoted is not None and promoted["plan_id"] == plan_id:
            obs.metrics.count("placement.promotions")
        elif self.waiting_others(plan_id):
            # a smaller plan just ran past a footprint that is still
            # waiting: the backfill evidence
            obs.metrics.count("placement.backfills")
        return DeviceGrant(plan_id, tuple(claimed), self)

    def _release_ordinals(self, ordinals: Tuple[int, ...]) -> None:
        with self._lock:
            for ordinal in ordinals:
                self.leases.release(f"device:{ordinal}")
                lease_mod._count("device_releases")
                self._granted.discard(ordinal)

    def release_all(self) -> None:
        """Free every ordinal this process granted (replica close)."""
        with self._lock:
            for ordinal in sorted(self._granted):
                self.leases.release(f"device:{ordinal}")
                lease_mod._count("device_releases")
            self._granted.clear()

    # -- waiting records (the no-starvation + operator surface) ----------

    def _note_waiting(self, plan_id: str, footprint: Dict[str, Any]):
        """Advertise an unsatisfied footprint (idempotent: the FIRST
        wait's timestamp is the promotion clock — rewriting it every
        retry would reset the starvation bound). A dead peer's record
        for the same plan is overwritten: after a takeover the
        survivor's identity owns the wait."""
        from ..checkpoint.manager import atomic_write_text

        path = _wait_path(self.leases.directory, plan_id)
        try:
            with open(path) as f:
                existing = json.load(f)
            if existing.get("holder") == self.leases.holder:
                return  # our record, original clock preserved
            pid = existing.get("pid")
            if pid is not None and not lease_mod._holder_dead(
                pid, existing.get("start_token", "")
            ):
                return  # a live peer's record (its plan lease rules)
        except (OSError, ValueError):
            pass
        try:
            atomic_write_text(path, json.dumps({
                "schema": _WAIT_SCHEMA,
                "plan_id": plan_id,
                "footprint": dict(footprint),
                "since": time.time(),
                "holder": self.leases.holder,
                "pid": os.getpid(),
                "start_token": lease_mod._pid_start_token(os.getpid())
                or "",
            }, sort_keys=True) + "\n")
        except OSError as e:
            logger.warning(
                "placement waiting record write failed for %s "
                "(%s: %s)", plan_id, type(e).__name__, e,
            )

    def clear_waiting(self, plan_id: str) -> None:
        try:
            os.unlink(_wait_path(self.leases.directory, plan_id))
        except OSError:
            pass

    def waiting_entries(self) -> List[Dict[str, Any]]:
        return waiting_entries(self.leases.directory, clear_dead=True)

    def waiting_others(self, plan_id: str) -> List[Dict[str, Any]]:
        return [
            e for e in self.waiting_entries()
            if e.get("plan_id") != plan_id
        ]

    def promoted(self) -> Optional[Dict[str, Any]]:
        """The fleet's oldest waiting record once it has starved past
        :func:`promotion_age`; None otherwise. Every replica computes
        this from the same directory, so promotion is fleet-wide."""
        entries = self.waiting_entries()
        if not entries:
            return None
        oldest = entries[0]
        if time.time() - float(oldest.get("since", 0.0)) \
                > promotion_age():
            return oldest
        return None

    # -- observation ------------------------------------------------------

    def free_ordinals(self) -> List[int]:
        """Ordinals claimable RIGHT NOW: no lease file, or a stale
        (breakable) one."""
        out = []
        for ordinal in range(self.size):
            info = self.leases.holder_info(f"device:{ordinal}")
            if info is None or info["stale"]:
                out.append(ordinal)
        return out

    def health(self) -> Dict[str, Any]:
        """The /readyz evidence block: pool size, this replica's held
        ordinals, the fleet's claimable count, and the waiting plans
        blocked on them."""
        waiting = self.waiting_entries()
        return {
            "size": self.size,
            "held": self.leases.held_device_ordinals(),
            "free": len(self.free_ordinals()),
            "waiting": len(waiting),
            "oldest_waiting": (
                waiting[0]["plan_id"] if waiting else None
            ),
        }
