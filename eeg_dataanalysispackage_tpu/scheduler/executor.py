"""The resident multi-tenant :class:`PlanExecutor`.

One process, N plans in flight, shared plan/feature/compile caches,
per-plan fault domains (scheduler/runtime.py), and a write-ahead
journal (scheduler/journal.py) that makes the whole thing crash-only.

Admission control deliberately reuses the serving layer's machinery
(serve/batcher.py): the same bounded :class:`AdmissionQueue` with
shed-with-evidence (a burst past ``queue_depth`` is refused with
:class:`PlanShedError` carrying the depth and the oldest queued plan's
age — never an unbounded queue, never a silent drop) and the same
resolve-once :class:`ServeFuture` behind every handle. A plan is a
bigger unit of work than a serving request, but the failure modes at
the door are identical, and two bounded queues with two shed stories
would be one too many.

Per-plan budgets:

- **deadline** — ``submit(deadline_s=...)`` threads an
  :class:`io.deadline.Deadline` through the whole execution
  (``deadline_scope``), so retry ladders underneath — io/remote
  backoff included — stop instead of sleeping past it; a plan whose
  budget died in the queue fails fast with the time it waited;
- **retries** — a failed execution attempt (a chaos injection at
  ``scheduler.plan``, a transient backend error) re-runs up to
  ``max_attempts`` with backoff; the parsed fault plan persists
  across attempts (one set of rule call counters — a ``once@N`` fault
  absorbed by attempt 1 stays absorbed). Exhaustion fails the handle
  with :class:`PlanFailedError` carrying the full attempt history and
  writes a terminal ``failed`` journal record.

Crash-only recovery: construct a fresh executor over the same
``journal_dir`` after a crash and call :meth:`PlanExecutor.recover` —
completed plans are returned as records (never re-run, their journal
files untouched), unfinished plans are re-submitted under their
original ids and produce statistics byte-identical to an uninterrupted
run (the pipeline is deterministic end to end; elastic plans re-enter
through their training checkpoints). Pinned in tests/test_scheduler.py
with a real ``SIGKILL`` mid-batch.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from .. import obs
from ..io import deadline as deadline_mod
from ..obs import chaos, domain as run_domain, events
from ..serve.batcher import (
    AdmissionQueue,
    ServeFuture,
    ServiceClosedError,
    ShedError,
)
from . import journal as journal_mod
from . import runtime

logger = logging.getLogger(__name__)


class PlanShedError(ShedError):
    """Admission control refused the plan (queue full); the message
    carries the shed evidence — depth, limit, oldest queued age — and
    ``plan_id`` names the journal record the shed wrote, so a caller
    retrying after backpressure can resubmit under the same id
    instead of minting a fresh terminal record per retry."""

    def __init__(self, message: str, plan_id: Optional[str] = None):
        super().__init__(message)
        self.plan_id = plan_id


class PlanFailedError(RuntimeError):
    """The plan exhausted its retry/deadline budget; the message
    carries the per-attempt history."""


class PlanResult:
    """A completed plan, with its execution provenance."""

    __slots__ = ("plan_id", "statistics", "builder", "attempts",
                 "report_dir", "recovered")

    def __init__(self, plan_id, statistics, builder, attempts,
                 report_dir, recovered=False):
        self.plan_id = plan_id
        self.statistics = statistics
        #: the PipelineBuilder that executed the plan — its per-run
        #: attributes (timers, run_metrics, degradation_history,
        #: mesh/precision/overlap resolution, telemetry) are the
        #: plan's isolated observability surface
        self.builder = builder
        self.attempts = attempts
        self.report_dir = report_dir
        #: True when this result came from journal recovery (a re-run
        #: of a plan some dead process left unfinished)
        self.recovered = recovered

    def __repr__(self) -> str:
        return (
            f"PlanResult({self.plan_id}, attempts={self.attempts}, "
            f"recovered={self.recovered})"
        )


class _PlanTicket:
    """One admitted plan riding the (reused) AdmissionQueue."""

    __slots__ = ("plan", "plan_id", "deadline", "future",
                 "submitted_at", "attempts", "history", "fault_plan",
                 "report_dir", "recovered")

    def __init__(self, plan, plan_id, deadline, fault_plan, report_dir,
                 recovered=False):
        self.plan = plan
        self.plan_id = plan_id
        self.deadline: Optional[deadline_mod.Deadline] = deadline
        self.future = ServeFuture()
        self.submitted_at = time.monotonic()
        self.attempts = 0
        self.history: List[str] = []
        self.fault_plan = fault_plan
        self.report_dir = report_dir
        self.recovered = recovered

    def batch_key(self):
        # plans never coalesce: every ticket is its own micro-batch
        # (the queue's collect(max_batch=1) pops exactly one)
        return self.plan_id


class PlanHandle:
    """The submitter's side of one plan: a resolve-once future."""

    __slots__ = ("plan_id", "query", "_ticket")

    def __init__(self, ticket: _PlanTicket):
        self.plan_id = ticket.plan_id
        self.query = ticket.plan.query
        self._ticket = ticket

    @property
    def done(self) -> bool:
        return self._ticket.future.done

    def result(self, timeout: Optional[float] = None) -> PlanResult:
        """Block for the outcome; raises the plan's failure
        (PlanFailedError / DeadlineExceededError / the terminal
        execution error) if it lost."""
        return self._ticket.future.result(timeout)


class PlanExecutor:
    """N worker threads draining a bounded admission queue of plans.

    ``max_concurrent`` bounds the plans in flight (each on its own
    worker thread, each in its own fault domain); ``queue_depth``
    bounds the backlog past which submissions shed. All plans share
    the process's plan/feature/compile caches — that sharing is the
    multi-tenancy dividend, and the feature cache's single-flight
    guard (io/feature_cache.py) keeps two plans missing the same entry
    from rebuilding it twice.
    """

    def __init__(
        self,
        max_concurrent: int = 2,
        queue_depth: int = 16,
        journal_dir: Optional[str] = None,
        filesystem=None,
        report_root: Optional[str] = None,
        max_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        name: str = "plans",
    ):
        if max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {max_concurrent}"
            )
        self.max_concurrent = int(max_concurrent)
        self.queue = AdmissionQueue(queue_depth)
        self.journal = (
            journal_mod.PlanJournal(journal_dir)
            if journal_dir
            else None
        )
        self._fs = filesystem
        self.report_root = report_root
        self.max_attempts = int(max_attempts)
        self.retry_backoff_s = float(retry_backoff_s)
        self.name = name
        # ids are seeded PAST anything already in the journal: a new
        # executor over a dead process's journal_dir must not mint the
        # dead process's ids and overwrite its records — submitting
        # before recover() would otherwise clobber a completed plan's
        # exactly-once record
        self._ids = itertools.count(self._seed_id() + 1)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        self._lock = threading.Lock()
        self._submit_lock = threading.Lock()

    def _seed_id(self) -> int:
        if self.journal is None:
            return 0
        max_seen = 0
        for entry in self.journal.entries():
            pid = str(entry.get("plan_id", ""))
            if pid.startswith("p"):
                try:
                    max_seen = max(max_seen, int(pid[1:]))
                except ValueError:
                    pass
        return max_seen

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for i in range(self.max_concurrent):
                t = threading.Thread(
                    target=self._worker,
                    name=f"eeg-tpu-{self.name}-worker-{i}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Stop the workers after the plan each has already popped;
        queued-but-unstarted plans stay journaled as submitted
        (recovery's job, by design) and their HANDLES are failed with
        :class:`ServiceClosedError` — an abandoned future that blocks
        its caller forever is the one outcome admission control
        exists to prevent."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=join_timeout_s)
        # the drain and every admission share _submit_lock: a submit
        # racing close() either sees _stop under the lock and refuses,
        # or lands its ticket before this drain runs — no window where
        # an admitted future is left unresolved
        with self._submit_lock:
            pending = self.queue.drain_pending()
        for ticket in pending:
            ticket.future.fail(ServiceClosedError(
                f"plan {ticket.plan_id} abandoned by executor close()"
                + (
                    "; its journal record stays 'submitted' — a new "
                    "executor's recover() will resume it"
                    if self.journal is not None
                    else "; unjournaled, the plan is lost"
                )
            ))

    def __enter__(self) -> "PlanExecutor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission ------------------------------------------------------

    def _next_id(self) -> str:
        return f"p{next(self._ids):04d}"

    def submit(
        self,
        query_or_plan,
        deadline_s: Optional[float] = None,
        plan_id: Optional[str] = None,
        _recovered: bool = False,
    ) -> PlanHandle:
        """Validate, journal, and enqueue one plan; returns its
        handle. Sheds with :class:`PlanShedError` (evidence included)
        when the queue is full — parse/validation errors raise
        *before* anything is journaled or queued, so an invalid query
        costs nothing and recovery never sees it."""
        from ..pipeline.plan import ExecutionPlan

        if self._stop.is_set():
            # the workers are gone: a silently queued plan would leave
            # its handle blocked forever (same contract as the
            # serving layer's drain)
            raise ServiceClosedError(
                "executor is closed; no new plan admissions"
            )
        self.start()
        plan = (
            query_or_plan
            if isinstance(query_or_plan, ExecutionPlan)
            else ExecutionPlan.parse(query_or_plan)
        )
        plan_id = plan_id or self._next_id()
        # one fault plan per submission, shared across retry attempts
        # (runtime.execute_plan would otherwise parse a fresh one per
        # attempt and deterministically replay the same firings)
        spec = plan.faults or chaos.plan_from_env()
        fault_plan = (
            chaos.parse_fault_spec(spec, seed=plan.faults_seed)
            if spec
            else None
        )
        report_dir = (
            None
            if self.report_root is None
            else f"{self.report_root.rstrip('/')}/{plan_id}"
        )
        deadline = (
            deadline_mod.Deadline(deadline_s)
            if deadline_s is not None
            else None
        )
        ticket = _PlanTicket(
            plan, plan_id, deadline, fault_plan, report_dir,
            recovered=_recovered,
        )
        with self._submit_lock:
            # checked under the same lock close() drains under: a
            # submit racing close() either refuses here or lands its
            # ticket before the drain — never an abandoned future.
            # The journal write sits under the SAME check: refusing
            # after record_submitted would strand a 'submitted'
            # record for a plan the caller was told was never
            # admitted — recover() would silently re-run it alongside
            # the caller's resubmission.
            if self._stop.is_set():
                raise ServiceClosedError(
                    "executor is closed; no new plan admissions"
                )
            if self.journal is not None:
                # journal writes belong to the plan's fault domain:
                # its scheduler.journal chaos rules govern them, and
                # ONLY its (the submit-side record rides a minimal
                # domain — no recorder/metrics child exists yet)
                with run_domain.activate(run_domain.RunDomain(
                    plan_id=plan_id, chaos=fault_plan
                )):
                    self.journal.record_submitted(
                        plan_id, plan.query,
                        meta={
                            "deadline_s": deadline_s,
                            "report_dir": report_dir,
                            "recovered": _recovered,
                        },
                    )
            if _recovered:
                # journal recovery must NEVER shed: these plans were
                # admitted once by the dead process, and a shed here
                # would write a terminal record for work that never
                # ran — permanent loss. Same rule as the batcher's
                # retry re-admission (the bound is the journal's own
                # size).
                self.queue.readmit(ticket)
                admitted = True
            else:
                # the offer and its evidence read are one atomic
                # decision under the lock: two threads shedding
                # concurrently must each journal THEIR OWN evidence,
                # not the other's
                admitted = self.queue.offer(ticket, block_s=0.0)
                evidence = (
                    "" if admitted else self.queue.last_shed_evidence
                )
        if not admitted:
            # same invariant as every other journal write: the shed
            # record (and its counter) belongs to THIS plan's fault
            # domain — a submit() called from inside another tenant's
            # domain must not charge the shed to that tenant's chaos
            # rules or metrics child
            with run_domain.activate(run_domain.RunDomain(
                plan_id=plan_id, chaos=fault_plan
            )):
                obs.metrics.count("scheduler.shed")
                if self.journal is not None:
                    self.journal.record_failed(
                        plan_id, plan.query,
                        error=f"shed at admission: {evidence}",
                        attempts=0,
                    )
            raise PlanShedError(
                f"plan {plan_id} shed at admission: {evidence}",
                plan_id=plan_id,
            )
        # same domain rule as the shed branch: submission accounting
        # belongs to the NEW plan, not to whatever tenant's domain is
        # ambient on the submitting thread
        with run_domain.activate(run_domain.RunDomain(
            plan_id=plan_id, chaos=fault_plan
        )):
            obs.metrics.count("scheduler.submitted")
            events.event("scheduler.submitted", plan=plan_id)
        return PlanHandle(ticket)

    def run(
        self, queries, deadline_s: Optional[float] = None,
        timeout_s: Optional[float] = None,
    ) -> List[PlanResult]:
        """Submit every query and block for all results, in order —
        the batch-driver convenience over the async surface.

        A shed mid-batch is BACKPRESSURE here, not loss: raising out
        of the submit loop would abandon the already-admitted handles
        (their plans keep running, journaling results the caller can
        no longer reach). Instead the batch waits for one of its own
        in-flight plans — whose worker pop freed queue space — and
        retries UNDER THE SHED PLAN'S ID, so the journal converges to
        one record per logical plan (the transient shed's 'failed'
        record is overwritten by the retry's write-ahead record)
        instead of accumulating a terminal failure per backpressure
        bounce. Only with none of its own plans in flight is a shed
        genuine (other tenants own the depth) and re-raised — its
        failed record then stands as the evidence."""
        handles: List[PlanHandle] = []
        for q in queries:
            retry_id: Optional[str] = None
            while True:
                try:
                    handles.append(self.submit(
                        q, deadline_s=deadline_s, plan_id=retry_id,
                    ))
                    break
                except PlanShedError as shed:
                    retry_id = shed.plan_id or retry_id
                    in_flight = next(
                        (h for h in handles if not h.done), None
                    )
                    if in_flight is None:
                        raise
                    try:
                        in_flight.result(timeout=timeout_s)
                    except Exception:
                        # resolved-with-error still freed its slot
                        # (the error resurfaces from the collection
                        # below — and a plan's own
                        # DeadlineExceededError is a resolution, not
                        # our wait expiring). An UNresolved handle
                        # means the wait itself timed out: re-raise
                        # rather than busy-loop on a queue another
                        # tenant is holding full.
                        if not in_flight.done:
                            raise
        return [h.result(timeout=timeout_s) for h in handles]

    # -- crash-only recovery ---------------------------------------------

    def recover(self) -> Dict[str, Any]:
        """Resume a journaled workload after a crash: every unfinished
        record is re-submitted under its ORIGINAL plan id (handles
        returned for the caller to await), every terminal record is
        returned untouched — completed plans are exactly-once by
        construction. Requires a ``journal_dir``."""
        if self.journal is None:
            raise ValueError(
                "recover() needs a journal_dir — an unjournaled "
                "executor has nothing to recover from"
            )
        resumed: List[PlanHandle] = []
        completed: List[Dict[str, Any]] = []
        failed: List[Dict[str, Any]] = []
        for entry in self.journal.entries():
            state = entry.get("state")
            if state == journal_mod.COMPLETED:
                completed.append(entry)
            elif state == journal_mod.FAILED:
                failed.append(entry)
            elif state == journal_mod.SUBMITTED:
                meta = entry.get("meta") or {}
                resumed.append(self.submit(
                    entry["query"],
                    deadline_s=meta.get("deadline_s"),
                    plan_id=entry["plan_id"],
                    _recovered=True,
                ))
        # fresh ids already start past the dead process's (the
        # constructor seeds the counter from the journal)
        obs.metrics.count("scheduler.recovered_plans", len(resumed))
        logger.info(
            "journal recovery: %d completed (kept), %d failed (kept), "
            "%d unfinished re-submitted",
            len(completed), len(failed), len(resumed),
        )
        return {
            "resumed": resumed,
            "completed": completed,
            "failed": failed,
        }

    # -- the worker loop -------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.collect(
                max_batch=1, wait_s=0.05, coalesce_s=0.0
            )
            if not batch:
                continue
            self._execute_ticket(batch[0])

    def _execute_ticket(self, ticket: _PlanTicket) -> None:
        from ..pipeline.builder import PipelineBuilder

        while True:
            if ticket.deadline is not None and ticket.deadline.expired:
                # attempts == 0: the budget died in the admission
                # queue. attempts > 0: it died during the retry
                # backoff sleep (can_cover guarded the sleep itself,
                # not the attempt after it) — either way, building a
                # fresh PipelineBuilder and telemetry dir for an
                # attempt that fails at its first deadline checkpoint
                # is pure waste: fail fast here.
                waited = time.monotonic() - ticket.submitted_at
                obs.metrics.count("scheduler.deadline_exceeded")
                if ticket.attempts == 0:
                    msg = (
                        f"deadline ({ticket.deadline.budget_s:.3f}s "
                        f"budget) exceeded after {waited:.3f}s in the "
                        f"admission queue; plan was never executed"
                    )
                else:
                    msg = (
                        f"deadline ({ticket.deadline.budget_s:.3f}s "
                        f"budget) expired during retry backoff after "
                        f"{ticket.attempts} failed; attempts: "
                        f"{ticket.history}"
                    )
                self._record_failed(ticket, msg)
                ticket.future.fail(deadline_mod.DeadlineExceededError(
                    f"plan {ticket.plan_id}: {msg}"
                ))
                return
            builder = PipelineBuilder(
                ticket.plan.query, filesystem=self._fs
            )
            try:
                with deadline_mod.deadline_scope(ticket.deadline):
                    statistics = runtime.execute_plan(
                        ticket.plan,
                        builder,
                        plan_id=ticket.plan_id,
                        fault_plan=ticket.fault_plan,
                        default_report_dir=ticket.report_dir,
                    )
            except Exception as e:
                ticket.attempts += 1
                ticket.history.append(
                    f"attempt {ticket.attempts}: "
                    f"{type(e).__name__}: {e}"
                )
                obs.metrics.count("scheduler.attempt_failures")
                events.event(
                    "scheduler.attempt_failed",
                    plan=ticket.plan_id, attempt=ticket.attempts,
                    error=f"{type(e).__name__}: {e}",
                )
                if isinstance(e, ValueError):
                    # caller bugs (conflicting knobs, bad grammar the
                    # IR could not see statically) fail identically on
                    # every attempt — surface NOW with the real error
                    self._record_failed(ticket, ticket.history[-1])
                    ticket.future.fail(e)
                    return
                if ticket.attempts >= self.max_attempts:
                    self._record_failed(
                        ticket,
                        f"retry budget ({self.max_attempts}) "
                        f"exhausted; attempts: {ticket.history}",
                    )
                    ticket.future.fail(PlanFailedError(
                        f"plan {ticket.plan_id} failed after "
                        f"{ticket.attempts} attempts (budget "
                        f"{self.max_attempts}); attempts: "
                        f"{ticket.history}"
                    ))
                    return
                if (
                    ticket.deadline is not None
                    and not ticket.deadline.can_cover(
                        self.retry_backoff_s
                    )
                ):
                    obs.metrics.count("scheduler.deadline_exceeded")
                    self._record_failed(
                        ticket,
                        f"deadline cannot cover another attempt "
                        f"after {ticket.attempts} failed; attempts: "
                        f"{ticket.history}",
                    )
                    ticket.future.fail(
                        deadline_mod.DeadlineExceededError(
                            f"plan {ticket.plan_id}: deadline "
                            f"({ticket.deadline.budget_s:.3f}s "
                            f"budget) cannot cover another attempt "
                            f"after {ticket.attempts} failed; "
                            f"attempts: {ticket.history}"
                        )
                    )
                    return
                obs.metrics.count("scheduler.retries")
                time.sleep(self.retry_backoff_s)
                continue
            ticket.attempts += 1
            if self.journal is not None:
                # same fault-domain rule as the submit-side record
                with run_domain.activate(run_domain.RunDomain(
                    plan_id=ticket.plan_id, chaos=ticket.fault_plan
                )):
                    self.journal.record_completed(
                        ticket.plan_id, ticket.plan.query,
                        str(statistics),
                        attempts=ticket.attempts,
                        meta={"recovered": ticket.recovered},
                    )
            obs.metrics.count("scheduler.completed")
            events.event(
                "scheduler.completed", plan=ticket.plan_id,
                attempts=ticket.attempts,
            )
            ticket.future.resolve(PlanResult(
                plan_id=ticket.plan_id,
                statistics=statistics,
                builder=builder,
                attempts=ticket.attempts,
                report_dir=ticket.report_dir,
                recovered=ticket.recovered,
            ))
            return

    def _record_failed(self, ticket: _PlanTicket, error: str) -> None:
        obs.metrics.count("scheduler.failed")
        if self.journal is not None:
            with run_domain.activate(run_domain.RunDomain(
                plan_id=ticket.plan_id, chaos=ticket.fault_plan
            )):
                self.journal.record_failed(
                    ticket.plan_id, ticket.plan.query, error,
                    attempts=ticket.attempts,
                )
