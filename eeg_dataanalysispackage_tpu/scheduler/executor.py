"""The resident multi-tenant :class:`PlanExecutor`.

One process, N plans in flight, shared plan/feature/compile caches,
per-plan fault domains (scheduler/runtime.py), and a write-ahead
journal (scheduler/journal.py) that makes the whole thing crash-only.

Admission control deliberately reuses the serving layer's machinery
(serve/batcher.py): the same bounded :class:`AdmissionQueue` with
shed-with-evidence (a burst past ``queue_depth`` is refused with
:class:`PlanShedError` carrying the depth and the oldest queued plan's
age — never an unbounded queue, never a silent drop) and the same
resolve-once :class:`ServeFuture` behind every handle. A plan is a
bigger unit of work than a serving request, but the failure modes at
the door are identical, and two bounded queues with two shed stories
would be one too many.

Per-plan budgets:

- **deadline** — ``submit(deadline_s=...)`` threads an
  :class:`io.deadline.Deadline` through the whole execution
  (``deadline_scope``), so retry ladders underneath — io/remote
  backoff included — stop instead of sleeping past it; a plan whose
  budget died in the queue fails fast with the time it waited;
- **retries** — a failed execution attempt (a chaos injection at
  ``scheduler.plan``, a transient backend error) re-runs up to
  ``max_attempts`` with backoff; the parsed fault plan persists
  across attempts (one set of rule call counters — a ``once@N`` fault
  absorbed by attempt 1 stays absorbed). Exhaustion fails the handle
  with :class:`PlanFailedError` carrying the full attempt history and
  writes a terminal ``failed`` journal record.

Crash-only recovery: construct a fresh executor over the same
``journal_dir`` after a crash and call :meth:`PlanExecutor.recover` —
completed plans are returned as records (never re-run, their journal
files untouched), unfinished plans are re-submitted under their
original ids and produce statistics byte-identical to an uninterrupted
run (the pipeline is deterministic end to end; elastic plans re-enter
through their training checkpoints). Pinned in tests/test_scheduler.py
with a real ``SIGKILL`` mid-batch.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from .. import obs
from ..io import deadline as deadline_mod
from ..obs import chaos, domain as run_domain, events
from ..serve.batcher import (
    AdmissionQueue,
    ServeFuture,
    ServiceClosedError,
    ShedError,
)
from . import journal as journal_mod
from . import lease as lease_mod
from . import runtime

logger = logging.getLogger(__name__)

#: sentinel returned by :meth:`PlanExecutor._try_place` when a plan's
#: footprint cannot currently be satisfied: the worker readmits the
#: ticket to the queue TAIL (smaller plans backfill past it) and the
#: journal record stays untouched.  Distinct from ``None``, which
#: means "run unplaced" (exempt, unsatisfiable, or pool degraded).
_PLACEMENT_WAIT = object()


class PlanShedError(ShedError):
    """Admission control refused the plan (queue full); the message
    carries the shed evidence — depth, limit, oldest queued age — and
    ``plan_id`` names the journal record the shed wrote, so a caller
    retrying after backpressure can resubmit under the same id
    instead of minting a fresh terminal record per retry."""

    def __init__(self, message: str, plan_id: Optional[str] = None):
        super().__init__(message)
        self.plan_id = plan_id


class PlanFailedError(RuntimeError):
    """The plan exhausted its retry/deadline budget; the message
    carries the per-attempt history."""


class PlanCancelledError(RuntimeError):
    """The plan was cancelled by its client while still queued (the
    gateway's DELETE); it never executed."""


class PlanOwnedElsewhereError(RuntimeError):
    """A lease-holding fleet peer owns this plan's execution: this
    executor must not run it (doing so would double-execute). The
    holder id rides along so the gateway can answer with the owner
    hint instead of an error."""

    def __init__(self, message: str, plan_id: str, holder: Optional[str]):
        super().__init__(message)
        self.plan_id = plan_id
        self.holder = holder


class IdempotencyConflictError(ValueError):
    """An idempotency key was reused with a DIFFERENT query body.
    Replaying the original plan's outcome would silently hand the
    caller statistics computed for a query it did not send, and
    running the new body would break the key's exactly-once meaning —
    so the reuse is rejected loudly (the gateway maps it to 409)."""


class PlanResult:
    """A completed plan, with its execution provenance."""

    __slots__ = ("plan_id", "statistics", "builder", "attempts",
                 "report_dir", "recovered", "replayed")

    def __init__(self, plan_id, statistics, builder, attempts,
                 report_dir, recovered=False, replayed=False):
        self.plan_id = plan_id
        self.statistics = statistics
        #: the PipelineBuilder that executed the plan — its per-run
        #: attributes (timers, run_metrics, degradation_history,
        #: mesh/precision/overlap resolution, telemetry) are the
        #: plan's isolated observability surface. None for a replayed
        #: result (the outcome came from the journal; ``statistics``
        #: is then the journaled text, equal under ``str()``).
        self.builder = builder
        self.attempts = attempts
        self.report_dir = report_dir
        #: True when this result came from journal recovery (a re-run
        #: of a plan some dead process left unfinished)
        self.recovered = recovered
        #: True when this result was REPLAYED from a terminal journal
        #: record (an idempotency-keyed re-submit of a completed plan:
        #: exactly-once, nothing re-executed)
        self.replayed = replayed

    def __repr__(self) -> str:
        return (
            f"PlanResult({self.plan_id}, attempts={self.attempts}, "
            f"recovered={self.recovered}, replayed={self.replayed})"
        )


class _PlanTicket:
    """One admitted plan riding the (reused) AdmissionQueue."""

    __slots__ = ("plan", "plan_id", "deadline", "future",
                 "submitted_at", "attempts", "history", "fault_plan",
                 "report_dir", "recovered", "state",
                 "idempotency_key", "gateway", "fleet", "trace_id",
                 "footprint")

    def __init__(self, plan, plan_id, deadline, fault_plan, report_dir,
                 recovered=False, idempotency_key=None, gateway=None,
                 fleet=None, trace_id=None):
        self.plan = plan
        self.plan_id = plan_id
        self.deadline: Optional[deadline_mod.Deadline] = deadline
        self.future = ServeFuture()
        self.submitted_at = time.monotonic()
        self.attempts = 0
        self.history: List[str] = []
        self.fault_plan = fault_plan
        self.report_dir = report_dir
        self.recovered = recovered
        #: the gateway's status surface: queued -> running ->
        #: completed | failed | cancelled (transitions written by the
        #: submit/worker/cancel paths that own each edge)
        self.state = "queued"
        self.idempotency_key = idempotency_key
        #: networked-submission attribution (gateway/), echoed into
        #: the plan's run report; None for in-process submissions
        self.gateway = gateway
        #: fleet attribution ({"replica", "takeover"}), echoed into
        #: the plan's run report; None outside a replica fleet
        self.fleet = fleet
        #: distributed trace id (gateway-minted, journaled with the
        #: plan meta so a takeover CONTINUES the trace); None for
        #: untraced submissions
        self.trace_id = trace_id
        #: cached ExecutionPlan.device_footprint() — computed once by
        #: the first placement attempt, reused every backfill retry
        self.footprint = None

    def batch_key(self):
        # plans never coalesce: every ticket is its own micro-batch
        # (the queue's collect(max_batch=1) pops exactly one)
        return self.plan_id


class _ReplayTicket:
    """A terminal journal record wearing the ticket interface: the
    resolved handle an idempotency-keyed re-submit of a finished plan
    gets back — nothing is re-executed, the journaled outcome IS the
    outcome (exactly-once made client-visible)."""

    __slots__ = ("plan_id", "query", "future", "state", "attempts",
                 "history", "recovered", "idempotency_key", "gateway")

    def __init__(self, entry: Dict[str, Any]):
        meta = entry.get("meta") or {}
        self.plan_id = entry["plan_id"]
        self.query = entry.get("query", "")
        self.future = ServeFuture()
        self.attempts = int(entry.get("attempts", 1) or 0)
        self.history: List[str] = []
        self.recovered = bool(meta.get("recovered"))
        self.idempotency_key = meta.get("idempotency_key")
        self.gateway = meta.get("gateway")
        if entry.get("state") == journal_mod.COMPLETED:
            self.state = "completed"
            self.future.resolve(PlanResult(
                plan_id=self.plan_id,
                statistics=entry.get("statistics", ""),
                builder=None,
                attempts=self.attempts,
                report_dir=meta.get("report_dir"),
                recovered=self.recovered,
                replayed=True,
            ))
        else:
            self.state = "failed"
            self.future.fail(PlanFailedError(
                f"plan {self.plan_id} failed (journaled outcome, not "
                f"re-executed): {entry.get('error', '')}"
            ))


class PlanHandle:
    """The submitter's side of one plan: a resolve-once future."""

    __slots__ = ("plan_id", "query", "_ticket", "replayed")

    def __init__(self, ticket, replayed: bool = False):
        self.plan_id = ticket.plan_id
        self.query = (
            ticket.query if isinstance(ticket, _ReplayTicket)
            else ticket.plan.query
        )
        self._ticket = ticket
        #: True when this handle resolves a prior submission's outcome
        #: (an idempotency-keyed re-submit): the plan id is the
        #: ORIGINAL one and nothing was enqueued for this call
        self.replayed = replayed

    @property
    def done(self) -> bool:
        return self._ticket.future.done

    @property
    def state(self) -> str:
        """queued | running | completed | failed | cancelled."""
        return self._ticket.state

    @property
    def attempts(self) -> int:
        return self._ticket.attempts

    @property
    def history(self) -> List[str]:
        return list(self._ticket.history)

    def result(self, timeout: Optional[float] = None) -> PlanResult:
        """Block for the outcome; raises the plan's failure
        (PlanFailedError / DeadlineExceededError / the terminal
        execution error) if it lost."""
        return self._ticket.future.result(timeout)


class PlanExecutor:
    """N worker threads draining a bounded admission queue of plans.

    ``max_concurrent`` bounds the plans in flight (each on its own
    worker thread, each in its own fault domain); ``queue_depth``
    bounds the backlog past which submissions shed. All plans share
    the process's plan/feature/compile caches — that sharing is the
    multi-tenancy dividend, and the feature cache's single-flight
    guard (io/feature_cache.py) keeps two plans missing the same entry
    from rebuilding it twice.
    """

    def __init__(
        self,
        max_concurrent: int = 2,
        queue_depth: int = 16,
        journal_dir: Optional[str] = None,
        filesystem=None,
        report_root: Optional[str] = None,
        max_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        name: str = "plans",
    ):
        if max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {max_concurrent}"
            )
        self.max_concurrent = int(max_concurrent)
        self.queue = AdmissionQueue(queue_depth)
        self.journal = (
            journal_mod.PlanJournal(journal_dir)
            if journal_dir
            else None
        )
        self._fs = filesystem
        #: the fleet's lease directory (scheduler/lease.py LeaseDir),
        #: attached by gateway/fleet.py BEFORE any submission. With it
        #: set, every admitted plan is lease-claimed atomically with
        #: its write-ahead record (a peer replica scanning the shared
        #: journal can never see an unleased record for a plan a live
        #: replica is executing) and released when the plan's terminal
        #: record lands. None (the default) = no fleet, no leases.
        self.leases: Optional[lease_mod.LeaseDir] = None
        #: the fleet's shared device pool (scheduler/placement.py
        #: DevicePool), attached by gateway/fleet.py when
        #: EEG_TPU_DEVICE_POOL enables placement. With it set, a
        #: popped plan's footprint is lease-claimed all-or-nothing
        #: before execution; an unsatisfiable footprint goes back to
        #: the queue's TAIL (journal state unchanged) so smaller plans
        #: backfill past it, bounded by the pool's age-based
        #: no-starvation promotion. None = unplaced execution, the
        #: pre-placement behavior byte-unchanged.
        self.placement = None
        #: pod-assist runner (gateway/fleet.py PodAssist): executes a
        #: ``processes>1`` plan by driving the pod bootstrap as
        #: coordinator with peer replicas enlisted as workers. None =
        #: pod plans run in-process (the builder's own pod ladder).
        self.pod_assist = None
        #: seconds a worker pauses after parking an unplaceable plan
        #: back on the queue — bounds the claim-file churn of a lone
        #: waiting gang without delaying backfill noticeably
        self.placement_backoff_s = 0.02
        #: set by drain_queued(): a worker holding a placement-WAITING
        #: ticket (popped, so queue.remove missed it) hands it back
        #: instead of re-queueing into a draining executor
        self._drain_requested = False
        self.report_root = report_root
        self.max_attempts = int(max_attempts)
        self.retry_backoff_s = float(retry_backoff_s)
        self.name = name
        # ids are seeded PAST anything already in the journal: a new
        # executor over a dead process's journal_dir must not mint the
        # dead process's ids and overwrite its records — submitting
        # before recover() would otherwise clobber a completed plan's
        # exactly-once record
        self._ids = itertools.count(self._seed_id() + 1)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        self._lock = threading.Lock()
        self._submit_lock = threading.Lock()
        #: every live ticket this executor admitted, by plan id — the
        #: status/cancel/idempotent-rejoin surface. Once a TERMINAL
        #: journal record has LANDED the ticket is evicted (a
        #: completed result pins its whole PipelineBuilder; failed/
        #: cancelled tickets pin their plan + fault plan) —
        #: status()/keyed re-submits fall back to the journal — so a
        #: resident executor's memory stays bounded by its queue, not
        #: its history. A degraded journal write keeps the ticket:
        #: the live copy is then the only record. Unjournaled
        #: executors keep everything (the in-process result surface).
        self._tickets: Dict[str, Any] = {}
        #: idempotency key -> plan id, seeded from the journal so a
        #: retried submit after a crash resolves to the ORIGINAL plan
        self._idempotency: Dict[str, str] = self._seed_idempotency()

    def _seed_id(self) -> int:
        if self.journal is None:
            return 0
        max_seen = 0
        for entry in self.journal.entries():
            pid = str(entry.get("plan_id", ""))
            if pid.startswith("p"):
                try:
                    max_seen = max(max_seen, int(pid[1:]))
                except ValueError:
                    pass
        return max_seen

    def _seed_idempotency(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        if self.journal is None:
            return out
        for entry in self.journal.entries():
            key = (entry.get("meta") or {}).get("idempotency_key")
            if key:
                out[str(key)] = entry["plan_id"]
        return out

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for i in range(self.max_concurrent):
                t = threading.Thread(
                    target=self._worker,
                    name=f"eeg-tpu-{self.name}-worker-{i}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Stop the workers after the plan each has already popped;
        queued-but-unstarted plans stay journaled as submitted
        (recovery's job, by design) and their HANDLES are failed with
        :class:`ServiceClosedError` — an abandoned future that blocks
        its caller forever is the one outcome admission control
        exists to prevent."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=join_timeout_s)
        # the drain and every admission share _submit_lock: a submit
        # racing close() either sees _stop under the lock and refuses,
        # or lands its ticket before this drain runs — no window where
        # an admitted future is left unresolved
        with self._submit_lock:
            pending = self.queue.drain_pending()
        for ticket in pending:
            ticket.state = "failed"
            # the journal record stays 'submitted'; releasing the
            # lease is what lets a fleet peer claim it NOW instead of
            # waiting out the stale-break timeout on a dead holder
            self._release_lease(ticket.plan_id)
            ticket.future.fail(ServiceClosedError(
                f"plan {ticket.plan_id} abandoned by executor close()"
                + (
                    "; its journal record stays 'submitted' — a new "
                    "executor's recover() will resume it"
                    if self.journal is not None
                    else "; unjournaled, the plan is lost"
                )
            ))

    def __enter__(self) -> "PlanExecutor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission ------------------------------------------------------

    #: how long a keyed submit waits (under ``_submit_lock``) for a
    #: fleet peer holding the key's registration claim to land its
    #: write-ahead record before degrading to a best-effort mint
    key_claim_wait_s = 1.0

    def _next_id(self) -> str:
        return f"p{next(self._ids):04d}"

    def _resolve_fleet_key(
        self, idempotency_key: str,
    ):
        """Resolve a previously-unseen idempotency key against the
        FLEET: re-seed the key index from the shared journal and,
        because two replicas can receive the same new key concurrently
        — each missing on the re-seed before either has journaled —
        serialize registration through a key-scoped lease
        (:func:`~.lease.key_claim_id`, the plan claim's own O_EXCL
        primitive). Returns ``(existing_plan_id, key_claim)``:

        - ``(plan_id, None)`` — the key is already bound (possibly by
          a peer); the caller takes the replay/rejoin/readmit path;
        - ``(None, PlanLease)`` — this replica holds the fleet-wide
          registration right; the caller MUST release the claim once
          its write-ahead record (which carries the binding) lands;
        - ``(None, None)`` — claiming unavailable, or a live peer held
          the claim past :attr:`key_claim_wait_s` without journaling
          (died mid-registration — its claim breaks once stale — or
          pathologically slow): degrade to a best-effort mint
          (``scheduler.key_claim_degraded``) rather than wedge the
          submit path.
        """
        claim_id = lease_mod.key_claim_id(idempotency_key)

        def _reseed() -> Optional[str]:
            # setdefault: live local mappings always win — the shared
            # journal is authoritative only for keys this process has
            # never seen
            for k, v in self._seed_idempotency().items():
                self._idempotency.setdefault(k, v)
            return self._idempotency.get(idempotency_key)

        deadline = time.monotonic() + self.key_claim_wait_s
        while True:
            claim = self.leases.try_claim(claim_id)
            if isinstance(claim, lease_mod.PlanLease):
                existing = _reseed()
                if existing is not None:
                    # the binding landed between our first miss and
                    # the claim winning — the claim is moot
                    self.leases.release(claim_id)
                    return existing, None
                return None, claim
            existing = _reseed()
            if existing is not None:
                return existing, None
            if claim is None:
                # locking unavailable (degraded journal dir, chaos):
                # fleet key dedup is best-effort this round
                obs.metrics.count("scheduler.key_claim_degraded")
                return None, None
            if time.monotonic() >= deadline:
                obs.metrics.count("scheduler.key_claim_degraded")
                logger.warning(
                    "idempotency key %r: registration claim held "
                    "elsewhere past %.1fs without a journaled "
                    "binding; proceeding best-effort",
                    idempotency_key, self.key_claim_wait_s,
                )
                return None, None
            time.sleep(0.02)

    def submit(
        self,
        query_or_plan,
        deadline_s: Optional[float] = None,
        plan_id: Optional[str] = None,
        _recovered: bool = False,
        idempotency_key: Optional[str] = None,
        gateway: Optional[Dict[str, Any]] = None,
        fleet: Optional[Dict[str, Any]] = None,
        trace_id: Optional[str] = None,
    ) -> PlanHandle:
        """Validate, journal, and enqueue one plan; returns its
        handle. Sheds with :class:`PlanShedError` (evidence included)
        when the queue is full — parse/validation errors raise
        *before* anything is journaled or queued, so an invalid query
        costs nothing and recovery never sees it.

        ``idempotency_key`` makes the submission retry-safe across
        crashes and timeouts: the key is journaled with the plan
        record, and a re-submit carrying the same key returns the
        ORIGINAL plan's handle — the live ticket while it runs, the
        journaled outcome once it is terminal (completed plans are
        never re-executed), a recovery re-admission when a dead
        process left only the write-ahead record. A shed never burns
        the key (backpressure must stay retryable), and neither does
        a client cancel.

        ``gateway`` is networked-submission attribution ({"via",
        "idempotency_key", "client"}), journaled and echoed into the
        plan's run report. ``fleet`` is replica attribution
        ({"replica", "takeover"}) — defaulted from the attached lease
        directory when one exists.

        With a lease directory attached (a fleet replica), admission
        claims the plan's lease BEFORE the write-ahead record lands;
        a plan whose lease a live peer holds raises
        :class:`PlanOwnedElsewhereError` instead of double-executing.
        A previously-unseen idempotency key is additionally registered
        under a fleet-wide key-scoped lease
        (:meth:`_resolve_fleet_key`), so two replicas racing one new
        key mint exactly one plan."""
        from ..pipeline.plan import ExecutionPlan

        if self._stop.is_set():
            # the workers are gone: a silently queued plan would leave
            # its handle blocked forever (same contract as the
            # serving layer's drain)
            raise ServiceClosedError(
                "executor is closed; no new plan admissions"
            )
        self.start()
        plan = (
            query_or_plan
            if isinstance(query_or_plan, ExecutionPlan)
            else ExecutionPlan.parse(query_or_plan)
        )
        # one fault plan per submission, shared across retry attempts
        # (runtime.execute_plan would otherwise parse a fresh one per
        # attempt and deterministically replay the same firings)
        spec = plan.faults or chaos.plan_from_env()
        fault_plan = (
            chaos.parse_fault_spec(spec, seed=plan.faults_seed)
            if spec
            else None
        )
        deadline = (
            deadline_mod.Deadline(deadline_s)
            if deadline_s is not None
            else None
        )
        with self._submit_lock:
            # checked under the same lock close() drains under: a
            # submit racing close() either refuses here or lands its
            # ticket before the drain — never an abandoned future.
            # The journal write sits under the SAME check: refusing
            # after record_submitted would strand a 'submitted'
            # record for a plan the caller was told was never
            # admitted — recover() would silently re-run it alongside
            # the caller's resubmission.
            if self._stop.is_set():
                raise ServiceClosedError(
                    "executor is closed; no new plan admissions"
                )
            if _recovered and plan_id is not None:
                live = self._tickets.get(plan_id)
                if live is not None:
                    # an idempotency-keyed re-submit raced recover()
                    # and already re-admitted this journal record
                    # under its original id — one ticket, one
                    # execution (re-admitting again would run the
                    # same plan twice into the same report_dir)
                    return PlanHandle(live, replayed=True)
            key_claim: Optional[lease_mod.PlanLease] = None
            if idempotency_key and not _recovered:
                # the check and the (later) registration share this
                # lock: two concurrent submits with one key resolve to
                # exactly one execution
                existing = self._idempotency.get(idempotency_key)
                if (
                    existing is None
                    and self.leases is not None
                    and self.journal is not None
                ):
                    # fleet: peers journal keys after this replica
                    # seeded its map, so the shared journal — not the
                    # in-memory cache — is the authoritative key
                    # index, and REGISTERING a previously-unseen key
                    # must itself be serialized across replicas: two
                    # replicas receiving one new key concurrently
                    # would each miss on the re-seed (neither has
                    # journaled yet) and each mint its own plan. The
                    # key-scoped lease closes that window; a non-None
                    # key_claim comes back held and MUST be released
                    # once the write-ahead record (which carries the
                    # binding) lands.
                    existing, key_claim = self._resolve_fleet_key(
                        idempotency_key
                    )
                if existing is not None:
                    live = self._tickets.get(existing)
                    entry = (
                        self.journal.entry(existing)
                        if self.journal is not None and live is None
                        else None
                    )
                    # the key's original query — replaying a DIFFERENT
                    # body's outcome (or running a new body under the
                    # old id) would both be silent lies
                    original = (
                        live.plan.query if live is not None
                        else entry.get("query") if entry is not None
                        else None
                    )
                    if original is not None and original != plan.query:
                        raise IdempotencyConflictError(
                            f"idempotency key {idempotency_key!r} was "
                            f"already used for a different query "
                            f"(plan {existing}); retry with the "
                            f"original body or a fresh key"
                        )
                    if live is not None:
                        obs.metrics.count("scheduler.idempotent_rejoin")
                        events.event(
                            "scheduler.idempotent_rejoin", plan=existing
                        )
                        return PlanHandle(live, replayed=True)
                    if entry is not None and entry.get("state") in (
                        journal_mod.COMPLETED, journal_mod.FAILED
                    ):
                        # terminal: replay the journaled outcome —
                        # exactly-once, nothing enqueued
                        obs.metrics.count("scheduler.idempotent_replay")
                        events.event(
                            "scheduler.idempotent_replay", plan=existing
                        )
                        return PlanHandle(
                            _ReplayTicket(entry), replayed=True
                        )
                    if entry is not None:
                        # a dead process's write-ahead record that
                        # recover() has not resumed: re-admit under
                        # the ORIGINAL id — never shed, it was
                        # admitted once
                        plan_id = existing
                        _recovered = True
                    # else: the mapping points at a record a degraded
                    # journal lost — fall through as a fresh submit
            fresh = plan_id is None
            if fresh:
                # minted only once the idempotency checks are past: a
                # replayed/rejoined submit consumes no id (ids in the
                # journal stay gapless under replay-heavy clients)
                plan_id = self._next_id()
            if self.leases is not None:
                # the lease is claimed BEFORE the write-ahead record:
                # a fleet peer scanning the shared journal therefore
                # never sees an unleased 'submitted' record for a plan
                # a live replica owns — the window that would double-
                # execute.
                if fresh:
                    # the lease is ALSO the fleet's cross-process id
                    # allocator: every replica mints from its own
                    # local counter, so two replicas over one journal
                    # WILL collide — a foreign-held fresh id is simply
                    # taken, mint the next. A claim that succeeds on
                    # an id whose journal record already exists found
                    # a peer's finished plan (terminal records hold no
                    # lease): release and move on — overwriting it
                    # would erase a served result. The peer's write
                    # happened-before its release happened-before our
                    # claim, so the under-lease record check is final.
                    # The record check runs EVEN when the claim came
                    # back None (lease dir degraded, chaos): a failed
                    # claim says nothing about ownership, and writing
                    # our record over a peer's — possibly terminal —
                    # one would erase a served result and resurface it
                    # as 'submitted'.
                    while True:
                        claim = self.leases.try_claim(plan_id)
                        if claim is lease_mod.FOREIGN_HELD:
                            plan_id = self._next_id()
                            continue
                        if (
                            self.journal is not None
                            and self.journal.entry(plan_id) is not None
                        ):
                            if claim is not None:
                                self.leases.release(plan_id)
                            plan_id = self._next_id()
                            continue
                        break
                elif self.leases.held(plan_id) is None:
                    claim = self.leases.try_claim(plan_id)
                    if claim is lease_mod.FOREIGN_HELD:
                        info = self.leases.holder_info(plan_id)
                        holder = info["holder"] if info else None
                        raise PlanOwnedElsewhereError(
                            f"plan {plan_id} is lease-held by replica "
                            f"{holder!r}; this replica will not "
                            f"double-execute it",
                            plan_id=plan_id, holder=holder,
                        )
                    # claim may be None (locking unavailable): proceed
                    # leaseless — the journal dir is degraded anyway
                    # and /readyz reports it
                if fleet is None:
                    fleet = {
                        "replica": self.leases.holder,
                        "takeover": False,
                    }
            report_dir = (
                None
                if self.report_root is None
                else f"{self.report_root.rstrip('/')}/{plan_id}"
            )
            ticket = _PlanTicket(
                plan, plan_id, deadline, fault_plan, report_dir,
                recovered=_recovered, idempotency_key=idempotency_key,
                gateway=gateway, fleet=fleet, trace_id=trace_id,
            )
            if self.journal is not None:
                # journal writes belong to the plan's fault domain:
                # its scheduler.journal chaos rules govern them, and
                # ONLY its (the submit-side record rides a minimal
                # domain — no recorder/metrics child exists yet)
                with run_domain.activate(run_domain.RunDomain(
                    plan_id=plan_id, chaos=fault_plan
                )):
                    self.journal.record_submitted(
                        plan_id, plan.query,
                        meta={
                            "deadline_s": deadline_s,
                            "report_dir": report_dir,
                            "recovered": _recovered,
                            "idempotency_key": idempotency_key,
                            "gateway": gateway,
                            "fleet": fleet,
                            "trace_id": trace_id,
                        },
                    )
            if key_claim is not None:
                # the write-ahead record carrying the key→plan binding
                # has landed (or the journal write degraded, and fleet
                # key dedup is best-effort anyway): peers re-seeding
                # the shared journal see the binding now — the
                # registration claim has done its job
                self.leases.release(key_claim.plan_id)
            if _recovered:
                # journal recovery must NEVER shed: these plans were
                # admitted once by the dead process, and a shed here
                # would write a terminal record for work that never
                # ran — permanent loss. Same rule as the batcher's
                # retry re-admission (the bound is the journal's own
                # size).
                self.queue.readmit(ticket)
                admitted = True
            else:
                # the offer and its evidence read are one atomic
                # decision under the lock: two threads shedding
                # concurrently must each journal THEIR OWN evidence,
                # not the other's
                admitted = self.queue.offer(ticket, block_s=0.0)
                evidence = (
                    "" if admitted else self.queue.last_shed_evidence
                )
            if admitted:
                # registered under the same lock as the idempotency
                # check above — a racing same-key submit sees either
                # nothing (and runs) or this ticket (and rejoins)
                self._tickets[plan_id] = ticket
                if idempotency_key:
                    self._idempotency[idempotency_key] = plan_id
        if not admitted:
            # same invariant as every other journal write: the shed
            # record (and its counter) belongs to THIS plan's fault
            # domain — a submit() called from inside another tenant's
            # domain must not charge the shed to that tenant's chaos
            # rules or metrics child
            with run_domain.activate(run_domain.RunDomain(
                plan_id=plan_id, chaos=fault_plan
            )):
                obs.metrics.count("scheduler.shed")
                if self.journal is not None:
                    self.journal.record_failed(
                        plan_id, plan.query,
                        error=f"shed at admission: {evidence}",
                        attempts=0,
                    )
            self._release_lease(plan_id)
            raise PlanShedError(
                f"plan {plan_id} shed at admission: {evidence}",
                plan_id=plan_id,
            )
        # same domain rule as the shed branch: submission accounting
        # belongs to the NEW plan, not to whatever tenant's domain is
        # ambient on the submitting thread
        with run_domain.activate(run_domain.RunDomain(
            plan_id=plan_id, chaos=fault_plan
        )):
            obs.metrics.count("scheduler.submitted")
            events.event("scheduler.submitted", plan=plan_id)
        return PlanHandle(ticket)

    def run(
        self, queries, deadline_s: Optional[float] = None,
        timeout_s: Optional[float] = None,
    ) -> List[PlanResult]:
        """Submit every query and block for all results, in order —
        the batch-driver convenience over the async surface.

        A shed mid-batch is BACKPRESSURE here, not loss: raising out
        of the submit loop would abandon the already-admitted handles
        (their plans keep running, journaling results the caller can
        no longer reach). Instead the batch waits for one of its own
        in-flight plans — whose worker pop freed queue space — and
        retries UNDER THE SHED PLAN'S ID, so the journal converges to
        one record per logical plan (the transient shed's 'failed'
        record is overwritten by the retry's write-ahead record)
        instead of accumulating a terminal failure per backpressure
        bounce. Only with none of its own plans in flight is a shed
        genuine (other tenants own the depth) and re-raised — its
        failed record then stands as the evidence."""
        handles: List[PlanHandle] = []
        for q in queries:
            retry_id: Optional[str] = None
            while True:
                try:
                    handles.append(self.submit(
                        q, deadline_s=deadline_s, plan_id=retry_id,
                    ))
                    break
                except PlanShedError as shed:
                    retry_id = shed.plan_id or retry_id
                    in_flight = next(
                        (h for h in handles if not h.done), None
                    )
                    if in_flight is None:
                        raise
                    try:
                        in_flight.result(timeout=timeout_s)
                    except Exception:
                        # resolved-with-error still freed its slot
                        # (the error resurfaces from the collection
                        # below — and a plan's own
                        # DeadlineExceededError is a resolution, not
                        # our wait expiring). An UNresolved handle
                        # means the wait itself timed out: re-raise
                        # rather than busy-loop on a queue another
                        # tenant is holding full.
                        if not in_flight.done:
                            raise
        return [h.result(timeout=timeout_s) for h in handles]

    # -- the gateway's status/cancel surface ------------------------------

    def status(self, plan_id: str) -> Optional[Dict[str, Any]]:
        """One plan's client-visible status — the live ticket's state
        machine (queued | running | completed | failed | cancelled)
        with its attempt history, falling back to the journal record
        (completed | failed | submitted) for plans this executor never
        admitted; None for an unknown id."""
        ticket = self._tickets.get(plan_id)
        if ticket is not None:
            return {
                "plan_id": plan_id,
                "state": ticket.state,
                "attempts": ticket.attempts,
                "history": list(ticket.history),
                "query": ticket.plan.query,
                "recovered": ticket.recovered,
                "report_dir": ticket.report_dir,
                "fleet": getattr(ticket, "fleet", None),
            }
        if self.journal is not None:
            entry = self.journal.entry(plan_id)
            if entry is not None:
                meta = entry.get("meta") or {}
                return {
                    "plan_id": plan_id,
                    # a cancel journals as a failure record (with the
                    # evidence) but the client-visible state machine
                    # keeps the distinction
                    "state": (
                        "cancelled" if meta.get("cancelled")
                        else entry.get("state")
                    ),
                    "attempts": int(entry.get("attempts", 0) or 0),
                    "history": [],
                    "query": entry.get("query", ""),
                    "error": entry.get("error"),
                    "statistics_sha256": entry.get("statistics_sha256"),
                    "report_dir": meta.get("report_dir"),
                    "fleet": meta.get("fleet"),
                }
        return None

    def cancel(self, plan_id: str) -> bool:
        """Cancel-if-queued (the gateway's DELETE): withdraw a plan
        the workers have not popped yet. True = cancelled (its handle
        fails with :class:`PlanCancelledError`, a terminal journal
        record carries the evidence); False = already running or
        terminal — an executing plan is not torn down mid-flight (its
        fault domain owns cleanup), the client awaits it instead.

        A cancel releases the plan's idempotency key: cancelling is a
        client decision, not a deterministic outcome, so a re-submit
        with the same key runs fresh."""
        ticket = self._tickets.get(plan_id)
        if ticket is None or not isinstance(ticket, _PlanTicket):
            return False
        if not self.queue.remove(ticket):
            # the pop path shares the queue lock: losing this race
            # means a worker owns the plan now
            return False
        ticket.state = "cancelled"
        with self._submit_lock:
            key = ticket.idempotency_key
            if key and self._idempotency.get(key) == plan_id:
                del self._idempotency[key]
        journaled = False
        with run_domain.activate(run_domain.RunDomain(
            plan_id=plan_id, chaos=ticket.fault_plan
        )):
            obs.metrics.count("scheduler.cancelled")
            events.event("scheduler.cancelled", plan=plan_id)
            if self.journal is not None:
                # no idempotency key in the meta — see above
                journaled = self.journal.record_failed(
                    plan_id, ticket.plan.query,
                    "cancelled by client while queued; never executed",
                    attempts=0,
                    meta={"cancelled": True, "gateway": ticket.gateway},
                )
        self._release_lease(plan_id)
        ticket.future.fail(PlanCancelledError(
            f"plan {plan_id} cancelled while queued; never executed"
        ))
        if journaled:
            # terminal-and-journaled, like every other eviction; the
            # journal fallback reports state 'cancelled' via the
            # record's meta
            self._tickets.pop(plan_id, None)
        return True

    def handle(self, plan_id: str) -> Optional[PlanHandle]:
        """The handle for a live (this-process) plan id, or None."""
        ticket = self._tickets.get(plan_id)
        return None if ticket is None else PlanHandle(ticket)

    def live_ids(self) -> List[str]:
        """Plan ids with a live ticket (queued/running, plus any
        terminal plan whose journal write degraded) — the set whose
        state the journal does not yet know."""
        return list(self._tickets)

    # -- crash-only recovery ---------------------------------------------

    def recover(self) -> Dict[str, Any]:
        """Resume a journaled workload after a crash: every unfinished
        record is re-submitted under its ORIGINAL plan id (handles
        returned for the caller to await), every terminal record is
        returned untouched — completed plans are exactly-once by
        construction. Requires a ``journal_dir``."""
        if self.journal is None:
            raise ValueError(
                "recover() needs a journal_dir — an unjournaled "
                "executor has nothing to recover from"
            )
        resumed: List[PlanHandle] = []
        completed: List[Dict[str, Any]] = []
        failed: List[Dict[str, Any]] = []
        for entry in self.journal.entries():
            state = entry.get("state")
            if state == journal_mod.COMPLETED:
                completed.append(entry)
            elif state == journal_mod.FAILED:
                failed.append(entry)
            elif state == journal_mod.SUBMITTED:
                meta = entry.get("meta") or {}
                try:
                    resumed.append(self.submit(
                        entry["query"],
                        deadline_s=meta.get("deadline_s"),
                        plan_id=entry["plan_id"],
                        _recovered=True,
                        idempotency_key=meta.get("idempotency_key"),
                        gateway=meta.get("gateway"),
                        trace_id=meta.get("trace_id"),
                    ))
                except PlanOwnedElsewhereError:
                    # a fleet peer lease-holds this record: recovery
                    # on this replica must leave it to them (the scan
                    # loop re-checks if their lease ever goes stale)
                    continue
        # fresh ids already start past the dead process's (the
        # constructor seeds the counter from the journal)
        obs.metrics.count("scheduler.recovered_plans", len(resumed))
        logger.info(
            "journal recovery: %d completed (kept), %d failed (kept), "
            "%d unfinished re-submitted",
            len(completed), len(failed), len(resumed),
        )
        return {
            "resumed": resumed,
            "completed": completed,
            "failed": failed,
        }

    # -- fleet takeover (gateway/fleet.py's scan loop) --------------------

    def claim_and_run(
        self,
        entry: Dict[str, Any],
        fleet: Optional[Dict[str, Any]] = None,
        takeover: bool = True,
    ) -> Optional[PlanHandle]:
        """Lease-claim one unfinished journal record and re-admit it
        under its ORIGINAL plan id — the fleet's takeover entry point.

        Returns the handle when this executor won the claim; None when
        it lost (a live peer holds the lease, the record is already
        live here, or claiming is unavailable this round — the scan
        loop simply retries later). Everything downstream composes
        unchanged: the journaled query re-parses, idempotency keys and
        report dirs ride the record's meta, ``_recovered=True``
        re-admission never sheds, and the completion record lands
        under the original id — so the taken-over plan's statistics
        are byte-identical to an uninterrupted run (the PR 10
        crash-only pin, at fleet scope)."""
        if self.journal is None or self.leases is None:
            raise ValueError(
                "claim_and_run() needs a journal_dir and an attached "
                "lease directory (gateway/fleet.py)"
            )
        plan_id = entry["plan_id"]
        if plan_id in self._tickets:
            return None
        already_held = self.leases.held(plan_id) is not None
        claim = self.leases.try_claim(plan_id, takeover=takeover)
        if not isinstance(claim, lease_mod.PlanLease):
            return None
        # re-read UNDER the lease: between the caller's unfinished()
        # scan and this claim, the holder may have finished the plan
        # and released — re-admitting now would overwrite a terminal
        # record with 'submitted' and re-run completed work. While we
        # hold the lease no peer can write this plan's records, so
        # this check is race-free.
        current = self.journal.entry(plan_id)
        if current is None or current.get("state") != journal_mod.SUBMITTED:
            if not already_held:
                self._release_lease(plan_id)
            return None
        meta = entry.get("meta") or {}
        if fleet is None:
            fleet = {
                "replica": self.leases.holder,
                "takeover": takeover,
            }
        try:
            return self.submit(
                entry["query"],
                deadline_s=meta.get("deadline_s"),
                plan_id=plan_id,
                _recovered=True,
                idempotency_key=meta.get("idempotency_key"),
                gateway=meta.get("gateway"),
                fleet=fleet,
                # the journaled trace id: the takeover segment joins
                # the SAME distributed trace the dead holder started
                trace_id=meta.get("trace_id"),
            )
        except Exception:
            # a claim this call took must not outlive its failure —
            # a lease held for a plan nobody is running would stall
            # every peer until the stale-break timeout
            if not already_held:
                self._release_lease(plan_id)
            raise

    def _release_lease(self, plan_id: str) -> None:
        if self.leases is not None:
            self.leases.release(plan_id)

    def drain_queued(self) -> List[str]:
        """Withdraw every still-queued plan WITHOUT a terminal record
        — the hand-back half of a fleet replica's graceful SIGTERM
        drain. Each withdrawn ticket's journal record stays
        'submitted', its lease is released so a peer claims it
        IMMEDIATELY (no stale-break timeout to wait out), and its
        local handle fails with :class:`ServiceClosedError`. Running
        plans are untouched — the drain finishes them. Returns the
        released plan ids."""
        # placement-waiting tickets cycle between the queue and a
        # worker's hands; the flag catches the in-hand ones the
        # queue.remove pass below cannot see
        self._drain_requested = True
        with self._submit_lock:
            queued = [
                t for t in self._tickets.values()
                if isinstance(t, _PlanTicket) and t.state == "queued"
            ]
        released: List[str] = []
        for ticket in queued:
            if not self.queue.remove(ticket):
                # a worker popped it while we looked: it is running
                # now, and the drain's wait loop will see it finish
                continue
            ticket.state = "failed"
            with self._submit_lock:
                self._tickets.pop(ticket.plan_id, None)
            self._release_lease(ticket.plan_id)
            obs.metrics.count("scheduler.drain_released")
            events.event(
                "scheduler.drain_released", plan=ticket.plan_id
            )
            ticket.future.fail(ServiceClosedError(
                f"plan {ticket.plan_id} released for peer takeover "
                f"during drain; its journal record stays 'submitted'"
            ))
            released.append(ticket.plan_id)
        return released

    # -- the worker loop -------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.collect(
                max_batch=1, wait_s=0.05, coalesce_s=0.0
            )
            if not batch:
                continue
            ticket = batch[0]
            grant = None
            if (
                self.placement is not None
                and isinstance(ticket, _PlanTicket)
                and ticket.state == "queued"
            ):
                placed = self._try_place(ticket)
                if placed is _PLACEMENT_WAIT:
                    if self._drain_requested:
                        # popped tickets are invisible to
                        # drain_queued's queue.remove pass — hand
                        # this one back here, identically
                        self._drain_waiting_ticket(ticket)
                        continue
                    if (
                        ticket.deadline is not None
                        and ticket.deadline.expired
                    ):
                        # die on time, with the deadline's own
                        # evidence path — never wait past the budget
                        self._execute_ticket(ticket)
                        continue
                    # back to the TAIL: smaller plans backfill past
                    # this footprint while it waits (journal state
                    # unchanged — the record stays 'submitted' and
                    # the plan lease stays held)
                    self.queue.readmit(ticket)
                    self._stop.wait(self.placement_backoff_s)
                    continue
                grant = placed
            try:
                self._execute_ticket(ticket, grant=grant)
            finally:
                if grant is not None:
                    grant.release()

    def _try_place(self, ticket: "_PlanTicket"):
        """One placement attempt: a DeviceGrant (run on these leased
        ordinals), None (run unplaced — exempt/unsatisfiable/pool
        degraded: the builder's availability ladder governs), or
        :data:`_PLACEMENT_WAIT` (requeue; backfill may pass)."""
        from . import placement as placement_mod

        try:
            if ticket.footprint is None:
                ticket.footprint = ticket.plan.device_footprint()
            placed = self.placement.admit(
                ticket.plan_id, ticket.footprint
            )
        except Exception as e:
            # placement must never kill a plan it exists to schedule:
            # degrade to unplaced execution, with the evidence
            obs.metrics.count("placement.errors")
            logger.warning(
                "placement degraded for %s (%s: %s); running unplaced",
                ticket.plan_id, type(e).__name__, e,
            )
            return None
        if placed is placement_mod.UNPLACED:
            return None
        if placed is None:
            return _PLACEMENT_WAIT
        return placed

    def _drain_waiting_ticket(self, ticket: "_PlanTicket") -> None:
        """drain_queued's hand-back, for a placement-waiting ticket a
        worker had already popped: journal record stays 'submitted',
        the plan lease is released for an immediate peer claim, the
        local handle fails."""
        ticket.state = "failed"
        with self._submit_lock:
            self._tickets.pop(ticket.plan_id, None)
        if self.placement is not None:
            self.placement.clear_waiting(ticket.plan_id)
        self._release_lease(ticket.plan_id)
        obs.metrics.count("scheduler.drain_released")
        events.event("scheduler.drain_released", plan=ticket.plan_id)
        ticket.future.fail(ServiceClosedError(
            f"plan {ticket.plan_id} released for peer takeover "
            f"during drain; its journal record stays 'submitted'"
        ))

    def _execute_ticket(self, ticket: _PlanTicket, grant=None) -> None:
        from ..pipeline.builder import PipelineBuilder

        ticket.state = "running"
        if grant is not None:
            # the granted ordinals ride the fleet attribution into
            # run_report.json and the journal meta: an artifact names
            # WHICH leased devices built its mesh
            ticket.fleet = dict(ticket.fleet or {})
            ticket.fleet["devices"] = list(grant.ordinals)
        while True:
            if ticket.deadline is not None and ticket.deadline.expired:
                # attempts == 0: the budget died in the admission
                # queue. attempts > 0: it died during the retry
                # backoff sleep (can_cover guarded the sleep itself,
                # not the attempt after it) — either way, building a
                # fresh PipelineBuilder and telemetry dir for an
                # attempt that fails at its first deadline checkpoint
                # is pure waste: fail fast here.
                waited = time.monotonic() - ticket.submitted_at
                obs.metrics.count("scheduler.deadline_exceeded")
                if ticket.attempts == 0:
                    msg = (
                        f"deadline ({ticket.deadline.budget_s:.3f}s "
                        f"budget) exceeded after {waited:.3f}s in the "
                        f"admission queue; plan was never executed"
                    )
                else:
                    msg = (
                        f"deadline ({ticket.deadline.budget_s:.3f}s "
                        f"budget) expired during retry backoff after "
                        f"{ticket.attempts} failed; attempts: "
                        f"{ticket.history}"
                    )
                self._record_failed(ticket, msg)
                ticket.future.fail(deadline_mod.DeadlineExceededError(
                    f"plan {ticket.plan_id}: {msg}"
                ))
                return
            builder = PipelineBuilder(
                ticket.plan.query, filesystem=self._fs
            )
            # fleet attribution rides as a kwarg only when set: solo
            # executors keep the pre-fleet call signature, which test
            # doubles for execute_plan rely on
            extra = {"fleet": ticket.fleet} if ticket.fleet else {}
            if ticket.trace_id:
                extra["trace_id"] = ticket.trace_id
            if grant is not None:
                extra["placement"] = grant.ordinals
            # a fleet-won `processes=N` plan (no explicit process_id:
            # the client asked for a pod, not a pod MEMBER) routes
            # through the pod-assist coordinator when one is attached;
            # None from it means "could not assemble a pod" and the
            # plan falls through to the inline ladder, which is
            # exactly the degrade-don't-wedge path
            assist = None
            if (
                self.pod_assist is not None
                and ticket.plan.pod is not None
                and (ticket.plan.pod.processes or 0) > 1
                and ticket.plan.pod.process_id is None
            ):
                assist = self.pod_assist
            try:
                with deadline_mod.deadline_scope(ticket.deadline):
                    statistics = None
                    if assist is not None:
                        statistics = assist.run(ticket)
                    if statistics is None:
                        statistics = runtime.execute_plan(
                            ticket.plan,
                            builder,
                            plan_id=ticket.plan_id,
                            fault_plan=ticket.fault_plan,
                            default_report_dir=ticket.report_dir,
                            gateway=ticket.gateway,
                            **extra,
                        )
            except Exception as e:
                ticket.attempts += 1
                ticket.history.append(
                    f"attempt {ticket.attempts}: "
                    f"{type(e).__name__}: {e}"
                )
                obs.metrics.count("scheduler.attempt_failures")
                events.event(
                    "scheduler.attempt_failed",
                    plan=ticket.plan_id, attempt=ticket.attempts,
                    error=f"{type(e).__name__}: {e}",
                )
                if isinstance(e, ValueError):
                    # caller bugs (conflicting knobs, bad grammar the
                    # IR could not see statically) fail identically on
                    # every attempt — surface NOW with the real error
                    self._record_failed(ticket, ticket.history[-1])
                    ticket.future.fail(e)
                    return
                if ticket.attempts >= self.max_attempts:
                    self._record_failed(
                        ticket,
                        f"retry budget ({self.max_attempts}) "
                        f"exhausted; attempts: {ticket.history}",
                    )
                    ticket.future.fail(PlanFailedError(
                        f"plan {ticket.plan_id} failed after "
                        f"{ticket.attempts} attempts (budget "
                        f"{self.max_attempts}); attempts: "
                        f"{ticket.history}"
                    ))
                    return
                if (
                    ticket.deadline is not None
                    and not ticket.deadline.can_cover(
                        self.retry_backoff_s
                    )
                ):
                    obs.metrics.count("scheduler.deadline_exceeded")
                    self._record_failed(
                        ticket,
                        f"deadline cannot cover another attempt "
                        f"after {ticket.attempts} failed; attempts: "
                        f"{ticket.history}",
                    )
                    ticket.future.fail(
                        deadline_mod.DeadlineExceededError(
                            f"plan {ticket.plan_id}: deadline "
                            f"({ticket.deadline.budget_s:.3f}s "
                            f"budget) cannot cover another attempt "
                            f"after {ticket.attempts} failed; "
                            f"attempts: {ticket.history}"
                        )
                    )
                    return
                obs.metrics.count("scheduler.retries")
                time.sleep(self.retry_backoff_s)
                continue
            ticket.attempts += 1
            journaled = False
            if self.journal is not None:
                # same fault-domain rule as the submit-side record
                with run_domain.activate(run_domain.RunDomain(
                    plan_id=ticket.plan_id, chaos=ticket.fault_plan
                )):
                    journaled = self.journal.record_completed(
                        ticket.plan_id, ticket.plan.query,
                        str(statistics),
                        attempts=ticket.attempts,
                        meta={
                            "recovered": ticket.recovered,
                            "idempotency_key": ticket.idempotency_key,
                            "gateway": ticket.gateway,
                            "fleet": ticket.fleet,
                            "report_dir": ticket.report_dir,
                            # survives into the terminal record so
                            # plan_admin trace resolves finished plans
                            "trace_id": ticket.trace_id,
                        },
                    )
            # terminal record landed (or degraded): either way this
            # replica is done executing — the lease has served its
            # purpose and holding it would only delay a peer's view
            self._release_lease(ticket.plan_id)
            obs.metrics.count("scheduler.completed")
            events.event(
                "scheduler.completed", plan=ticket.plan_id,
                attempts=ticket.attempts,
            )
            ticket.state = "completed"
            ticket.future.resolve(PlanResult(
                plan_id=ticket.plan_id,
                statistics=statistics,
                builder=builder,
                attempts=ticket.attempts,
                report_dir=ticket.report_dir,
                recovered=ticket.recovered,
            ))
            if journaled:
                # the durable record has LANDED: evict the live
                # ticket so its result (which pins the whole
                # PipelineBuilder) can be collected once the caller
                # drops the handle — status() and keyed re-submits
                # fall back to the journal (a degraded journal write
                # keeps the ticket instead: the live copy is then the
                # only record)
                self._tickets.pop(ticket.plan_id, None)
            return

    def _record_failed(self, ticket: _PlanTicket, error: str) -> None:
        ticket.state = "failed"
        self._release_lease(ticket.plan_id)
        obs.metrics.count("scheduler.failed")
        if self.journal is not None:
            with run_domain.activate(run_domain.RunDomain(
                plan_id=ticket.plan_id, chaos=ticket.fault_plan
            )):
                journaled = self.journal.record_failed(
                    ticket.plan_id, ticket.plan.query, error,
                    attempts=ticket.attempts,
                    meta={
                        "idempotency_key": ticket.idempotency_key,
                        "gateway": ticket.gateway,
                        "fleet": ticket.fleet,
                        "report_dir": ticket.report_dir,
                        "trace_id": ticket.trace_id,
                    },
                )
            if journaled:
                # same bound as the completed path: the journal now
                # holds the terminal record (error + attempts), so
                # the live ticket — its ExecutionPlan, fault plan,
                # deadline — need not outlive it
                self._tickets.pop(ticket.plan_id, None)
