"""``train_clf=`` / ``load_clf=`` plugin registry.

Parity with the reference's classifier switch
(PipelineBuilder.java:156-169): svm, logreg, dt, rf, nn. Unknown names
raise the reference's error message.
"""

from __future__ import annotations

from typing import Callable, Dict

from . import base

_REGISTRY: Dict[str, Callable[[], base.Classifier]] = {}


def register(name: str, factory: Callable[[], base.Classifier]) -> None:
    _REGISTRY[name] = factory


def create(name: str) -> base.Classifier:
    if name not in _REGISTRY:
        raise ValueError("Unsupported classifier argument")
    return _REGISTRY[name]()


def names() -> list:
    return sorted(_REGISTRY)


def _register_builtins() -> None:
    from . import linear

    register("logreg", linear.LogisticRegressionClassifier)
    register("svm", linear.SVMClassifier)
    from . import trees

    register("dt", trees.DecisionTreeClassifier)
    register("rf", trees.RandomForestClassifier)
    # -tpu variants grow the whole forest in one XLA program
    # (models/trees_device.py), mirroring the fe= dwt-8/dwt-8-tpu
    # naming convention
    register("dt-tpu", lambda: trees.DecisionTreeClassifier(backend="device"))
    register("rf-tpu", lambda: trees.RandomForestClassifier(backend="device"))
    # restored from the reference's commented-out test surface
    # (ClassifierTest.java:213) — MLlib GradientBoostedTrees analogue
    register("gbt", trees.GradientBoostedTreesClassifier)
    register(
        "gbt-tpu",
        lambda: trees.GradientBoostedTreesClassifier(backend="device"),
    )
    from . import nn

    register("nn", nn.NeuralNetworkClassifier)


_register_builtins()
