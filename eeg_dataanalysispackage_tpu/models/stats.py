"""Classification statistics (reference: Utils/ClassificationStatistics.java).

Confusion-matrix accumulator with the same fields, accuracy/MSE math,
rounding rule (Math.round: half-up), and report text as the reference
(ClassificationStatistics.java:50-96). A vectorized ``from_arrays``
builds it from whole prediction batches (the XLA-friendly path:
confusion matrix = 4-way bincount).
"""

from __future__ import annotations

import math
import threading as _threading
from collections import deque as _deque
from typing import Optional

import numpy as np


def mark_extended(statistics, cost_fp: float = 1.0,
                  cost_fn: float = 1.0) -> None:
    """Opt ``statistics`` into the extended imbalanced-class report
    (precision/recall/F1/balanced accuracy/expected cost) with the
    run's misclassification costs. Recurses through the dict-shaped
    containers (population / fan-out), so every member's ``__str__``
    — and therefore the ``result_path`` text — carries the block."""
    if isinstance(statistics, dict):
        for member in statistics.values():
            mark_extended(member, cost_fp, cost_fn)
        return
    statistics.extended_report = True
    statistics.cost_fp = float(cost_fp)
    statistics.cost_fn = float(cost_fn)


def _java_round(x: float) -> int:
    # Java Math.round = floor(x + 0.5); Python round() half-to-even differs.
    return math.floor(x + 0.5)


class ClassificationStatistics:
    def __init__(self, tp: int = 0, tn: int = 0, fp: int = 0, fn: int = 0):
        self.true_positives = tp
        self.true_negatives = tn
        self.false_positives = fp
        self.false_negatives = fn
        self.mse = 0.0
        self.class1_sum = 0.0  # sum of real outputs on expected-0 patterns
        self.class2_sum = 0.0  # sum of real outputs on expected-1 patterns
        # the seizure workload's reporting surface (imbalanced-class
        # metrics + an expected-cost summary). OFF by default:
        # ``__str__`` must stay BYTE-IDENTICAL for every P300 run —
        # the extended block renders only when a workload opts in
        # (pipeline/builder.py task=seizure; pinned in
        # tests/test_stats_metrics.py).
        self.extended_report = False
        self.cost_fp = 1.0  # cost of one false positive
        self.cost_fn = 1.0  # cost of one false negative

    def add(self, real_output: float, expected_output: float) -> None:
        """Incremental accumulation (ClassificationStatistics.java:68-83)."""
        self.mse += (expected_output - real_output) ** 2
        e = _java_round(expected_output)
        r = _java_round(real_output)
        if e == 0 and e == r:
            self.true_negatives += 1
            self.class1_sum += real_output
        elif e == 0 and e != r:
            self.false_positives += 1
            self.class1_sum += real_output
        elif e == 1 and e == r:
            self.true_positives += 1
            self.class2_sum += real_output
        elif e == 1 and e != r:
            self.false_negatives += 1
            self.class2_sum += real_output

    @classmethod
    def from_arrays(
        cls,
        real_outputs: np.ndarray,
        expected_outputs: np.ndarray,
        confusion_only: bool = False,
    ) -> "ClassificationStatistics":
        """Batched construction.

        ``confusion_only=True`` reproduces the reference's MLlib path,
        which builds statistics from the confusion matrix alone and
        leaves MSE/class sums at 0
        (LogisticRegressionClassifier.java:133-138); the incremental
        path (NN — NeuralNetworkClassifier.java:164) fills them.

        Bug-as-behavior: the reference indexes Spark's *column-major*
        ``confusionMatrix().toArray()`` — actually [tn, fn, fp, tp] —
        as ``[tn, fp, fn, tp]`` (LogisticRegressionClassifier.java:
        133-137), so every MLlib-path report prints false positives
        and false negatives swapped. ``confusion_only=True`` preserves
        that swap for report parity; accuracy is unaffected. The
        incremental path labels them correctly, as the reference NN
        does.
        """
        real = np.asarray(real_outputs, dtype=np.float64)
        exp = np.asarray(expected_outputs, dtype=np.float64)
        e = np.floor(exp + 0.5).astype(np.int64)
        r = np.floor(real + 0.5).astype(np.int64)
        true_fp = int(((e == 0) & (r != 0)).sum())
        true_fn = int(((e == 1) & (r != 1)).sum())
        if confusion_only:
            true_fp, true_fn = true_fn, true_fp
        stats = cls(
            tp=int(((e == 1) & (r == 1)).sum()),
            tn=int(((e == 0) & (r == 0)).sum()),
            fp=true_fp,
            fn=true_fn,
        )
        if not confusion_only:
            stats.mse = float(((exp - real) ** 2).sum())
            stats.class1_sum = float(real[e == 0].sum())
            stats.class2_sum = float(real[e == 1].sum())
        return stats

    @property
    def num_patterns(self) -> int:
        return (
            self.true_positives
            + self.true_negatives
            + self.false_positives
            + self.false_negatives
        )

    def calc_accuracy(self) -> float:
        # Java's int/int-widened-to-double 0/0 yields NaN, not a crash.
        if self.num_patterns == 0:
            return math.nan
        return (self.true_positives + self.true_negatives) / self.num_patterns

    # -- imbalanced-class metrics (the seizure workload) ----------------
    # All 0/0 cases return NaN, the accuracy convention above: a run
    # with no positive patterns has no defined recall, and pretending
    # 0.0 or 1.0 would mislead the cost sweep that reads these.

    def precision(self) -> float:
        denom = self.true_positives + self.false_positives
        return math.nan if denom == 0 else self.true_positives / denom

    def recall(self) -> float:
        denom = self.true_positives + self.false_negatives
        return math.nan if denom == 0 else self.true_positives / denom

    def specificity(self) -> float:
        denom = self.true_negatives + self.false_positives
        return math.nan if denom == 0 else self.true_negatives / denom

    def f1(self) -> float:
        p, r = self.precision(), self.recall()
        if math.isnan(p) or math.isnan(r) or (p + r) == 0:
            return math.nan
        return 2.0 * p * r / (p + r)

    def balanced_accuracy(self) -> float:
        r, s = self.recall(), self.specificity()
        if math.isnan(r) or math.isnan(s):
            return math.nan
        return (r + s) / 2.0

    def expected_cost(self, cost_fp: Optional[float] = None,
                      cost_fn: Optional[float] = None) -> float:
        """Mean per-pattern misclassification cost: each false
        positive bills ``cost_fp``, each false negative ``cost_fn``
        (defaults: the costs the run was configured with). THE
        seizure-detection headline — accuracy rewards predicting
        'no seizure' always; this is what the cost-sensitive knobs
        are tuned against."""
        cfp = self.cost_fp if cost_fp is None else float(cost_fp)
        cfn = self.cost_fn if cost_fn is None else float(cost_fn)
        if self.num_patterns == 0:
            return math.nan
        return (
            cfp * self.false_positives + cfn * self.false_negatives
        ) / self.num_patterns

    def extended_summary(self) -> dict:
        """The imbalanced-class metric block (run_report.json's
        ``classification`` field for extended-report runs)."""
        return {
            "accuracy": self.calc_accuracy(),
            "precision": self.precision(),
            "recall": self.recall(),
            "specificity": self.specificity(),
            "f1": self.f1(),
            "balanced_accuracy": self.balanced_accuracy(),
            "cost_fp": self.cost_fp,
            "cost_fn": self.cost_fn,
            "expected_cost": self.expected_cost(),
        }

    def __str__(self) -> str:
        # Field order and wording match ClassificationStatistics.java:86-96.
        mse = math.nan if self.num_patterns == 0 else self.mse / self.num_patterns
        base = (
            f"Number of patterns: {self.num_patterns}\n"
            f"True positives: {self.true_positives}\n"
            f"True negatives: {self.true_negatives}\n"
            f"False positives: {self.false_positives}\n"
            f"False negatives: {self.false_negatives}\n"
            f"Accuracy: {self.calc_accuracy() * 100}%\n"
            f"MSE: {mse}\n"
            f"Non-targets: {self.class1_sum}\n"
            f"Targets: {self.class2_sum}\n"
        )
        if not self.extended_report:
            # the P300 surface: byte-identical to the reference format
            return base
        return base + (
            f"Precision: {self.precision()}\n"
            f"Recall: {self.recall()}\n"
            f"F1: {self.f1()}\n"
            f"Balanced accuracy: {self.balanced_accuracy()}\n"
            f"Expected cost (fp={self.cost_fp}, fn={self.cost_fn}): "
            f"{self.expected_cost()}\n"
        )


class WindowedStatistics:
    """Bounded sliding window of served (prediction, label) outcomes.

    The serving lifecycle's gate/drift currency (serve/lifecycle.py):
    expected cost and recall over the most recent ``window`` labeled
    outcomes, so a drifting electrode montage shows up in the window
    while a week-old baseline cannot dilute it. Purely host-side and
    deterministic — the same outcome stream produces the same windowed
    numbers in any process, which is what makes the promotion gate and
    the drift signal replayable evidence rather than a mood. Reads and
    writes are lock-guarded: the serving adapter thread appends while
    monitors snapshot a live service's stats block.
    """

    def __init__(self, window: int, cost_fp: float = 1.0,
                 cost_fn: float = 1.0):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.cost_fp = float(cost_fp)
        self.cost_fn = float(cost_fn)
        #: (prediction, label) pairs, oldest first, len <= window
        self._outcomes: "deque" = _deque(maxlen=self.window)
        self._lock = _threading.Lock()
        #: total outcomes ever added (the window position — drift
        #: firing is rate-limited against this, not wall time)
        self.seen = 0

    def add(self, prediction: float, label: float) -> None:
        with self._lock:
            self._outcomes.append(
                (_java_round(float(prediction)),
                 _java_round(float(label)))
            )
            self.seen += 1

    def reset(self) -> None:
        """Forget the window (a model swap starts a fresh record —
        the new model must earn its own numbers)."""
        with self._lock:
            self._outcomes.clear()

    @property
    def n(self) -> int:
        with self._lock:
            return len(self._outcomes)

    @property
    def full(self) -> bool:
        return self.n >= self.window

    def counts(self) -> tuple:
        """(tp, tn, fp, fn) over the window."""
        with self._lock:
            outcomes = list(self._outcomes)
        tp = tn = fp = fn = 0
        for r, e in outcomes:
            if e == 1:
                if r == 1:
                    tp += 1
                else:
                    fn += 1
            else:
                if r == 0:
                    tn += 1
                else:
                    fp += 1
        return tp, tn, fp, fn

    def expected_cost(self) -> float:
        tp, tn, fp, fn = self.counts()
        total = tp + tn + fp + fn
        if total == 0:
            return math.nan
        return (self.cost_fp * fp + self.cost_fn * fn) / total

    def recall(self) -> float:
        tp, _tn, _fp, fn = self.counts()
        denom = tp + fn
        return math.nan if denom == 0 else tp / denom

    def summary(self) -> dict:
        tp, tn, fp, fn = self.counts()
        cost = self.expected_cost()
        recall = self.recall()
        return {
            "window": self.window,
            "n": self.n,
            "seen": self.seen,
            "tp": tp, "tn": tn, "fp": fp, "fn": fn,
            "expected_cost": (
                None if math.isnan(cost) else round(cost, 6)
            ),
            "recall": None if math.isnan(recall) else round(recall, 6),
        }


class PopulationStatistics(dict):
    """Ordered ``{member label: ClassificationStatistics}`` from a
    population training run (models/population.py): the cartesian
    expansion of cross-validation folds x init seeds x a hyperparameter
    grid, trained as one stacked program (or its looped sequential
    twin — same members, same statistics).

    A plain dict like :class:`FanOutStatistics`, so callers index
    per-member statistics directly (``stats["f0.s42"]``); ``shape``
    records the population axes and ``mode`` whether the members
    trained vmapped or looped. ``summary()`` is the cross-member
    digest (best member, mean/std accuracy) the run report and the
    ``result_path`` text both embed.
    """

    def __init__(self, shape: dict | None = None, mode: str = "vmap"):
        super().__init__()
        #: {"folds": k, "cv_mode": ..., "seeds": m, "grid": {...}}
        self.shape = dict(shape or {})
        #: "vmap" | "looped" — how the members actually trained
        self.mode = mode

    def summary(self) -> dict:
        accs = {name: s.calc_accuracy() for name, s in self.items()}
        finite = {
            n: a for n, a in accs.items() if not math.isnan(a)
        }
        if not finite:
            return {"members": len(self), "best": None,
                    "best_accuracy": math.nan, "mean_accuracy": math.nan,
                    "std_accuracy": math.nan}
        # deterministic best: highest accuracy, first label on ties
        best = max(sorted(finite), key=lambda n: finite[n])
        values = np.array([finite[n] for n in sorted(finite)])
        return {
            "members": len(self),
            "best": best,
            "best_accuracy": float(finite[best]),
            "mean_accuracy": float(values.mean()),
            "std_accuracy": float(values.std()),
        }

    def calc_accuracy(self) -> float:
        """The population's headline accuracy: its best member's —
        what a hyperparameter sweep selects."""
        return self.summary()["best_accuracy"]

    def __str__(self) -> str:
        # NOTE: deliberately mode-free. The vmapped engine and its
        # looped twin must render byte-identical reports for the same
        # member set — that equality (result_path text, the bench
        # pair's report_sha256) IS the parity contract; the mode lives
        # in the run report's population block.
        s = self.summary()
        header = (
            f"population: {s['members']} members "
            f"(folds={self.shape.get('folds', 1)} "
            f"seeds={self.shape.get('seeds', 1)} "
            f"grid={self.shape.get('grid_points', 1)})\n"
            f"best member: {s['best']} "
            f"(accuracy {s['best_accuracy'] * 100}%)\n"
            f"mean accuracy: {s['mean_accuracy'] * 100}% "
            f"(std {s['std_accuracy'] * 100}%)\n"
        )
        members = "\n".join(
            f"member: {name}\n{stats}" for name, stats in self.items()
        )
        return header + "\n" + members


class FanOutStatistics(dict):
    """Ordered ``{classifier name: ClassificationStatistics}`` from a
    ``classifiers=`` fan-out run (pipeline/builder.py).

    A plain dict, so callers index per-classifier statistics directly
    (``stats["svm"].calc_accuracy()``); ``str()`` renders the
    concatenated per-classifier reports in request order — the form
    ``result_path`` persists. When the run carried population axes
    (``cv=``/``seeds=``/``sweep=``), SGD-family legs hold a
    :class:`PopulationStatistics` instead of a single
    ``ClassificationStatistics`` — ``str()`` composes either way.
    """

    def __str__(self) -> str:
        return "\n".join(
            f"classifier: {name}\n{stats}" for name, stats in self.items()
        )
