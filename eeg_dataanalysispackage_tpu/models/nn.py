"""Neural-network classifier: flax network + optax updaters.

TPU-native re-design of ``Classification/NeuralNetworkClassifier.java``
(DL4J 0.8 ``MultiLayerNetwork`` + ND4J C++ backend -> flax module +
optax optimizer + one jitted train step on XLA). The entire DL4J
config surface is preserved:

- required scalars: ``config_seed``, ``config_num_iterations``,
  ``config_learning_rate``, ``config_momentum``,
  ``config_weight_init``, ``config_updater``,
  ``config_optimization_algo`` (the reference has NO code-level
  defaults — missing keys throw, NeuralNetworkClassifier.java:102-110);
- layer count = #(config_layer* keys)/4; per layer i (1-based):
  ``config_layer{i}_layer_type`` (output|dense|auto_encoder|rbm|
  graves_lstm), ``_n_out``, ``_drop_out``, ``_activation_function``;
  output layers read the global ``config_loss_function``
  (NeuralNetworkClassifier.java:258-320);
- enum mappings with the reference's silent fallbacks
  (NeuralNetworkClassifier.java:201-255): weight_init xavier|zero|
  sigmoid|uniform|relu (default relu), updater sgd|adam|nesterovs|
  adagrad|rmsprop (default nesterovs), loss mse|xent|squared_loss|
  negativeloglikelihood (default mse), activation sigmoid|softmax|
  relu|tanh|identity|softplus|elu (default sigmoid);
  optimization_algo stochastic_gradient_descent|lbfgs|
  conjugate_gradient|line_gradient_descent are all FUNCTIONAL
  (optax L-BFGS / PR+ CG / backtracking line search; unknown values
  fall back to the sgd family silently, like DL4J);
- labels are one-hot pairs [target, 1-target]
  (NeuralNetworkClassifier.java:81-84) and the prediction is
  ``output[0]`` (:161);
- ``config_pretrain``/``config_backprop`` are required flags with
  DL4J's ``model.fit`` semantics (NeuralNetworkClassifier.java:126-137,
  145): pretrain=true runs **greedy layerwise pretraining** of the
  auto_encoder/rbm layers before (optional) backprop; backprop=false
  skips supervised training entirely. Pretraining here: auto_encoder
  layers train a tied-weight denoising autoencoder (corruption 0.3,
  DL4J 0.8's AutoEncoder default) on the layer's input activations by
  MSE reconstruction; rbm layers run CD-1 contrastive divergence
  (sigmoid hidden units, linear visible reconstruction — the
  Gaussian-visible convention for real-valued features). Both use the
  configured updater/learning-rate/iterations. Exact DL4J RNG
  trajectories are not reproduced (closed native backend);
- ``graves_lstm`` is a **real LSTM** (``linen.OptimizedLSTMCell``
  scanned over time via ``linen.RNN``), not a dense stand-in
  (NeuralNetworkClassifier.java:258-320 layer switch). The layer's
  configured activation function becomes the cell activation, as in
  DL4J. Flat ``(batch, features)`` inputs — the reference's only
  shipped shape — run the cell for a single step; ``(batch, time,
  features)`` sequences (net-new TPU capability) are scanned on
  device, recurrent layers emit full sequences, and the output layer
  reads the final timestep.

Training runs ``config_num_iterations`` full-batch optimizer steps
(DL4J ``.iterations(n)`` + ``model.fit(dataSet)``) inside a single
``lax.scan`` jit — the reference's per-iteration ND4J JNI round trips
collapse into one XLA program.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen

from . import base

_ACTIVATIONS = {
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "identity": lambda x: x,
    "softplus": jax.nn.softplus,
    "elu": jax.nn.elu,
}
_LAYER_TYPES = ("output", "dense", "auto_encoder", "rbm", "graves_lstm")
_PRETRAINABLE = ("auto_encoder", "rbm")
# DL4J 0.8 AutoEncoder default corruption level (denoising)
_AE_CORRUPTION = 0.3


def _activation(name: str):
    return _ACTIVATIONS.get(name, _ACTIVATIONS["sigmoid"])


def _weight_init(name: str):
    inits = {
        "xavier": linen.initializers.glorot_uniform(),
        "zero": linen.initializers.zeros_init(),
        "sigmoid": linen.initializers.glorot_uniform(),  # DL4J SIGMOID_UNIFORM
        "uniform": linen.initializers.uniform(scale=0.01),
        "relu": linen.initializers.he_normal(),
    }
    return inits.get(name, inits["relu"])


def _updater(name: str, lr: float, momentum: float):
    opts = {
        "sgd": lambda: optax.sgd(lr),
        "adam": lambda: optax.adam(lr),
        "nesterovs": lambda: optax.sgd(lr, momentum=momentum, nesterov=True),
        "adagrad": lambda: optax.adagrad(lr),
        "rmsprop": lambda: optax.rmsprop(lr),
    }
    return opts.get(name, opts["nesterovs"])()


def _conjugate_gradient(lr: float) -> optax.GradientTransformation:
    """Polak-Ribière+ nonlinear CG. DL4J pairs CG with a line search;
    here the configured learning rate fixes the step (documented
    functional equivalent, not a DL4J trajectory match)."""

    def tdot(a, b):
        leaves_a = jax.tree_util.tree_leaves(a)
        leaves_b = jax.tree_util.tree_leaves(b)
        return sum(jnp.vdot(x, y) for x, y in zip(leaves_a, leaves_b))

    def init_fn(params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return (z, z)  # (prev_grad, prev_dir)

    def update_fn(grads, state, params=None):
        del params
        prev_g, prev_d = state
        num = tdot(
            grads,
            jax.tree_util.tree_map(lambda g, p: g - p, grads, prev_g),
        )
        den = tdot(prev_g, prev_g)
        # first step (den == 0) and PR+ restart both give beta = 0,
        # i.e. plain steepest descent
        beta = jnp.where(
            den > 0.0, jnp.maximum(num / jnp.maximum(den, 1e-30), 0.0), 0.0
        )
        d = jax.tree_util.tree_map(
            lambda g, pd: -g + beta * pd, grads, prev_d
        )
        updates = jax.tree_util.tree_map(lambda x: lr * x, d)
        return updates, (grads, d)

    return optax.GradientTransformation(init_fn, update_fn)


def _optimizer(algo: str, updater_name: str, lr: float, momentum: float):
    """(transform, needs_value_fn) for ``config_optimization_algo``.

    DL4J's four algorithms (NeuralNetworkClassifier.java:246-255,
    silent fallback to STOCHASTIC_GRADIENT_DESCENT): sgd runs the
    configured updater; lbfgs and line_gradient_descent run optax's
    L-BFGS / steepest-descent-with-backtracking-line-search (their
    ``update`` needs value/grad/value_fn); conjugate_gradient runs
    Polak-Ribière+ CG.
    """
    if algo == "lbfgs":
        return optax.lbfgs(), True
    if algo == "line_gradient_descent":
        return (
            optax.chain(
                optax.sgd(learning_rate=1.0),
                optax.scale_by_backtracking_linesearch(
                    max_backtracking_steps=15
                ),
            ),
            True,
        )
    if algo == "conjugate_gradient":
        return _conjugate_gradient(lr), False
    return _updater(updater_name, lr, momentum), False


class _Net(linen.Module):
    """The configured layer stack. Layer i's parameters live under
    ``params/layer{i}`` (Dense: kernel/bias; graves_lstm: the RNN cell
    pytree), which is what lets greedy pretraining write tensors back
    by name and lets prefix sub-networks reuse the same params."""

    layer_types: Sequence[str]
    n_outs: Sequence[int]
    activations: Sequence[str]
    dropouts: Sequence[float]
    weight_init: str

    @linen.compact
    def __call__(self, x, train: bool = False):
        n_layers = len(self.n_outs)
        for i, (ltype, n_out, act, drop) in enumerate(
            zip(self.layer_types, self.n_outs, self.activations,
                self.dropouts)
        ):
            is_last = i == n_layers - 1
            if ltype == "graves_lstm":
                seq = x if x.ndim == 3 else x[:, None, :]
                # RNN is scope-transparent: naming the cell puts its
                # gate params directly under params/layer{i+1}
                rnn = linen.RNN(
                    linen.OptimizedLSTMCell(
                        n_out,
                        activation_fn=_activation(act),
                        kernel_init=_weight_init(self.weight_init),
                        name=f"layer{i+1}",
                    ),
                )
                seq = rnn(seq)
                x = seq if x.ndim == 3 else seq[:, -1, :]
            else:
                if is_last and x.ndim == 3:
                    # output layer reads the final timestep of a
                    # recurrent sequence
                    x = x[:, -1, :]
                x = linen.Dense(
                    n_out,
                    kernel_init=_weight_init(self.weight_init),
                    name=f"layer{i+1}",
                )(x)
                x = _activation(act)(x)
            if drop > 0.0:
                x = linen.Dropout(rate=drop, deterministic=not train)(x)
        return x


def _loss_fn(name: str, weight_pos: float = 1.0, weight_neg: float = 1.0):
    """The configured loss; with non-unit class weights, the
    cost-sensitive variant (seizure workload): each sample's loss term
    scales by its class's weight — positives (``y[..., 0] == 1``, the
    one-hot pair convention) by ``weight_pos``, negatives by
    ``weight_neg`` — normalized by the weight sum. Unit weights (the
    default) return the EXACT pre-knob closures, so P300 training is
    byte-unchanged."""

    def mse(pred, y):
        return jnp.mean((pred - y) ** 2)

    def xent(pred, y):
        p = jnp.clip(pred, 1e-7, 1 - 1e-7)
        return -jnp.mean(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))

    def nll(pred, y):
        p = jnp.clip(pred, 1e-7, 1.0)
        return -jnp.mean(jnp.sum(y * jnp.log(p), axis=-1))

    losses = {"mse": mse, "xent": xent, "squared_loss": mse,
              "negativeloglikelihood": nll}
    if weight_pos == 1.0 and weight_neg == 1.0:
        return losses.get(name, mse)

    def per_sample(pred, y):
        if name == "xent":
            p = jnp.clip(pred, 1e-7, 1 - 1e-7)
            return -jnp.mean(
                y * jnp.log(p) + (1 - y) * jnp.log1p(-p), axis=-1
            )
        if name == "negativeloglikelihood":
            p = jnp.clip(pred, 1e-7, 1.0)
            return -jnp.sum(y * jnp.log(p), axis=-1)
        return jnp.mean((pred - y) ** 2, axis=-1)  # mse family

    def weighted(pred, y):
        t = y[..., 0]  # the [target, 1-target] one-hot convention
        w = t * weight_pos + (1.0 - t) * weight_neg
        return jnp.sum(w * per_sample(pred, y)) / jnp.sum(w)

    return weighted


def _make_backprop_step(model, tx, needs_value_fn, loss, rng, x, y):
    """The per-iteration supervised scan body, shared by the
    monolithic fit scan and the chunked elastic scan so the two can
    never drift (the sgd.py ``_make_scan_step`` discipline).
    ``step((params, opt_state), it) -> ((params, opt_state), value)``
    with ``it`` the ABSOLUTE iteration index (it keys the dropout
    rng, so chunked and monolithic runs share trajectories).

    Callers pass ``x``/``y`` through their jit boundary and build the
    step inside the traced function (x/y arrive as tracers) — binding
    concrete arrays here would bake the whole training set into the
    lowered program as constants."""

    def step(carry, it):
        params, opt_state = carry

        def objective(p):
            pred = model.apply(
                p, x, train=True,
                rngs={"dropout": jax.random.fold_in(rng, it)},
            )
            return loss(pred, y)

        value, grads = jax.value_and_grad(objective)(params)
        if needs_value_fn:  # lbfgs / line-search transforms
            updates, opt_state2 = tx.update(
                grads, opt_state, params,
                value=value, grad=grads, value_fn=objective,
            )
        else:
            updates, opt_state2 = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state2), value

    return step


# -- greedy layerwise pretraining --------------------------------------


def _pretrain_ae(key, h, kernel, bias, act_name, tx, iterations,
                 needs_value_fn=False):
    """Tied-weight denoising autoencoder on activations ``h``:
    encode z = act(h_corrupt @ W + b), decode r = z @ W.T + c (linear
    visible units), minimize MSE(r, h). Returns trained (W, b).

    The AE objective is a real scalar loss, so the configured
    optimization algorithm applies here too (lbfgs/line-search pass
    value/grad/value_fn through ``needs_value_fn``)."""
    act = _activation(act_name)
    c0 = jnp.zeros((h.shape[1],), h.dtype)
    params = {"W": kernel, "b": bias, "c": c0}
    opt_state = tx.init(params)

    @jax.jit
    def run(params, opt_state, h):
        def step(carry, it):
            params, opt_state = carry
            mask_key = jax.random.fold_in(key, it)
            keep = jax.random.bernoulli(
                mask_key, 1.0 - _AE_CORRUPTION, h.shape
            ).astype(h.dtype)

            def objective(p):
                z = act((h * keep) @ p["W"] + p["b"])
                r = z @ p["W"].T + p["c"]
                return jnp.mean((r - h) ** 2)

            value, grads = jax.value_and_grad(objective)(params)
            if needs_value_fn:
                updates, opt_state2 = tx.update(
                    grads, opt_state, params,
                    value=value, grad=grads, value_fn=objective,
                )
            else:
                updates, opt_state2 = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state2), None

        (params, opt_state), _ = jax.lax.scan(
            step, (params, opt_state), jnp.arange(iterations)
        )
        return params

    out = run(params, opt_state, h)
    return out["W"], out["b"]


def _pretrain_rbm(key, h, kernel, bias, tx, iterations):
    """CD-1 contrastive divergence: sigmoid hidden units, linear
    (Gaussian-convention) visible reconstruction. Returns (W, b)."""
    c0 = jnp.zeros((h.shape[1],), h.dtype)
    params = {"W": kernel, "b": bias, "c": c0}
    opt_state = tx.init(params)
    n = h.shape[0]

    @jax.jit
    def run(params, opt_state, v0):
        def step(carry, it):
            params, opt_state = carry
            W, b, c = params["W"], params["b"], params["c"]
            h0_prob = jax.nn.sigmoid(v0 @ W + b)
            h0_sample = jax.random.bernoulli(
                jax.random.fold_in(key, it), h0_prob
            ).astype(v0.dtype)
            v1 = h0_sample @ W.T + c
            h1_prob = jax.nn.sigmoid(v1 @ W + b)
            # negative gradients (CD ascends the likelihood proxy)
            g_w = -(v0.T @ h0_prob - v1.T @ h1_prob) / n
            g_b = -jnp.mean(h0_prob - h1_prob, axis=0)
            g_c = -jnp.mean(v0 - v1, axis=0)
            grads = {"W": g_w, "b": g_b, "c": g_c}
            updates, opt_state2 = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state2), None

        (params, opt_state), _ = jax.lax.scan(
            step, (params, opt_state), jnp.arange(iterations)
        )
        return params

    out = run(params, opt_state, h)
    return out["W"], out["b"]


class NeuralNetworkClassifier(base.Classifier):
    confusion_only_stats = False  # reference NN uses incremental add()

    def __init__(self) -> None:
        super().__init__()
        self.params = None
        self._arch: Dict | None = None

    def set_config(self, config) -> None:
        # fail at CONFIG time, not after a full training run: the
        # pipeline sets config, fits (potentially hours), then saves
        # — save-time rejection would waste the training (review
        # finding). See save() for why mllib output is impossible.
        if dict(config).get("config_model_format") == "mllib":
            raise NotImplementedError(
                "config_model_format=mllib is not available for nn: "
                "DL4J ModelSerializer zips wrap closed ND4J "
                "serialization (docs/MIGRATION.md)"
            )
        super().set_config(config)

    # -- config parsing ------------------------------------------------

    def _parse_layers(self) -> tuple:
        c = self.config
        num_layers = sum(1 for k in c if k.startswith("config_layer")) // 4
        if num_layers == 0:
            raise ValueError("no config_layer* keys; at least one layer required")
        ltypes: List[str] = []
        n_outs: List[int] = []
        acts: List[str] = []
        drops: List[float] = []
        for i in range(1, num_layers + 1):
            ltype = c.get(f"config_layer{i}_layer_type", "output")
            if ltype not in _LAYER_TYPES:
                ltype = "output"
            ltypes.append(ltype)
            n_outs.append(int(c[f"config_layer{i}_n_out"]))
            acts.append(c[f"config_layer{i}_activation_function"])
            drops.append(float(c[f"config_layer{i}_drop_out"]))
        return ltypes, n_outs, acts, drops

    def _require(self, key: str) -> str:
        # the reference NPEs on missing keys; fail with a named error
        if key not in self.config:
            raise ValueError(f"missing required NN config key: {key}")
        return self.config[key]

    def _build(self) -> _Net:
        return _Net(
            tuple(self._arch["layer_types"]),
            tuple(self._arch["n_outs"]),
            tuple(self._arch["activations"]),
            tuple(self._arch["dropouts"]),
            self._arch["weight_init"],
        )

    # -- training ------------------------------------------------------

    def _parse_scalars(self) -> dict:
        """The required DL4J scalar surface, parsed once — shared by
        :meth:`_prepare_fit` and :meth:`population_fit` so the two
        can never disagree about what a config means."""
        return {
            "seed": int(self._require("config_seed")),
            "iterations": int(self._require("config_num_iterations")),
            "lr": float(self._require("config_learning_rate")),
            "momentum": float(self._require("config_momentum")),
            "weight_init": self._require("config_weight_init"),
            "updater_name": self._require("config_updater"),
            "algo": self._require("config_optimization_algo").lower(),
            # Boolean.parseBoolean semantics: "true" (any case) is true
            "pretrain": self._require("config_pretrain").lower() == "true",
            "backprop": self._require("config_backprop").lower() == "true",
            # cost-sensitive class weights (optional; absent = 1.0,
            # the byte-identical pre-knob loss — docs/workloads.md)
            "weight_pos": float(self.config.get("config_weight_pos", 1.0)),
            "weight_neg": float(self.config.get("config_weight_neg", 1.0)),
        }

    def _prepare_fit(self, features: np.ndarray, labels: np.ndarray):
        """The shared front half of training: config parsing, arch
        recording, param init, optimizer/loss construction, and
        (optional) greedy pretraining. Returns everything the
        backprop loop needs, so :meth:`fit` (monolithic scan) and
        :meth:`fit_elastic` (chunked resumable scan) start from the
        identical state."""
        c = self._parse_scalars()
        seed, iterations, lr, momentum = (
            c["seed"], c["iterations"], c["lr"], c["momentum"]
        )
        weight_init, updater_name, algo = (
            c["weight_init"], c["updater_name"], c["algo"]
        )
        pretrain, backprop = c["pretrain"], c["backprop"]
        ltypes, n_outs, acts, drops = self._parse_layers()

        x = jnp.asarray(features, dtype=jnp.float32)
        # one-hot pairs: [target, 1-target] (NeuralNetworkClassifier.java:81-84)
        t = jnp.asarray(labels, dtype=jnp.float32)
        y = jnp.stack([t, jnp.abs(1.0 - t)], axis=1)

        self._arch = {
            "layer_types": ltypes,
            "n_outs": n_outs,
            "activations": acts,
            "dropouts": drops,
            "weight_init": weight_init,
            "n_in": int(x.shape[-1]),
        }
        model = self._build()
        rng = jax.random.PRNGKey(seed)
        params = model.init({"params": rng, "dropout": rng}, x[:1], train=False)
        tx, needs_value_fn = _optimizer(algo, updater_name, lr, momentum)
        loss = _loss_fn(
            self.config.get("config_loss_function", "mse"),
            weight_pos=c["weight_pos"], weight_neg=c["weight_neg"],
        )

        if pretrain:
            params = self._greedy_pretrain(
                model, params, x, ltypes, n_outs, acts, drops, weight_init,
                updater_name, lr, momentum, iterations, rng, algo,
            )
        return (
            model, params, tx, needs_value_fn, loss, x, y, rng,
            iterations, backprop,
        )

    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        (
            model, params, tx, needs_value_fn, loss, x, y, rng,
            iterations, backprop,
        ) = self._prepare_fit(features, labels)

        if backprop:
            opt_state = tx.init(params)

            @jax.jit
            def run(params, opt_state, x, y):
                step = _make_backprop_step(
                    model, tx, needs_value_fn, loss, rng, x, y
                )
                (params, opt_state), _ = jax.lax.scan(
                    step, (params, opt_state), jnp.arange(iterations)
                )
                return params

            params = run(params, opt_state, x, y)

        self.params = params

    def fit_elastic(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        manager,
        save_every: int = 1,
        max_restarts: int = 3,
        sentinel=None,
        chunk_iters: int = 10,
        probe_on_failure: bool = True,
    ) -> None:
        """:meth:`fit` with mid-train checkpoint/restore: the backprop
        scan runs in ``chunk_iters``-sized chunks through
        ``obs.failure.elastic_train``, checkpointing
        ``{"params", "opt"}`` after every chunk. Absolute iteration
        indices keep the per-iteration dropout keys identical to the
        monolithic scan, so an uninterrupted elastic run and a
        crash-restored one land on the same parameters. Greedy
        pretraining (when configured) runs up front, un-chunked — it
        is small relative to backprop and re-runs deterministically.
        """
        import functools

        from ..obs import chaos, failure

        (
            model, params0, tx, needs_value_fn, loss, x, y, rng,
            iterations, backprop,
        ) = self._prepare_fit(features, labels)
        if not backprop:
            self.params = params0
            return
        opt0 = tx.init(params0)

        @functools.partial(jax.jit, static_argnames=("n",))
        def run_chunk(state, it0, x, y, *, n):
            step = _make_backprop_step(
                model, tx, needs_value_fn, loss, rng, x, y
            )
            (params, opt_state), values = jax.lax.scan(
                step, (state["params"], state["opt"]),
                it0 + jnp.arange(n),
            )
            return {"params": params, "opt": opt_state}, values[-1]

        def init_state():
            return {"params": params0, "opt": opt0}

        chunks = [
            (it0, min(int(chunk_iters), iterations - it0))
            for it0 in range(0, iterations, int(chunk_iters))
        ]

        def chunk_step(state, it0, n):
            from ..obs import events

            # telemetry: one event per elastic chunk (crash reports
            # show how far backprop got before a failure)
            events.event("train.nn_chunk", it0=int(it0), iters=int(n))
            # host-level chaos injection point (one chunk = one
            # "device step" of the elastic driver)
            chaos.maybe_fire("device.step")
            return run_chunk(state, it0, x, y, n=n)

        state, _, _ = failure.elastic_train(
            manager,
            init_state,
            chunk_step,
            lambda: list(chunks),
            max_restarts=max_restarts,
            save_every=save_every,
            sentinel=sentinel,
            probe_on_failure=probe_on_failure,
        )
        self.params = state["params"]

    def population_fit(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        seeds,
        learning_rates,
    ) -> list:
        """Train P members — one per (init seed, learning rate) pair —
        as ONE vmapped program (parallel/population.py), returning a
        list of per-member param pytrees in member order. Each
        member's trajectory is exactly what :meth:`fit` runs for
        ``config_seed=seeds[i]`` / ``config_learning_rate=lrs[i]``:
        same init streams, same dropout keys, same backprop scan body.

        Raises ``PopulationVmapUnsupported`` for configs whose
        training cannot batch onto a member axis — greedy pretraining
        (a host-driven layer walk), value_fn-carrying optimizers
        (lbfgs / line search), or ``backprop=false`` (nothing to
        scan) — and the population orchestrator falls back to the
        looped engine for those.
        """
        from ..parallel.population import (
            PopulationVmapUnsupported, train_nn_population,
        )

        c = self._parse_scalars()
        _, needs_value_fn = _optimizer(
            c["algo"], c["updater_name"], c["lr"], c["momentum"]
        )
        if c["pretrain"]:
            raise PopulationVmapUnsupported(
                "greedy pretraining is a host-driven layer walk; "
                "population members with config_pretrain=true train "
                "looped"
            )
        if needs_value_fn:
            raise PopulationVmapUnsupported(
                f"optimization_algo={c['algo']} carries a value_fn "
                "closure; population members train looped"
            )
        if not c["backprop"]:
            raise PopulationVmapUnsupported(
                "config_backprop=false leaves nothing to scan; "
                "population members train looped"
            )
        ltypes, n_outs, acts, drops = self._parse_layers()
        x = np.asarray(features, dtype=np.float32)
        t = np.asarray(labels, dtype=np.float32)
        y = np.stack([t, np.abs(1.0 - t)], axis=1)
        self._arch = {
            "layer_types": ltypes,
            "n_outs": n_outs,
            "activations": acts,
            "dropouts": drops,
            "weight_init": c["weight_init"],
            "n_in": int(x.shape[-1]),
        }
        model = self._build()
        loss = _loss_fn(
            self.config.get("config_loss_function", "mse"),
            weight_pos=c["weight_pos"], weight_neg=c["weight_neg"],
        )
        momentum = c["momentum"]
        updater_name = c["updater_name"]

        def make_optimizer(lr):
            # lr may be a tracer carrying the member axis; every
            # first-order optax updater scales by it trace-safely
            return _updater(updater_name, lr, momentum)

        return train_nn_population(
            model, make_optimizer, loss, x, y,
            seeds, learning_rates, c["iterations"],
        )

    def _greedy_pretrain(
        self, model, params, x, ltypes, n_outs, acts, drops, weight_init,
        updater_name, lr, momentum, iterations, rng,
        algo="stochastic_gradient_descent",
    ):
        """DL4J MultiLayerNetwork pretrain walk: for each pretrainable
        layer, feed the input forward through the preceding layers
        (with their current weights) and train that layer unsupervised
        on the resulting activations, writing the tensors back into
        the model's params by layer name.

        AE layers honor ``config_optimization_algo`` (their
        reconstruction loss is a real objective); RBM layers always
        use the first-order updater — CD-1's pseudo-gradient has no
        scalar objective for a line search to evaluate."""
        params = jax.tree_util.tree_map(lambda a: a, params)  # shallow copy
        for i, ltype in enumerate(ltypes):
            if ltype not in _PRETRAINABLE or i == len(ltypes) - 1:
                continue
            if i == 0:
                h = x
            else:
                prefix = _Net(
                    tuple(ltypes[:i]), tuple(n_outs[:i]), tuple(acts[:i]),
                    (0.0,) * i, weight_init,
                )
                sub = {
                    "params": {
                        k: v for k, v in params["params"].items()
                        if k in {f"layer{j+1}" for j in range(i)}
                    }
                }
                h = prefix.apply(sub, x, train=False)
            if h.ndim == 3:  # recurrent activations: fold time into batch
                h = h.reshape(-1, h.shape[-1])
            name = f"layer{i+1}"
            kernel = params["params"][name]["kernel"]
            bias = params["params"][name]["bias"]
            key = jax.random.fold_in(rng, 1000 + i)
            if ltype == "auto_encoder":
                tx, needs_value_fn = _optimizer(
                    algo, updater_name, lr, momentum
                )
                w, b = _pretrain_ae(
                    key, h, kernel, bias, acts[i], tx, iterations,
                    needs_value_fn=needs_value_fn,
                )
            else:  # rbm: CD-1 pseudo-gradient, first-order updater only
                tx = _updater(updater_name, lr, momentum)
                w, b = _pretrain_rbm(key, h, kernel, bias, tx, iterations)
            params["params"][name] = dict(
                params["params"][name], kernel=w, bias=b
            )
        return params

    def predict(self, features: np.ndarray) -> np.ndarray:
        if self.params is None:
            raise ValueError("model not trained or loaded")
        model = self._build()
        out = model.apply(
            self.params, jnp.asarray(features, dtype=jnp.float32), train=False
        )
        return np.asarray(out[:, 0], dtype=np.float64)  # P(target), :161

    # -- persistence ---------------------------------------------------

    def save(self, path: str) -> None:
        from flax import serialization

        from ..io import modelfiles

        if self.config.get("config_model_format") == "mllib":
            # the GLM/tree classifiers honor this key
            # (io/mllib_format.py); the NN's JVM twin is a DL4J
            # ModelSerializer zip around closed ND4J array
            # serialization — refuse loudly rather than write npz
            # under a name the user asked to be Spark-loadable
            raise NotImplementedError(
                "config_model_format=mllib is not available for nn: "
                "DL4J ModelSerializer zips wrap closed ND4J "
                "serialization (docs/MIGRATION.md)"
            )
        blob = serialization.to_bytes(self.params)
        header = json.dumps({"arch": self._arch, "config": self.config})
        data = (
            len(header).to_bytes(8, "little") + header.encode() + blob
        )
        modelfiles.write_model_bytes(path, data)

    def load(self, path: str) -> None:
        from flax import serialization

        from ..io import modelfiles

        raw = modelfiles.read_model_bytes(path)
        if raw[:2] == b"PK":
            # a reference deployment's ModelSerializer archive
            # (sniffed on the BYTES so remote URIs and file:// paths
            # hit the same refusal — review finding): the
            # architecture (configuration.json) IS importable — the
            # weights are not (closed ND4J serialization)
            raise NotImplementedError(
                "this is a DL4J ModelSerializer zip; its weights use "
                "closed ND4J serialization and cannot be imported — "
                "port the architecture with "
                "io.dl4j_compat.import_dl4j_architecture(path), "
                "set_config() it, and retrain (docs/MIGRATION.md)"
            )
        hlen = int.from_bytes(raw[:8], "little")
        header = json.loads(raw[8 : 8 + hlen].decode())
        blob = raw[8 + hlen :]
        self._arch = header["arch"]
        if "layer_types" not in self._arch:  # round-1 save files
            self._arch["layer_types"] = (
                ["dense"] * (len(self._arch["n_outs"]) - 1) + ["output"]
            )
        self.config = header["config"]
        model = self._build()
        template = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, self._arch["n_in"]), jnp.float32),
        )
        self.params = serialization.from_bytes(template, blob)
