"""Neural-network classifier: flax MLP + optax updaters.

TPU-native re-design of ``Classification/NeuralNetworkClassifier.java``
(DL4J 0.8 ``MultiLayerNetwork`` + ND4J C++ backend -> flax module +
optax optimizer + one jitted train step on XLA). The entire DL4J
config surface is preserved:

- required scalars: ``config_seed``, ``config_num_iterations``,
  ``config_learning_rate``, ``config_momentum``,
  ``config_weight_init``, ``config_updater``,
  ``config_optimization_algo`` (the reference has NO code-level
  defaults — missing keys throw, NeuralNetworkClassifier.java:102-110);
- layer count = #(config_layer* keys)/4; per layer i (1-based):
  ``config_layer{i}_layer_type`` (output|dense|auto_encoder|rbm|
  graves_lstm), ``_n_out``, ``_drop_out``, ``_activation_function``;
  output layers read the global ``config_loss_function``
  (NeuralNetworkClassifier.java:258-320). auto_encoder/rbm/graves_lstm
  forward like dense layers over a 48-dim feature vector, which is
  exactly what DL4J's backprop-only path does with them here;
- enum mappings with the reference's silent fallbacks
  (NeuralNetworkClassifier.java:201-255): weight_init xavier|zero|
  sigmoid|uniform|relu (default relu), updater sgd|adam|nesterovs|
  adagrad|rmsprop (default nesterovs), loss mse|xent|squared_loss|
  negativeloglikelihood (default mse), activation sigmoid|softmax|
  relu|tanh|identity|softplus|elu (default sigmoid);
- labels are one-hot pairs [target, 1-target]
  (NeuralNetworkClassifier.java:81-84) and the prediction is
  ``output[0]`` (:161);
- ``config_pretrain``/``config_backprop`` are required flags; pretrain
  is accepted and ignored (DL4J 0.8 layerwise pretraining of RBM/AE
  stacks is not reproduced — backprop training subsumes it here).

Training runs ``config_num_iterations`` full-batch optimizer steps
(DL4J ``.iterations(n)`` + ``model.fit(dataSet)``) inside a single
``lax.scan`` jit — the reference's per-iteration ND4J JNI round trips
collapse into one XLA program.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen

from . import base

_ACTIVATIONS = {
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "identity": lambda x: x,
    "softplus": jax.nn.softplus,
    "elu": jax.nn.elu,
}
_LAYER_TYPES = ("output", "dense", "auto_encoder", "rbm", "graves_lstm")


def _activation(name: str):
    return _ACTIVATIONS.get(name, _ACTIVATIONS["sigmoid"])


def _weight_init(name: str):
    inits = {
        "xavier": linen.initializers.glorot_uniform(),
        "zero": linen.initializers.zeros_init(),
        "sigmoid": linen.initializers.glorot_uniform(),  # DL4J SIGMOID_UNIFORM
        "uniform": linen.initializers.uniform(scale=0.01),
        "relu": linen.initializers.he_normal(),
    }
    return inits.get(name, inits["relu"])


def _updater(name: str, lr: float, momentum: float):
    opts = {
        "sgd": lambda: optax.sgd(lr),
        "adam": lambda: optax.adam(lr),
        "nesterovs": lambda: optax.sgd(lr, momentum=momentum, nesterov=True),
        "adagrad": lambda: optax.adagrad(lr),
        "rmsprop": lambda: optax.rmsprop(lr),
    }
    return opts.get(name, opts["nesterovs"])()


class _MLP(linen.Module):
    n_outs: Sequence[int]
    activations: Sequence[str]
    dropouts: Sequence[float]
    weight_init: str

    @linen.compact
    def __call__(self, x, train: bool = False):
        for i, (n_out, act, drop) in enumerate(
            zip(self.n_outs, self.activations, self.dropouts)
        ):
            x = linen.Dense(
                n_out, kernel_init=_weight_init(self.weight_init), name=f"layer{i+1}"
            )(x)
            x = _activation(act)(x)
            if drop > 0.0:
                x = linen.Dropout(rate=drop, deterministic=not train)(x)
        return x


def _loss_fn(name: str):
    def mse(pred, y):
        return jnp.mean((pred - y) ** 2)

    def xent(pred, y):
        p = jnp.clip(pred, 1e-7, 1 - 1e-7)
        return -jnp.mean(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))

    def nll(pred, y):
        p = jnp.clip(pred, 1e-7, 1.0)
        return -jnp.mean(jnp.sum(y * jnp.log(p), axis=-1))

    return {"mse": mse, "xent": xent, "squared_loss": mse,
            "negativeloglikelihood": nll}.get(name, mse)


class NeuralNetworkClassifier(base.Classifier):
    confusion_only_stats = False  # reference NN uses incremental add()

    def __init__(self) -> None:
        super().__init__()
        self.params = None
        self._arch: Dict | None = None

    # -- config parsing ------------------------------------------------

    def _parse_layers(self) -> tuple:
        c = self.config
        num_layers = sum(1 for k in c if k.startswith("config_layer")) // 4
        if num_layers == 0:
            raise ValueError("no config_layer* keys; at least one layer required")
        n_outs: List[int] = []
        acts: List[str] = []
        drops: List[float] = []
        for i in range(1, num_layers + 1):
            ltype = c.get(f"config_layer{i}_layer_type", "output")
            if ltype not in _LAYER_TYPES:
                ltype = "output"
            n_outs.append(int(c[f"config_layer{i}_n_out"]))
            acts.append(c[f"config_layer{i}_activation_function"])
            drops.append(float(c[f"config_layer{i}_drop_out"]))
        return n_outs, acts, drops

    def _require(self, key: str) -> str:
        # the reference NPEs on missing keys; fail with a named error
        if key not in self.config:
            raise ValueError(f"missing required NN config key: {key}")
        return self.config[key]

    # -- training ------------------------------------------------------

    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        seed = int(self._require("config_seed"))
        iterations = int(self._require("config_num_iterations"))
        lr = float(self._require("config_learning_rate"))
        momentum = float(self._require("config_momentum"))
        weight_init = self._require("config_weight_init")
        updater_name = self._require("config_updater")
        self._require("config_optimization_algo")  # accepted; SGD family only
        self._require("config_pretrain")
        self._require("config_backprop")
        n_outs, acts, drops = self._parse_layers()

        x = jnp.asarray(features, dtype=jnp.float32)
        # one-hot pairs: [target, 1-target] (NeuralNetworkClassifier.java:81-84)
        t = jnp.asarray(labels, dtype=jnp.float32)
        y = jnp.stack([t, jnp.abs(1.0 - t)], axis=1)

        model = _MLP(tuple(n_outs), tuple(acts), tuple(drops), weight_init)
        rng = jax.random.PRNGKey(seed)
        params = model.init({"params": rng, "dropout": rng}, x[:1], train=False)
        tx = _updater(updater_name, lr, momentum)
        opt_state = tx.init(params)
        loss = _loss_fn(self.config.get("config_loss_function", "mse"))

        @jax.jit
        def run(params, opt_state, x, y):
            def step(carry, it):
                params, opt_state = carry

                def objective(p):
                    pred = model.apply(
                        p, x, train=True,
                        rngs={"dropout": jax.random.fold_in(rng, it)},
                    )
                    return loss(pred, y)

                grads = jax.grad(objective)(params)
                updates, opt_state2 = tx.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state2), None

            (params, opt_state), _ = jax.lax.scan(
                step, (params, opt_state), jnp.arange(iterations)
            )
            return params

        self.params = run(params, opt_state, x, y)
        self._arch = {
            "n_outs": n_outs,
            "activations": acts,
            "dropouts": drops,
            "weight_init": weight_init,
            "n_in": int(x.shape[1]),
        }

    def predict(self, features: np.ndarray) -> np.ndarray:
        if self.params is None:
            raise ValueError("model not trained or loaded")
        model = _MLP(
            tuple(self._arch["n_outs"]),
            tuple(self._arch["activations"]),
            tuple(self._arch["dropouts"]),
            self._arch["weight_init"],
        )
        out = model.apply(
            self.params, jnp.asarray(features, dtype=jnp.float32), train=False
        )
        return np.asarray(out[:, 0], dtype=np.float64)  # P(target), :161

    # -- persistence ---------------------------------------------------

    def save(self, path: str) -> None:
        from flax import serialization

        if os.path.exists(path) and os.path.isfile(path):
            os.remove(path)  # reference deletes the target first (:171)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        blob = serialization.to_bytes(self.params)
        with open(path, "wb") as f:
            header = json.dumps({"arch": self._arch, "config": self.config})
            f.write(len(header).to_bytes(8, "little"))
            f.write(header.encode())
            f.write(blob)

    def load(self, path: str) -> None:
        from flax import serialization

        with open(path, "rb") as f:
            hlen = int.from_bytes(f.read(8), "little")
            header = json.loads(f.read(hlen).decode())
            blob = f.read()
        self._arch = header["arch"]
        self.config = header["config"]
        model = _MLP(
            tuple(self._arch["n_outs"]),
            tuple(self._arch["activations"]),
            tuple(self._arch["dropouts"]),
            self._arch["weight_init"],
        )
        template = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, self._arch["n_in"]), jnp.float32),
        )
        self.params = serialization.from_bytes(template, blob)
