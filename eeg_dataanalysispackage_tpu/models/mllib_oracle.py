"""Exact float64 host emulation of Spark MLlib 1.6 ``GradientDescent``.

The device path (``models/sgd.py``) is the production engine: one f32
XLA program per training run. This module is its *oracle*: a plain
NumPy float64 re-enactment of what the reference's JVM actually
computes when ``ClassifierTest.java:98-105`` runs
``new LogisticRegressionWithSGD().run(rdd)`` — every operation in the
order MLlib 1.6.2's ``GradientDescent.runMiniBatchSGD`` performs it:

- zero initial weights, no intercept, no feature scaling
  (``GeneralizedLinearAlgorithm`` defaults; the reference never calls
  ``setIntercept``);
- iteration ``i`` (1-based): full-batch gradient sum over the data in
  RDD order (``treeAggregate`` seqOp accumulation), divided by the
  batch count;
- ``SquaredL2Updater``: ``w = w*(1 - step_i*regParam) - step_i*g``
  with ``step_i = stepSize/sqrt(i)``;
- the **convergence check** MLlib applies from iteration 2 onward:
  stop when ``norm(w_prev - w_cur) < tol * max(norm(w_cur), 1)`` with
  default ``convergenceTol = 0.001`` — the reference's default-config
  classifiers inherit this early stop;
- prediction thresholds: logreg ``sigmoid(margin) > 0.5`` (strict,
  ``LogisticRegressionModel.predictPoint``), svm ``margin > 0.0``
  (``SVMModel.predictPoint``).

The deterministic full-batch path (``miniBatchFraction == 1.0``) is
emulated exactly. The sampled path depends on Spark's per-partition
XORShift sampler and cannot be bit-reproduced (documented in
``models/sgd.py``); it is emulated *statistically* — same per-element
Bernoulli process, numpy PRNG — so seed-sweep distributions of the
device engine, this oracle, and the JVM are mutually comparable even
though individual trajectories are not.

Why this exists: the reference's informal accuracy pin
0.6415094339622641 (``ClassifierTest.java:105``, commented out) is
34/53 — it needs a 53-point test split, i.e. a ~177-epoch corpus that
is NOT in the shipped ``test-data/`` fixture (which yields 11 epochs
→ a 4-point test split whose accuracies are multiples of 0.25). The
reproducible contract is therefore: this oracle's trajectory on the
shipped fixture, pinned by ``tests/test_mllib_accuracy_parity.py``,
with the device f32 path asserted to agree.
"""

from __future__ import annotations

import math

import numpy as np


def run_gradient_descent(
    features: np.ndarray,
    labels: np.ndarray,
    *,
    loss: str,
    step_size: float = 1.0,
    num_iterations: int = 100,
    reg_param: float = 0.01,
    mini_batch_fraction: float = 1.0,
    convergence_tol: float = 0.001,
    seed: int = 42,
) -> tuple[np.ndarray, list[float], int]:
    """Return (weights_f64, loss_history, iterations_run).

    ``loss`` is "logistic" (LogisticGradient, binary) or "hinge"
    (HingeGradient).

    ``mini_batch_fraction < 1.0`` runs the *sampled emulation*: per
    iteration, each row is kept Bernoulli(fraction) — the same
    per-element sampling model as MLlib's ``RDD.sample`` — but drawn
    from numpy's PRNG seeded ``[seed, i]``, NOT Spark's per-partition
    XORShift seeded ``42 + i``, so individual trajectories are NOT
    bit-comparable to the JVM (or to the device engine, which folds
    ``i`` into a JAX PRNG key). What IS comparable — and what
    tests/test_mllib_accuracy_parity.py asserts — is the seed-sweep
    *distribution* of outcomes (final weight norm, accuracy): three
    different PRNGs driving the same Bernoulli process must land in
    the same place statistically. MLlib's empty-sample semantics are
    kept: a sampled-empty iteration leaves the weights unchanged and
    appends no loss, and the convergence check compares consecutive
    *updated* iterates only.
    """
    if loss not in ("logistic", "hinge"):
        raise ValueError(f"unknown loss: {loss}")
    if not 0.0 < mini_batch_fraction <= 1.0:
        raise ValueError(
            f"mini_batch_fraction must be in (0, 1]; got {mini_batch_fraction}"
        )

    x = np.asarray(features, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64)
    n, d = x.shape
    w = np.zeros(d, dtype=np.float64)

    loss_history: list[float] = []
    # regVal seeding: updater.compute(w0, 0, 0, 1, regParam)._2 with
    # w0 == 0 gives 0.0 for SquaredL2Updater.
    reg_val = 0.5 * reg_param * float(np.dot(w, w))

    prev_w: np.ndarray | None = None
    cur_w: np.ndarray | None = None
    converged = False
    i = 1
    while not converged and i <= num_iterations:
        if mini_batch_fraction >= 1.0:
            sampled = range(n)
            batch_size = n
        else:
            rng = np.random.default_rng([seed, i])
            keep = rng.random(n) < mini_batch_fraction
            sampled = np.flatnonzero(keep)
            batch_size = int(keep.sum())

        grad_sum = np.zeros(d, dtype=np.float64)
        loss_sum = 0.0
        if loss == "logistic":
            # LogisticGradient.compute (binary): margin = -w.x,
            # multiplier = 1/(1+exp(margin)) - label
            for k in sampled:
                margin = -float(np.dot(x[k], w))
                # np.exp returns inf past ~709 (Java Math.exp
                # semantics: 1/(1+Inf) == 0); math.exp would raise
                with np.errstate(over="ignore"):
                    multiplier = float(
                        1.0 / (1.0 + np.exp(np.float64(margin)))
                    ) - y[k]
                grad_sum += multiplier * x[k]
                # MLUtils.log1pExp(margin), minus margin for label 0
                if margin > 0:
                    point_loss = margin + math.log1p(math.exp(-margin))
                else:
                    point_loss = math.log1p(math.exp(margin))
                loss_sum += point_loss if y[k] > 0 else point_loss - margin
        else:  # hinge
            for k in sampled:
                dot = float(np.dot(x[k], w))
                label_scaled = 2.0 * y[k] - 1.0
                if 1.0 > label_scaled * dot:
                    grad_sum += (-label_scaled) * x[k]
                    loss_sum += 1.0 - label_scaled * dot

        if batch_size > 0:
            loss_history.append(loss_sum / batch_size + reg_val)
            # SquaredL2Updater.compute
            step_i = step_size / math.sqrt(i)
            w_new = w * (1.0 - step_i * reg_param) - step_i * (
                grad_sum / batch_size
            )
            reg_val = 0.5 * reg_param * float(np.dot(w_new, w_new))
            w = w_new

            prev_w = cur_w
            cur_w = w
            if prev_w is not None:
                diff = float(np.linalg.norm(prev_w - cur_w))
                converged = diff < convergence_tol * max(
                    float(np.linalg.norm(cur_w)), 1.0
                )
        i += 1

    return w, loss_history, i - 1


def predict_logreg(features: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """LogisticRegressionModel.predictPoint: sigmoid(w.x) > 0.5, strict."""
    x = np.asarray(features, dtype=np.float64)
    margin = x @ np.asarray(weights, dtype=np.float64)
    score = 1.0 / (1.0 + np.exp(-margin))
    return (score > 0.5).astype(np.float64)


def predict_svm(features: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """SVMModel.predictPoint: margin > 0.0, strict."""
    x = np.asarray(features, dtype=np.float64)
    margin = x @ np.asarray(weights, dtype=np.float64)
    return (margin > 0.0).astype(np.float64)
