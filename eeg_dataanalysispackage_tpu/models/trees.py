"""Decision-tree and random-forest classifiers (histogram CART).

TPU-era re-design of ``Classification/DecisionTreeClassifier.java``
and ``Classification/RandomForestClassifier.java`` (Spark MLlib 1.6
``DecisionTree``/``RandomForest``). MLlib's architecture — quantile
binning to ``maxBins``, then level-by-level growth driven by
per-(node, feature, bin, class) histogram aggregation — maps naturally
onto array programs, and that is what this module does: one vectorized
histogram pass per tree level over dense bin indices, no per-sample
recursion. Flat array node storage gives vectorized prediction.

Config surface parity:

- DT requires all of ``config_max_bins``, ``config_impurity``
  (gini|entropy), ``config_max_depth``,
  ``config_min_instances_per_node`` to use custom values
  (DecisionTreeClassifier.java:103-120), else MLlib classification
  defaults (gini, maxDepth 5, maxBins 32, minInstances 1);
- RF additionally requires ``config_num_trees`` and
  ``config_feature_subset`` (auto|all|sqrt|log2|onethird;
  RandomForestClassifier.java:106-129), defaulting to numTrees=100,
  'auto' (RandomForestClassifier.java:132-135); bootstrap + subset
  sampling is seeded with MLlib's fixed seed 12345
  (RandomForestClassifier.java:104);
- save/load mirror the reference's ``file://``-prefix tolerance
  (DecisionTreeClassifier.java:157-165).
"""

from __future__ import annotations

import json
import io
import math
from typing import Dict, List, Optional

import numpy as np

from . import base

_EPS = 1e-12


def _impurity(counts: np.ndarray, kind: str) -> np.ndarray:
    """counts: (..., n_classes) -> impurity (...)."""
    total = counts.sum(axis=-1, keepdims=True)
    p = counts / np.maximum(total, _EPS)
    if kind == "entropy":
        # MLlib's Entropy.log2 is log(x)/log(2), NOT a fused log2 —
        # matched so impurities bit-agree with mllib_tree_oracle
        return -(p * (np.log(np.maximum(p, _EPS)) / math.log(2.0))).sum(axis=-1)
    return 1.0 - (p**2).sum(axis=-1)  # gini


class _Tree:
    """Flat-array binary tree over binned features."""

    __slots__ = ("feature", "threshold_bin", "left", "right", "prediction")

    def __init__(self):
        self.feature: List[int] = []
        self.threshold_bin: List[int] = []
        self.left: List[int] = []
        self.right: List[int] = []
        self.prediction: List[float] = []

    def add_node(self) -> int:
        self.feature.append(-1)
        self.threshold_bin.append(-1)
        self.left.append(-1)
        self.right.append(-1)
        self.prediction.append(0.0)
        return len(self.feature) - 1

    def to_arrays(self) -> Dict[str, np.ndarray]:
        return {
            "feature": np.array(self.feature, dtype=np.int32),
            "threshold_bin": np.array(self.threshold_bin, dtype=np.int32),
            "left": np.array(self.left, dtype=np.int32),
            "right": np.array(self.right, dtype=np.int32),
            "prediction": np.array(self.prediction, dtype=np.float64),
        }


def compute_bin_edges(features: np.ndarray, max_bins: int) -> np.ndarray:
    """Candidate split thresholds per feature: (d, max_bins-1).

    Thresholds come from MLlib 1.6.2's count-stride sketch over sorted
    distinct *observed values* (``DecisionTree
    .findSplitsForContinuousFeature``; emulated exactly in
    ``models/mllib_tree_oracle.py``), NOT from interpolated
    ``np.quantile`` — so the production tree evaluates the same
    candidate set the reference's JVM does.  ``maxPossibleBins =
    min(maxBins, numExamples)`` as in ``DecisionTreeMetadata``.
    Features with fewer thresholds than ``max_bins - 1`` are padded
    with ``+inf``; padded candidates produce an empty right child and
    are rejected by the min-instances validity mask in both growers,
    keeping the dense (d, max_bins-1) shape the device path tiles."""
    from . import mllib_tree_oracle

    features = np.asarray(features, dtype=np.float64)
    n, d = features.shape
    num_splits = min(max_bins, n) - 1
    edges = np.full((d, max_bins - 1), np.inf, dtype=np.float64)
    for j in range(d):
        th = mllib_tree_oracle.find_splits_for_continuous_feature(
            features[:, j], num_splits
        )
        edges[j, : len(th)] = th
    return edges


def bin_features(features: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """(n, d) continuous -> (n, d) int bin indices in [0, max_bins).

    ``side='left'``: a value equal to a threshold lands in the bin
    that threshold closes, so the split ``bin <= b`` sends it LEFT —
    MLlib's ``(split(b-1), split(b)]`` bin semantics
    (``TreePoint.findBin``)."""
    n, d = features.shape
    binned = np.empty((n, d), dtype=np.int32)
    for j in range(d):
        binned[:, j] = np.searchsorted(edges[j], features[:, j], side="left")
    return binned


def _grow_tree(
    binned: np.ndarray,
    labels: np.ndarray,
    max_bins: int,
    impurity: str,
    max_depth: int,
    min_instances: int,
    feature_subset: Optional[int],
    rng: np.random.RandomState,
) -> _Tree:
    """Level-by-level CART growth via vectorized histograms.

    Per level, one bincount over (sample -> node x feature x bin x
    class) builds every node's split statistics at once — the same
    aggregation shape MLlib distributes over executors, here a single
    dense reduction.
    """
    n, d = binned.shape
    tree = _Tree()
    root = tree.add_node()
    active = {root: np.arange(n)}

    for _depth in range(max_depth):
        if not active:
            break
        next_active: Dict[int, np.ndarray] = {}
        for node_id, idx in active.items():
            y = labels[idx]
            pos = float(y.sum())
            tree.prediction[node_id] = 1.0 if pos * 2 > len(idx) else 0.0
            if len(idx) < 2 * min_instances or pos == 0 or pos == len(idx):
                continue
            feats = (
                np.sort(rng.choice(d, size=feature_subset, replace=False))
                if feature_subset is not None and feature_subset < d
                else np.arange(d)
            )
            sub = binned[idx][:, feats]  # (m, f)
            m, f = sub.shape
            # histogram: (f, max_bins, 2) class counts per feature/bin
            flat = (np.arange(f)[None, :] * max_bins + sub) * 2 + y[:, None].astype(
                np.int64
            )
            hist = np.bincount(flat.ravel(), minlength=f * max_bins * 2).reshape(
                f, max_bins, 2
            )
            # cumulative over bins: candidate split "bin <= b" for b < max_bins-1
            cum = hist.cumsum(axis=1)  # (f, bins, 2)
            total = cum[:, -1:, :]
            left_counts = cum[:, :-1, :]  # (f, bins-1, 2)
            right_counts = total - left_counts
            nl = left_counts.sum(-1)
            nr = right_counts.sum(-1)
            valid = (nl >= min_instances) & (nr >= min_instances)
            parent_imp = _impurity(total[:, 0, :], impurity)[:, None]
            # MLlib association order (calculateGainForSplit):
            # impurity - lw*lImp - rw*rImp, mirrored by the device
            # grower and models/mllib_tree_oracle.py so near-tie
            # argmaxes bit-match the oracle
            gain = (
                parent_imp
                - (nl / m) * _impurity(left_counts, impurity)
                - (nr / m) * _impurity(right_counts, impurity)
            )
            gain = np.where(valid, gain, -np.inf)
            best_flat = int(np.argmax(gain))
            bf, bb = divmod(best_flat, max_bins - 1)
            if not np.isfinite(gain[bf, bb]) or gain[bf, bb] <= 0:
                continue
            feat = int(feats[bf])
            go_left = binned[idx, feat] <= bb
            li, ri = tree.add_node(), tree.add_node()
            tree.feature[node_id] = feat
            tree.threshold_bin[node_id] = int(bb)
            tree.left[node_id] = li
            tree.right[node_id] = ri
            next_active[li] = idx[go_left]
            next_active[ri] = idx[~go_left]
        active = next_active

    # finalize predictions for any still-active leaves
    for node_id, idx in active.items():
        y = labels[idx]
        tree.prediction[node_id] = 1.0 if y.sum() * 2 > len(idx) else 0.0
    return tree


def _predict_tree(arrays: Dict[str, np.ndarray], binned: np.ndarray) -> np.ndarray:
    """Vectorized traversal: all samples walk the flat tree together."""
    n = binned.shape[0]
    node = np.zeros(n, dtype=np.int32)
    feature = arrays["feature"]
    for _ in range(64):  # depth bound
        is_leaf = feature[node] < 0
        if is_leaf.all():
            break
        f = np.maximum(feature[node], 0)
        go_left = binned[np.arange(n), f] <= arrays["threshold_bin"][node]
        nxt = np.where(go_left, arrays["left"][node], arrays["right"][node])
        node = np.where(is_leaf, node, nxt).astype(np.int32)
    return arrays["prediction"][node]


class DecisionTreeClassifier(base.Classifier):
    required_keys = (
        "config_max_bins",
        "config_impurity",
        "config_max_depth",
        "config_min_instances_per_node",
    )

    def __init__(self, backend: str = "host") -> None:
        """``backend='host'`` is the numpy reference grower;
        ``'device'`` grows the whole forest in one XLA program
        (``models/trees_device.py``; also selectable per run via the
        ``config_backend`` extension key). Both produce the same tree
        array format, so prediction and persistence are shared."""
        super().__init__()
        if backend not in ("host", "device"):
            raise ValueError(f"unknown tree backend: {backend!r}")
        self.backend = backend
        self.trees: List[Dict[str, np.ndarray]] = []
        self.edges: Optional[np.ndarray] = None
        self._params: Dict = {}
        # packed (T, n_nodes) device arrays for predict_linked_forest,
        # built lazily and invalidated whenever self.trees changes
        self._device_pack = None
        # a loaded MLlib model directory (io/mllib_format.py) — raw
        # continuous thresholds, so prediction routes through its own
        # reference-semantics descent instead of the binned forest
        self._mllib = None

    # MLlib class tag this classifier accepts from a model directory
    _mllib_class = "org.apache.spark.mllib.tree.model.DecisionTreeModel"

    def _resolved_backend(self) -> str:
        """The run's backend: ``config_backend`` overrides the ctor
        choice; invalid values raise here, so every consumer (fit and
        predict alike) fails loudly instead of silently routing to
        the host path."""
        backend = self.config.get("config_backend", self.backend)
        if backend not in ("host", "device"):
            raise ValueError(f"unknown tree backend: {backend!r}")
        return backend

    # MLlib Strategy.defaultStrategy("Classification") values
    def _tree_params(self) -> Dict:
        c = self.config
        if all(k in c for k in self.required_keys):
            return {
                "max_bins": int(c["config_max_bins"]),
                "impurity": c["config_impurity"],
                "max_depth": int(c["config_max_depth"]),
                "min_instances": int(c["config_min_instances_per_node"]),
            }
        return {"max_bins": 32, "impurity": "gini", "max_depth": 5, "min_instances": 1}

    def _n_trees(self) -> int:
        return 1

    def _feature_subset(self, d: int) -> Optional[int]:
        return None

    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        p = self._tree_params()
        self._params = p
        self._device_pack = None
        # training replaces any previously imported MLlib model; the
        # predict short-circuit must follow the new trees
        self._mllib = None
        y = np.floor(np.asarray(labels, dtype=np.float64) + 0.5).astype(np.int64)
        self.edges = compute_bin_edges(features, p["max_bins"])
        binned = bin_features(features, self.edges)
        if self._resolved_backend() == "device":
            self._fit_device(binned, y, p)
            return
        rng = np.random.RandomState(12345)  # RandomForestClassifier.java:104
        n = len(y)
        self.trees = []
        for _t in range(self._n_trees()):
            if self._n_trees() > 1:
                idx = rng.randint(0, n, size=n)  # bootstrap
            else:
                idx = np.arange(n)
            tree = _grow_tree(
                binned[idx],
                y[idx],
                p["max_bins"],
                p["impurity"],
                p["max_depth"],
                p["min_instances"],
                self._feature_subset(features.shape[1]),
                rng,
            )
            self.trees.append(tree.to_arrays())

    def _fit_device(self, binned: np.ndarray, y: np.ndarray, p: Dict) -> None:
        """Grow the whole forest in one XLA program (vmap over trees).

        Bootstrap draws and per-heap-slot feature masks are set up
        host-side with the reference's fixed seed 12345; the growth
        itself — one batched histogram scatter + gain argmax per tree
        level — runs on device (models/trees_device.py)."""
        import jax.numpy as jnp

        from . import trees_device

        n, d = binned.shape
        T = self._n_trees()
        rng = np.random.RandomState(12345)
        if T > 1:
            boot = rng.randint(0, n, size=(T, n))
        else:
            boot = np.arange(n)[None, :]
        masks = trees_device.draw_feature_masks(
            T,
            trees_device.n_heap_nodes(p["max_depth"] - 1),  # internal nodes
            d,
            self._feature_subset(d),
        )
        forest = trees_device.grow_forest(
            jnp.asarray(binned, jnp.int32),
            jnp.asarray(y, jnp.int32),
            jnp.asarray(boot, jnp.int32),
            jnp.asarray(masks),
            max_bins=p["max_bins"],
            impurity=p["impurity"],
            max_depth=p["max_depth"],
            min_instances=p["min_instances"],
        )
        self.trees = trees_device.heap_to_host_arrays(forest)

    def predict(self, features: np.ndarray) -> np.ndarray:
        if self._mllib is not None:
            return self._mllib.predict(features)
        if not self.trees or self.edges is None:
            raise ValueError("model not trained or loaded")
        binned = bin_features(np.asarray(features, dtype=np.float64), self.edges)
        if self._resolved_backend() == "device":
            # whole-forest inference as one XLA program; votes are
            # 0/1 so the f32 mean is exact for any practical T
            import jax.numpy as jnp

            from . import trees_device

            if self._device_pack is None:
                self._device_pack = trees_device.host_trees_to_device(
                    self.trees
                )
            votes = np.asarray(
                trees_device.predict_linked_forest(
                    *self._device_pack,
                    jnp.asarray(binned, jnp.int32),
                    max_iters=int(self._params["max_depth"]),
                )
            )
        else:
            votes = np.stack(
                [_predict_tree(t, binned) for t in self.trees]
            )
        return (votes.mean(axis=0) > 0.5).astype(np.float64)

    # -- persistence (file:// prefix tolerated like the reference) -----

    @staticmethod
    def _strip_prefix(path: str) -> str:
        return path[7:] if path.startswith("file://") else path

    def export_mllib_dir(self, path: str) -> None:
        """Write this model as a Spark-1.6 MLlib model directory
        (io/mllib_format.py format 1.0) — the reverse migration: a
        model trained here keeps serving on an existing Spark
        deployment (the artifact ``DecisionTreeModel.load`` /
        ``RandomForestModel.load`` consumes,
        DecisionTreeClassifier.java:163-165).

        The production trees store BINNED split thresholds; each maps
        back to its real-valued bin edge exactly (``bin <= b`` in
        ``bin_features``'s ``(lo, hi]`` semantics is ``value <=
        edges[feature, b]`` — MLlib's own continuous-split
        predicate), so the exported model predicts identically to
        this one. An imported model re-exports as-is."""
        from ..io import mllib_format as mf

        if self._mllib is not None:
            mf.write_tree_ensemble(
                path,
                self._mllib.model_class,
                self._mllib.trees,
                tree_weights=self._mllib.tree_weights,
                algo=self._mllib.algo,
                # preserved verbatim (re-export-as-is contract), in
                # Spark's capitalized spelling
                combining={
                    "vote": "Vote", "sum": "Sum", "average": "Average"
                }[self._mllib.combining],
            )
            return
        if not self.trees or self.edges is None:
            raise ValueError("model not trained or loaded")
        trees = []
        for t in self.trees:
            feat = np.asarray(t["feature"])
            leaf = feat < 0  # the growers' leaf marker
            safe_feat = np.maximum(feat, 0)
            thr_bin = np.clip(
                np.asarray(t["threshold_bin"]), 0, self.edges.shape[1] - 1
            )
            k = len(feat)
            trees.append(
                {
                    "feature": safe_feat,
                    "threshold": np.where(
                        leaf, np.inf, self.edges[safe_feat, thr_bin]
                    ),
                    "left": np.where(leaf, np.arange(k), t["left"]),
                    "right": np.where(leaf, np.arange(k), t["right"]),
                    "leaf": leaf,
                    "predict": np.asarray(t["prediction"], np.float64),
                }
            )
        mf.write_tree_ensemble(
            path, self._mllib_class, trees,
            tree_weights=self._export_tree_weights(len(trees)),
        )

    def _export_tree_weights(self, n_trees: int):
        return [1.0] * n_trees

    def save(self, path: str) -> None:
        from ..io import modelfiles

        path = self._strip_prefix(path)
        if self.config.get("config_model_format") == "mllib":
            # query-level reverse migration (see linear.py save) —
            # checked BEFORE the imported-model guard: with the
            # explicit format key, re-saving an imported directory is
            # exactly what the user asked for (export_mllib_dir
            # handles the imported case verbatim)
            modelfiles.delete_local_dir_target(path)
            self.export_mllib_dir(path)
            return
        if self._mllib is not None:
            # re-exporting an imported directory is an explicit
            # operation, not a silent format change under the native
            # save path
            raise ValueError(
                "this model was loaded from an MLlib model directory; "
                "re-export it with export_mllib_dir(path)"
            )
        modelfiles.delete_local_dir_target(path)
        payload = {
            "kind": self.__class__.__name__,
            "params": self._params,
            "config": self.config,
            "edges": self.edges,
            "n_trees": len(self.trees),
        }
        flat = {}
        for i, t in enumerate(self.trees):
            for k, v in t.items():
                flat[f"tree{i}_{k}"] = v
        buf = io.BytesIO()
        np.savez(
            buf,
            meta=json.dumps(
                {k: v for k, v in payload.items() if k not in ("edges",)}
            ),
            edges=payload["edges"],
            **flat,
        )
        fname = path if path.endswith(".npz") else path + ".npz"
        modelfiles.write_model_bytes(fname, buf.getvalue())

    def load(self, path: str) -> None:
        from ..io import mllib_format, modelfiles

        path = self._strip_prefix(path)
        if mllib_format.is_model_dir(path):
            # a reference-deployment artifact (the same directory
            # DecisionTreeClassifier.java:163-165 hands to
            # DecisionTreeModel.load)
            ens = mllib_format.read_tree_ensemble(path)
            if ens.model_class != self._mllib_class:
                raise ValueError(
                    f"model dir at {path} holds {ens.model_class}, but "
                    f"{self.__class__.__name__} loads {self._mllib_class}"
                )
            self._mllib = ens
            self.trees = []
            self.edges = None
            self._device_pack = None
            return
        self._mllib = None
        fname = path if path.endswith(".npz") else path + ".npz"
        data = np.load(
            io.BytesIO(modelfiles.read_model_bytes(fname)),
            allow_pickle=False,
        )
        meta = json.loads(str(data["meta"]))
        if meta["kind"] != self.__class__.__name__:
            raise ValueError(
                f"model at {path} was saved by {meta['kind']}, "
                f"not {self.__class__.__name__}"
            )
        self._params = meta["params"]
        self.config = meta["config"]
        self.edges = data["edges"]
        self._device_pack = None
        self.trees = [
            {
                k: data[f"tree{i}_{k}"]
                for k in ("feature", "threshold_bin", "left", "right", "prediction")
            }
            for i in range(meta["n_trees"])
        ]


class RandomForestClassifier(DecisionTreeClassifier):
    # the reference's custom-config gate requires these six keys, with
    # the subset strategy under 'config_feature_subset'
    # (RandomForestClassifier.java:106-111)
    required_keys = DecisionTreeClassifier.required_keys + (
        "config_num_trees",
        "config_feature_subset",
    )
    _mllib_class = "org.apache.spark.mllib.tree.model.RandomForestModel"

    def _n_trees(self) -> int:
        c = self.config
        if all(k in c for k in self.required_keys):
            return int(c["config_num_trees"])
        return 100  # RandomForestClassifier.java:132-135

    def _feature_subset(self, d: int) -> Optional[int]:
        c = self.config
        strategy = (
            c["config_feature_subset"]
            if all(k in c for k in self.required_keys)
            else "auto"
        )
        # MLlib 1.6 RandomForest.selectFeatures semantics: 'auto' means
        # 'all' for a single tree and sqrt for classification forests;
        # sqrt/log2/onethird use ceil; unknown strategies throw.
        if strategy == "auto":
            strategy = "all" if self._n_trees() == 1 else "sqrt"
        if strategy == "all":
            return None
        if strategy == "sqrt":
            return max(1, int(np.ceil(np.sqrt(d))))
        if strategy == "log2":
            return max(1, int(np.ceil(np.log2(d))))
        if strategy == "onethird":
            return max(1, int(np.ceil(d / 3.0)))
        raise ValueError(f"unsupported feature subset strategy: {strategy}")


def _grow_regression_tree(
    binned: np.ndarray,
    residuals: np.ndarray,
    max_bins: int,
    max_depth: int,
    min_instances: int,
) -> _Tree:
    """Variance-reduction CART on binned features for GBT residuals.

    Same vectorized-histogram shape as ``_grow_tree``, but the per-bin
    statistics are (count, sum r, sum r^2) and leaves predict the mean
    residual.
    """
    n, d = binned.shape
    tree = _Tree()
    root = tree.add_node()
    active = {root: np.arange(n)}

    for _depth in range(max_depth):
        if not active:
            break
        next_active: Dict[int, np.ndarray] = {}
        for node_id, idx in active.items():
            r = residuals[idx]
            tree.prediction[node_id] = float(r.mean())
            if len(idx) < 2 * min_instances:
                continue
            sub = binned[idx]  # (m, d)
            m = len(idx)
            flat = np.arange(d)[None, :] * max_bins + sub
            cnt = np.bincount(flat.ravel(), minlength=d * max_bins).reshape(
                d, max_bins
            )
            s1 = np.bincount(
                flat.ravel(), weights=np.repeat(r, d), minlength=d * max_bins
            ).reshape(d, max_bins)
            c_cnt, c_s1 = cnt.cumsum(axis=1), s1.cumsum(axis=1)
            nl = c_cnt[:, :-1]
            nr = m - nl
            sl = c_s1[:, :-1]
            sr = c_s1[:, -1:] - sl
            # SSE reduction: parent SSE - (left SSE + right SSE); the
            # sum-of-squares terms cancel, leaving the mean terms
            with np.errstate(divide="ignore", invalid="ignore"):
                score = sl**2 / np.maximum(nl, _EPS) + sr**2 / np.maximum(
                    nr, _EPS
                )
            valid = (nl >= min_instances) & (nr >= min_instances)
            score = np.where(valid, score, -np.inf)
            bf, bb = divmod(int(np.argmax(score)), max_bins - 1)
            if not np.isfinite(score[bf, bb]):
                continue
            parent_score = c_s1[bf, -1] ** 2 / m
            if score[bf, bb] <= parent_score + 1e-12:
                continue  # no variance reduction
            go_left = binned[idx, bf] <= bb
            li, ri = tree.add_node(), tree.add_node()
            tree.feature[node_id] = int(bf)
            tree.threshold_bin[node_id] = int(bb)
            tree.left[node_id] = li
            tree.right[node_id] = ri
            next_active[li] = idx[go_left]
            next_active[ri] = idx[~go_left]
        active = next_active

    for node_id, idx in active.items():
        tree.prediction[node_id] = float(residuals[idx].mean())
    return tree


class GradientBoostedTreesClassifier(DecisionTreeClassifier):
    """Gradient-boosted trees with logistic loss.

    The reference's test suite exercises a ``GradientBoostedTreesClassifier``
    (MLlib ``GradientBoostedTrees``) that was removed from its main
    tree (ClassifierTest.java:213, commented out) — restored here as a
    first-class registry entry (``train_clf=gbt``). Defaults follow
    MLlib 1.6 ``BoostingStrategy.defaultParams("Classification")``:
    100 iterations, learning rate 0.1, depth-3 trees, LogLoss.

    Boosting: F_0 = 0; per round fit a variance-reduction regression
    tree to the logistic residual ``y - sigmoid(F)`` and update
    ``F += lr * tree(x)``. Prediction: ``sigmoid(F) >= 0.5``.
    """

    required_keys = (
        "config_num_iterations",
        "config_learning_rate",
        "config_max_depth",
    )
    _mllib_class = (
        "org.apache.spark.mllib.tree.model.GradientBoostedTreesModel"
    )

    def _export_tree_weights(self, n_trees: int):
        # our boosting applies the learning rate to EVERY round
        # (F = sum lr * t_i, fit()); MLlib's Sum combining computes
        # sum(w_i * t_i), so uniform lr weights reproduce F. The only
        # semantic daylight vs this class's predict is the F == 0
        # boundary (MLlib: > 0 -> 1; here: >= 0 -> 1).
        lr = float(self._params.get("learning_rate", 0.1))
        return [lr] * n_trees

    def _boost_params(self) -> Dict:
        c = self.config
        if all(k in c for k in self.required_keys):
            return {
                "num_iterations": int(c["config_num_iterations"]),
                "learning_rate": float(c["config_learning_rate"]),
                "max_depth": int(c["config_max_depth"]),
            }
        return {"num_iterations": 100, "learning_rate": 0.1, "max_depth": 3}

    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        p = self._boost_params()
        bp = {"max_bins": 32, "min_instances": 1}
        self._params = {**p, **bp}
        self._device_pack = None
        self._mllib = None  # training replaces any imported model
        y = np.floor(np.asarray(labels, dtype=np.float64) + 0.5)
        self.edges = compute_bin_edges(features, bp["max_bins"])
        binned = bin_features(features, self.edges)
        if self._resolved_backend() == "device":
            self._fit_device_boost(binned, y, p, bp)
            return
        F = np.zeros(len(y), dtype=np.float64)
        self.trees = []
        for _round in range(p["num_iterations"]):
            residual = y - 1.0 / (1.0 + np.exp(-F))
            tree = _grow_regression_tree(
                binned, residual, bp["max_bins"], p["max_depth"],
                bp["min_instances"],
            )
            arrays = tree.to_arrays()
            self.trees.append(arrays)
            F += p["learning_rate"] * _predict_tree(arrays, binned)

    def _fit_device_boost(self, binned, y, p: Dict, bp: Dict) -> None:
        """gbt-tpu: the whole boosting loop as one XLA program
        (trees_device.boost_gbt — a lax.scan over rounds, each round
        one matmul-histogram regression tree), versus MLlib's
        one-Spark-job-per-round shape. Trees come back through
        ``heap_to_host_arrays`` so prediction and persistence share
        the host format."""
        import jax.numpy as jnp

        from . import trees_device

        trees_device._check_device_depth(p["max_depth"])
        heaps = trees_device.boost_gbt(
            jnp.asarray(binned, jnp.int32),
            jnp.asarray(y, jnp.float32),
            rounds=p["num_iterations"],
            learning_rate=p["learning_rate"],
            max_bins=bp["max_bins"],
            max_depth=p["max_depth"],
            min_instances=bp["min_instances"],
        )
        self.trees = trees_device.heap_to_host_arrays(heaps)

    def predict(self, features: np.ndarray) -> np.ndarray:
        if self._mllib is not None:
            return self._mllib.predict(features)
        if not self.trees or self.edges is None:
            raise ValueError("model not trained or loaded")
        binned = bin_features(np.asarray(features, dtype=np.float64), self.edges)
        lr = self._params.get("learning_rate", 0.1)
        F = np.zeros(binned.shape[0], dtype=np.float64)
        for t in self.trees:
            F += lr * _predict_tree(t, binned)
        return (F >= 0.0).astype(np.float64)
