"""Population training: folds x seeds x hyperparameter grid, one program.

The reference pipeline (and our ``train_clf=`` path) evaluates one
model on one 70/30 split. The comparisons the paper's line of work
actually runs — wavelet-NN classifiers (arXiv:1307.7897), DWT-feature
seizure prediction (arXiv:2102.01647) — hinge on training *many*
variants over the same 48-dim feature rows: cross-validation folds,
seed ensembles, hyperparameter sweeps. This module is that workload's
front end: a **population** is the cartesian expansion of

    cross-validation folds (``cv=k``, k-fold or Monte-Carlo)
  x init/sampling seeds   (``seeds=m`` — base seed, base+1, ...)
  x a hyperparameter grid (``sweep=lr:0.1,0.03;reg:0.0,0.01``)

trained by the stacked engines in ``parallel/population.py`` (one
compile + one dispatch for all P members, ``jax.vmap`` over the member
axis) or by the looped sequential twin (``population_mode=looped`` —
the bench baseline and the fallback for members vmap cannot express).

Fold semantics: ``cv=1`` IS the reference's seed-1 shuffle + 70/30
split (not a degenerate 1-fold), so ``cv=1&seeds=1`` with no sweep
reproduces the plain ``train_clf=`` run exactly. ``cv=k`` k-folds the
seed-1 shuffled order into contiguous test blocks; ``cv_mode=mc``
draws k independent shuffle+70/30 splits from seeds 1..k (seed 1
first, so fold 0 is again the plain split).

Per-member statistics come from the same ``test_features`` path the
sequential runs use; ``models.stats.PopulationStatistics`` carries the
per-member table plus the cross-member summary (best member, mean/std
accuracy) that the run report and ``result_path`` embed.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import stats
from ..utils import java_compat

logger = logging.getLogger(__name__)

#: classifier names whose training is an SGD-family iteration scan —
#: the ones the population engines can stack onto a member axis.
#: Tree growers / oracles keep the sequential path (pipeline/builder).
SGD_FAMILY = ("logreg", "svm", "nn")

#: sweep axes the grammar accepts (lr = step size / learning rate,
#: reg = L2 regularization — linear family only; cost_fp/cost_fn =
#: cost-sensitive class weights, the seizure workload's sweep —
#: cost_fn weights the positive class, cost_fp the negative)
_SWEEP_AXES = ("lr", "reg", "cost_fp", "cost_fn")

_QUERY_KEYS = (
    "cv", "cv_mode", "seeds", "sweep", "population_mode", "fe_sweep"
)


def parse_sweep(spec: str) -> Tuple[Tuple[str, Tuple[float, ...]], ...]:
    """``lr:0.1,0.03;reg:0.0,0.01`` -> (("lr", (0.1, 0.03)), ...).

    Axis order is the spec's order; duplicate axes and unknown axis
    names are errors (a typo'd axis silently training the wrong grid
    is the worst outcome).
    """
    axes: List[Tuple[str, Tuple[float, ...]]] = []
    seen = set()
    for part in spec.split(";"):
        if not part:
            continue
        name, sep, values = part.partition(":")
        name = name.strip()
        if not sep or name not in _SWEEP_AXES:
            raise ValueError(
                f"sweep= axis must be one of {'/'.join(_SWEEP_AXES)} "
                f"(axis:v1,v2;...), got {part!r}"
            )
        if name in seen:
            raise ValueError(f"sweep= axis {name!r} given twice")
        seen.add(name)
        try:
            vals = tuple(float(v) for v in values.split(",") if v != "")
        except ValueError:
            raise ValueError(
                f"sweep= axis {name!r} has a non-numeric value in "
                f"{values!r}"
            )
        if not vals:
            raise ValueError(f"sweep= axis {name!r} has no values")
        if len(set(vals)) != len(vals):
            # duplicate grid points would train the same member twice
            # and collide on the member label (last silently wins)
            raise ValueError(
                f"sweep= axis {name!r} repeats a value: {values!r}"
            )
        axes.append((name, vals))
    return tuple(axes)


@dataclasses.dataclass(frozen=True)
class PopulationSpec:
    """The population axes one pipeline run requested."""

    cv: int = 1
    cv_mode: str = "kfold"  # "kfold" | "mc" (Monte-Carlo splits)
    seeds: int = 1
    sweep: Tuple[Tuple[str, Tuple[float, ...]], ...] = ()
    mode: str = "vmap"  # "vmap" | "looped"
    #: feature-config comparison axis (``fe_sweep=cfg1|cfg2`` — full
    #: fe= grammar strings): every member trains against its config's
    #: feature matrix, stacked onto the vmapped program's member axis
    #: (parallel/population.py ``stacked_features``). Seizure
    #: workload, linear family only (docs/workloads.md).
    fe_configs: Tuple[str, ...] = ()

    @classmethod
    def from_query_map(cls, query_map: Dict[str, str]) -> "PopulationSpec":
        def _int(name, default):
            value = query_map.get(name, "")
            if not value:
                return default
            try:
                return int(value)
            except ValueError:
                raise ValueError(
                    f"query parameter {name}= must be an integer, "
                    f"got {value!r}"
                )

        spec = cls(
            cv=_int("cv", 1),
            cv_mode=query_map.get("cv_mode", "") or "kfold",
            seeds=_int("seeds", 1),
            sweep=parse_sweep(query_map.get("sweep", "")),
            mode=query_map.get("population_mode", "") or "vmap",
            # the builder normalizes fe_sweep= to its raw value (the
            # configs' level=/stats= '='s survive the query map's
            # second-'=' truncation quirk)
            fe_configs=tuple(
                s for s in query_map.get("fe_sweep", "").split("|") if s
            ),
        )
        if len(set(spec.fe_configs)) != len(spec.fe_configs):
            raise ValueError(
                "fe_sweep= repeats a feature config; duplicate members "
                "would train the same model twice"
            )
        if spec.cv < 1:
            raise ValueError("cv= must be >= 1")
        if spec.seeds < 1:
            raise ValueError("seeds= must be >= 1")
        if spec.cv_mode not in ("kfold", "mc"):
            raise ValueError(
                f"cv_mode= must be kfold or mc, got {spec.cv_mode!r}"
            )
        if spec.mode not in ("vmap", "looped"):
            raise ValueError(
                f"population_mode= must be vmap or looped, "
                f"got {spec.mode!r}"
            )
        return spec

    @property
    def active(self) -> bool:
        """True when the run asked for more than the plain split's
        single model — the builder routes SGD-family training through
        the population engine iff this holds."""
        return (
            self.cv > 1 or self.seeds > 1 or bool(self.sweep)
            or bool(self.fe_configs)
        )

    def axis_values(self, axis: str) -> Optional[Tuple[float, ...]]:
        for name, values in self.sweep:
            if name == axis:
                return values
        return None

    def grid_points(self) -> int:
        points = 1
        for _, values in self.sweep:
            points *= len(values)
        return points

    def describe(self) -> Dict:
        out = {
            "folds": self.cv,
            "cv_mode": self.cv_mode if self.cv > 1 else "plain_split",
            "seeds": self.seeds,
            "grid": {name: list(values) for name, values in self.sweep},
            "grid_points": self.grid_points(),
        }
        if self.fe_configs:
            out["fe_configs"] = list(self.fe_configs)
        return out


def folds_for(spec: PopulationSpec, n: int) -> List[Tuple[List[int], List[int]]]:
    """(train_idx, test_idx) per fold, indices into original row order.

    ``cv=1``: the reference's seed-1 shuffle + 70/30 split — the plain
    ``train_clf=`` fold. ``kfold``: contiguous test blocks over the
    seed-1 shuffled permutation (every row tests exactly once).
    ``mc``: ``cv`` independent shuffle+70/30 splits, seeds 1..cv.
    """
    def _as_fold(train, test):
        # int arrays, not lists: population features may be a shared
        # device buffer (the fan-out's one-transfer satellite), and
        # jnp rejects list indexing
        return (
            np.asarray(train, dtype=np.int64),
            np.asarray(test, dtype=np.int64),
        )

    if spec.cv <= 1:
        return [_as_fold(*java_compat.train_test_split_indices(n, seed=1))]
    if spec.cv > n:
        raise ValueError(f"cv={spec.cv} exceeds the {n} available rows")
    if spec.cv_mode == "mc":
        return [
            _as_fold(*java_compat.train_test_split_indices(n, seed=1 + i))
            for i in range(spec.cv)
        ]
    perm = java_compat.java_shuffle_indices(n, seed=1)
    k = spec.cv
    bounds = [i * n // k for i in range(k + 1)]
    return [
        _as_fold(
            perm[: bounds[i]] + perm[bounds[i + 1]:],
            perm[bounds[i]: bounds[i + 1]],
        )
        for i in range(k)
    ]


@dataclasses.dataclass(frozen=True)
class Member:
    """One population member: a fold, a seed, and grid overrides
    (None = the classifier config's base value). ``fe`` indexes the
    spec's ``fe_configs`` when a feature-config axis rides along."""

    fold: int
    seed: int
    lr: Optional[float] = None
    reg: Optional[float] = None
    cost_fp: Optional[float] = None
    cost_fn: Optional[float] = None
    fe: Optional[int] = None

    @property
    def label(self) -> str:
        out = f"f{self.fold}.s{self.seed}"
        if self.fe is not None:
            out = f"fe{self.fe}." + out
        if self.lr is not None:
            out += f".lr{self.lr:g}"
        if self.reg is not None:
            out += f".reg{self.reg:g}"
        if self.cost_fp is not None:
            out += f".cfp{self.cost_fp:g}"
        if self.cost_fn is not None:
            out += f".cfn{self.cost_fn:g}"
        return out


def expand_members(
    spec: PopulationSpec,
    n_folds: int,
    base_seed: int,
    supports_reg: bool,
    name: str = "",
    supports_cost: bool = True,
) -> List[Member]:
    """The cartesian member list, feature-config-major, then fold,
    then seed, then grid — the order every engine and every report
    preserves. Axes a family cannot express collapse with a log line
    (the NN has no L2 ``reg`` hyperparameter and its loss closure
    bakes the class weights, so per-member cost axes cannot batch;
    duplicating its members per point would train the same model
    twice and report it as two)."""
    lrs: Sequence[Optional[float]] = spec.axis_values("lr") or (None,)
    regs: Sequence[Optional[float]] = spec.axis_values("reg") or (None,)
    cfps: Sequence[Optional[float]] = spec.axis_values("cost_fp") or (None,)
    cfns: Sequence[Optional[float]] = spec.axis_values("cost_fn") or (None,)
    if not supports_reg and spec.axis_values("reg") is not None:
        logger.warning(
            "sweep axis reg does not apply to %s; collapsing %d grid "
            "points onto the base config", name, len(regs),
        )
        regs = (None,)
    if not supports_cost and (
        spec.axis_values("cost_fp") is not None
        or spec.axis_values("cost_fn") is not None
    ):
        logger.warning(
            "sweep axes cost_fp/cost_fn do not apply to %s; collapsing "
            "%d grid points onto the base config",
            name, len(cfps) * len(cfns),
        )
        cfps = cfns = (None,)
    fes: Sequence[Optional[int]] = (
        tuple(range(len(spec.fe_configs))) if spec.fe_configs else (None,)
    )
    return [
        Member(
            fold=f, seed=base_seed + s, lr=lr, reg=reg,
            cost_fp=cfp, cost_fn=cfn, fe=fe,
        )
        for fe in fes
        for f in range(n_folds)
        for s in range(spec.seeds)
        for lr in lrs
        for reg in regs
        for cfp in cfps
        for cfn in cfns
    ]


def _fold_masks(
    members: Sequence[Member],
    folds: Sequence[Tuple[List[int], List[int]]],
    n: int,
) -> np.ndarray:
    """(P, n) float32 train-row masks — the multi-fold population's
    uniform-shape formulation (``_run_sgd``'s ``sample_mask`` seam)."""
    masks = np.zeros((len(members), n), dtype=np.float32)
    for i, m in enumerate(members):
        masks[i, folds[m.fold][0]] = 1.0
    return masks


def _null_stage(_name, **_attrs):
    return contextlib.nullcontext()


def member_mesh_axis(mesh):
    """The mesh axis (or axes) the member axis shards over: on a pod's
    hybrid mesh (a ``hosts`` DCN axis outermost —
    parallel/distributed.hybrid_mesh) EVERY axis, hosts first, so the
    members span every device of every host; on a single-host mesh,
    ``data`` when present (the population IS data parallelism over
    members), else the mesh's first axis — one rule shared by the
    engine dispatch and the telemetry so they can never disagree.
    Returns a string for one axis, a tuple for several."""
    from ..parallel import distributed, mesh as pmesh

    if distributed.DCN_AXIS in mesh.axis_names:
        return (distributed.DCN_AXIS,) + tuple(
            a for a in mesh.axis_names if a != distributed.DCN_AXIS
        )
    return (
        pmesh.DATA_AXIS
        if pmesh.DATA_AXIS in mesh.axis_names
        else mesh.axis_names[0]
    )


def run_population(
    name: str,
    make_classifier: Callable,
    config: Dict[str, str],
    features,
    targets,
    spec: PopulationSpec,
    stage: Optional[Callable] = None,
    feature_sets: Optional[Sequence[Tuple[str, np.ndarray]]] = None,
    mesh=None,
) -> Tuple[stats.PopulationStatistics, Dict]:
    """Train + evaluate one classifier family's population.

    Returns ``(PopulationStatistics, telemetry block)`` — the block is
    what the run report embeds under ``population`` (member count,
    axes shape, mode actually used, compiles recorded during training,
    the per-member accuracy table).

    ``stage`` is the pipeline builder's ``_stage`` context factory so
    train/test wall time lands in the same StageTimer rows (and the
    same ``stage.train``/``stage.test`` spans) the sequential paths
    use; defaults to a no-op for library callers.

    ``mesh`` (a ``jax.sharding.Mesh``) shards the MEMBER axis over the
    mesh's data axis for linear-family vmap-mode populations
    (``parallel/population.train_linear_population_sharded`` — members
    padded to a mesh multiple with inert zero-mask members), so the
    population trains on every device of the mesh. Any sharded-engine
    failure degrades to the single-device vmapped engine — recorded in
    the block's ``mesh`` sub-block (``rung``/``error``), counted as
    ``population.mesh_fallback`` — and NN populations always train
    single-device (logged; the NN engine has no sharded formulation).

    ``feature_sets`` carries the ``fe_sweep=`` axis: ordered
    ``(config label, (n, d) feature matrix)`` pairs, one per entry in
    ``spec.fe_configs``, all over the SAME rows (identical targets).
    Each member then trains and tests against its config's matrix —
    stacked onto the vmapped program's member axis, so ≥2 feature
    pipelines compare inside one compiled program. Linear family
    only (the NN engine shares one gathered train matrix).
    """
    from .. import obs
    from ..obs import events
    from ..obs.report import CompilationMonitor
    from ..parallel.population import PopulationVmapUnsupported

    if name not in SGD_FAMILY:
        raise ValueError(
            f"population training supports the SGD family "
            f"({', '.join(SGD_FAMILY)}); {name!r} trains one model "
            f"per run"
        )
    linear = name in ("logreg", "svm")
    if spec.fe_configs and not linear:
        raise ValueError(
            "fe_sweep= applies to the linear family (logreg/svm); the "
            f"{name} engine shares one feature matrix"
        )
    if spec.fe_configs:
        if feature_sets is None or len(feature_sets) != len(spec.fe_configs):
            raise ValueError(
                f"fe_sweep= lists {len(spec.fe_configs)} configs but "
                f"{0 if feature_sets is None else len(feature_sets)} "
                f"feature matrices were provided"
            )
        shapes = {np.asarray(f).shape for _, f in feature_sets}
        if len(shapes) != 1:
            raise ValueError(
                f"fe_sweep= feature configs must agree on the feature "
                f"matrix shape to share one stacked program; got "
                f"{sorted(shapes)} — match the level=/stats= sets"
            )
    stage = stage or _null_stage
    targets = np.asarray(targets, dtype=np.float64)
    n = len(targets)
    folds = folds_for(spec, n)

    template = make_classifier()
    template.set_config(config)
    if linear:
        base_cfg = template._sgd_config()
        base_seed = base_cfg.seed
    else:
        base_cfg = None
        base_seed = int(template._require("config_seed"))
    members = expand_members(
        spec, len(folds), base_seed, supports_reg=linear, name=name,
        supports_cost=linear,
    )
    if linear and spec.seeds > 1 and base_cfg.mini_batch_fraction >= 1.0:
        # zero-init full-batch SGD has no randomness: the seed only
        # keys the Bernoulli minibatch sampler, so these seed members
        # train identical models. Kept (the user asked for the axis,
        # and the report shows the duplication honestly) but flagged.
        logger.warning(
            "seeds=%d is inert for full-batch %s (zero init, "
            "mini_batch_fraction>=1): seed members will be identical; "
            "set config_mini_batch_fraction<1 for a live seed axis",
            spec.seeds, name,
        )
        obs.metrics.count("population.degenerate_seed_axis")

    mode_used = spec.mode
    mesh_block = None
    n_shards = 1
    if mesh is not None:
        axis = member_mesh_axis(mesh)
        axis_names = (axis,) if isinstance(axis, str) else tuple(axis)
        for a in axis_names:
            n_shards *= int(mesh.shape[a])
        mesh_block = {
            "rung": "single_device",
            # one axis renders as itself; the pod's multi-axis member
            # spec renders joined ("hosts,data") — JSON-stable either way
            "axis": axis if isinstance(axis, str) else ",".join(axis),
            "shape": {k: int(v) for k, v in mesh.shape.items()},
            "devices": int(mesh.devices.size),
        }
        if not linear:
            logger.warning(
                "population mesh sharding applies to the linear family "
                "(logreg/svm); %s trains single-device", name,
            )
            obs.metrics.count("population.mesh_unsupported_family")
        elif spec.mode != "vmap":
            # the looped twin is the bench baseline — sharding it
            # would measure the mesh, not the engine
            logger.warning(
                "population_mode=looped trains single-device; the mesh "
                "applies to the vmapped engine"
            )
    comp = CompilationMonitor()
    with comp, stage("train", classifier=name, population=len(members)), \
            events.span(
                f"population.{name}", classifier=name,
                members=len(members), mode=spec.mode,
            ):
        trained = None
        if (
            mesh is not None and linear and spec.mode == "vmap"
        ):
            try:
                trained = _train_sharded(
                    template, features, targets, folds, members,
                    base_cfg, mesh, feature_sets=feature_sets,
                )
                mode_used = "sharded"
                from ..parallel import population as engines

                padded = engines.pad_members(len(members), n_shards)
                mesh_block.update(
                    rung="mesh",
                    members_per_device=padded // n_shards,
                    padded_members=padded - len(members),
                )
                obs.metrics.count("population.sharded_members",
                                  len(members))
            except Exception as e:  # mesh rung -> single-device rung
                evidence = f"{type(e).__name__}: {e}"
                logger.warning(
                    "population %s mesh training failed; degrading to "
                    "the single-device engine: %s", name, evidence,
                )
                obs.metrics.count("population.mesh_fallback")
                events.event("population.mesh_fallback", error=evidence)
                mesh_block["error"] = evidence
                trained = None
        if trained is None and spec.mode == "vmap":
            try:
                trained = _train_vmapped(
                    name, template, features, targets, folds, members,
                    base_cfg, feature_sets=feature_sets,
                )
            except PopulationVmapUnsupported as e:
                logger.warning(
                    "population %s falls back to looped training: %s",
                    name, e,
                )
                obs.metrics.count("population.fallback_looped")
                mode_used = "looped"
                trained = _train_looped(
                    name, make_classifier, config, features, targets,
                    folds, members, base_cfg, template,
                    feature_sets=feature_sets,
                )
        elif trained is None:
            trained = _train_looped(
                name, make_classifier, config, features, targets,
                folds, members, base_cfg, template,
                feature_sets=feature_sets,
            )
    obs.metrics.count("population.members", len(members))
    obs.metrics.count(f"population.{mode_used}")

    def member_features(m):
        """The rows this member trains/tests against: its fe_sweep
        config's matrix when the feature axis rides, else the shared
        one."""
        if m.fe is None or feature_sets is None:
            return features
        return feature_sets[m.fe][1]

    result = stats.PopulationStatistics(
        shape=spec.describe(), mode=mode_used
    )
    with stage("test", classifier=name, population=len(members)):
        for m, state in zip(members, trained):
            if linear:
                template.weights = state
                template.intercept = 0.0
                template.margin_threshold = 0.0
            else:
                template.params = state
            _, test_idx = folds[m.fold]
            with events.span(
                "population.member", classifier=name, member=m.label,
                fold=m.fold, seed=m.seed,
            ):
                member_stats = template.test_features(
                    member_features(m)[test_idx], targets[test_idx]
                )
            result[m.label] = member_stats

    snapshot = comp.snapshot()
    block = {
        "classifier": name,
        "members": len(members),
        "mode": mode_used,
        "requested_mode": spec.mode,
        "mesh": mesh_block,
        "shape": spec.describe(),
        "compiles": (
            snapshot["compilations"] if snapshot["available"] else None
        ),
        "accuracy": {
            label: round(s.calc_accuracy(), 6)
            for label, s in result.items()
        },
        "summary": result.summary(),
    }
    return result, block


def _member_axes(members, base_cfg):
    """The linear family's per-member hyperparameter arrays: steps,
    regs, seeds, and the cost-sensitive class weights (cost_fn
    weights the positive class, cost_fp the negative — the expected-
    cost convention in models/stats.py). Shared by the vmapped and
    looped engines so the member order and value resolution can never
    drift between them."""
    return (
        [m.lr if m.lr is not None else base_cfg.step_size
         for m in members],
        [m.reg if m.reg is not None else base_cfg.reg_param
         for m in members],
        [m.seed for m in members],
        [m.cost_fn if m.cost_fn is not None else base_cfg.weight_pos
         for m in members],
        [m.cost_fp if m.cost_fp is not None else base_cfg.weight_neg
         for m in members],
    )


def _stacked_features(members, feature_sets, row_idx=None):
    """(P, n, d) float32 member-axis feature stack for an fe_sweep
    population: member i's matrix is its config's, gathered to
    ``row_idx`` (the shared single-fold train rows) when given."""
    mats = []
    for m in members:
        f = np.asarray(feature_sets[m.fe][1], dtype=np.float32)
        mats.append(f if row_idx is None else f[row_idx])
    return np.stack(mats)


def _train_vmapped(
    name, template, features, targets, folds, members, base_cfg,
    feature_sets=None,
) -> List:
    """All members in one stacked program (parallel/population.py)."""
    from ..parallel import population as engines
    from ..parallel.population import PopulationVmapUnsupported

    if name in ("logreg", "svm"):
        steps, regs, seeds, wpos, wneg = _member_axes(members, base_cfg)
        stacked = feature_sets is not None and any(
            m.fe is not None for m in members
        )
        if len(folds) == 1:
            # single-fold: gather the shared train rows once — the
            # member invocation is then byte-for-byte the train_clf=
            # invocation, just batched
            train_idx = folds[0][0]
            x = (
                _stacked_features(members, feature_sets, train_idx)
                if stacked
                else np.asarray(features)[train_idx]
            )
            weights = engines.train_linear_population(
                x, targets[train_idx],
                base_cfg, steps, regs, seeds, masks=None,
                weight_pos=wpos, weight_neg=wneg,
                stacked_features=stacked,
            )
        else:
            masks = _fold_masks(members, folds, len(targets))
            x = (
                _stacked_features(members, feature_sets)
                if stacked
                else features
            )
            weights = engines.train_linear_population(
                x, targets, base_cfg, steps, regs, seeds,
                masks=masks, weight_pos=wpos, weight_neg=wneg,
                stacked_features=stacked,
            )
        return list(weights)

    # nn: the vmapped engine batches seeds x learning rates over ONE
    # fold's gathered rows; a multi-fold NN population would need a
    # masked loss, which the sequential fit has no equivalent of
    if len(folds) > 1:
        raise PopulationVmapUnsupported(
            "multi-fold NN populations train looped (the vmapped NN "
            "engine shares one gathered train matrix)"
        )
    train_idx = folds[0][0]
    lrs = [
        m.lr if m.lr is not None
        else float(template._require("config_learning_rate"))
        for m in members
    ]
    return template.population_fit(
        np.asarray(features)[train_idx], targets[train_idx],
        [m.seed for m in members], lrs,
    )


def _train_sharded(
    template, features, targets, folds, members, base_cfg, mesh,
    feature_sets=None,
) -> List:
    """The linear family's member set over a device mesh: the SAME
    fold/feature dispatch as :func:`_train_vmapped`, handed to
    ``train_linear_population_sharded`` so the per-member invocation
    (and therefore the statistics contract) cannot drift between the
    single-device and sharded engines."""
    from ..parallel import population as engines

    axis = member_mesh_axis(mesh)
    steps, regs, seeds, wpos, wneg = _member_axes(members, base_cfg)
    stacked = feature_sets is not None and any(
        m.fe is not None for m in members
    )
    if len(folds) == 1:
        train_idx = folds[0][0]
        x = (
            _stacked_features(members, feature_sets, train_idx)
            if stacked
            else np.asarray(features)[train_idx]
        )
        weights = engines.train_linear_population_sharded(
            x, np.asarray(targets)[train_idx],
            base_cfg, steps, regs, seeds, masks=None, mesh=mesh,
            weight_pos=wpos, weight_neg=wneg,
            stacked_features=stacked, axis=axis,
        )
    else:
        masks = _fold_masks(members, folds, len(targets))
        x = (
            _stacked_features(members, feature_sets)
            if stacked
            else features
        )
        weights = engines.train_linear_population_sharded(
            x, targets, base_cfg, steps, regs, seeds,
            masks=masks, mesh=mesh,
            weight_pos=wpos, weight_neg=wneg,
            stacked_features=stacked, axis=axis,
        )
    return list(weights)


def _train_looped(
    name, make_classifier, config, features, targets, folds, members,
    base_cfg, template=None, feature_sets=None,
) -> List:
    """The sequential twin: per member, the same training program the
    vmapped engine batches, dispatched one member at a time — the
    bench's ``population_looped`` baseline and the vmap-unsupported
    fallback. Single-fold linear members are exactly the
    ``train_clf=`` invocation (gathered train rows); multi-fold
    linear members run the mask formulation through
    ``train_linear_population_looped`` so minibatch sample streams
    (which key off the mask's row count) match the vmapped engine
    member for member — gathering per fold here would draw different
    Bernoulli masks and break the vmap==looped parity contract
    whenever ``mini_batch_fraction < 1``."""
    import dataclasses as dc

    from . import sgd
    from ..parallel import population as engines

    trained = []
    linear = name in ("logreg", "svm")
    stacked = (
        linear and feature_sets is not None
        and any(m.fe is not None for m in members)
    )
    if linear and (len(folds) > 1 or stacked):
        # the mask/stacked formulation through the looped engine: the
        # per-member invocation (and therefore the Bernoulli sample
        # stream and the weighted static) matches the vmapped engine
        # member for member — the parity contract
        steps, regs, seeds, wpos, wneg = _member_axes(members, base_cfg)
        if len(folds) > 1:
            masks = _fold_masks(members, folds, len(targets))
            x = (
                _stacked_features(members, feature_sets)
                if stacked else features
            )
            y = targets
        else:
            train_idx = folds[0][0]
            masks = None
            x = _stacked_features(members, feature_sets, train_idx)
            y = targets[train_idx]
        weights = engines.train_linear_population_looped(
            x, y, base_cfg, steps, regs, seeds, masks,
            weight_pos=wpos, weight_neg=wneg, stacked_features=stacked,
        )
        return list(weights)
    for m in members:
        train_idx, _ = folds[m.fold]
        if linear:
            cfg = dc.replace(
                base_cfg,
                step_size=(
                    m.lr if m.lr is not None else base_cfg.step_size
                ),
                reg_param=(
                    m.reg if m.reg is not None else base_cfg.reg_param
                ),
                seed=m.seed,
                weight_pos=(
                    m.cost_fn if m.cost_fn is not None
                    else base_cfg.weight_pos
                ),
                weight_neg=(
                    m.cost_fp if m.cost_fp is not None
                    else base_cfg.weight_neg
                ),
            )
            trained.append(
                sgd.train_linear(
                    np.asarray(features)[train_idx], targets[train_idx],
                    cfg,
                )
            )
        else:
            clf = make_classifier()
            member_config = dict(config)
            member_config["config_seed"] = str(m.seed)
            if m.lr is not None:
                member_config["config_learning_rate"] = repr(m.lr)
            clf.set_config(member_config)
            clf.fit(np.asarray(features)[train_idx], targets[train_idx])
            if template is not None and template._arch is None:
                # the evaluation loop predicts through the template;
                # looped NN training is the one path that never set
                # its arch (population_fit and fit both do)
                template._arch = clf._arch
            trained.append(clf.params)
    return trained
