"""Mini-batch SGD engine with Spark-MLlib-1.6 semantics, as one XLA program.

Replaces MLlib's ``GradientDescent.runMiniBatchSGD`` driver loop
(the training hot loop behind ``LogisticRegressionWithSGD`` /
``SVMWithSGD`` — LogisticRegressionClassifier.java:104-112,
SVMClassifier.java:95-110). Semantics preserved:

- iteration t (1-based) uses step size ``step / sqrt(t)``;
- each iteration samples the dataset Bernoulli(miniBatchFraction) and
  averages gradients over the *sampled count* (Spark seeds its
  per-element XORShift sampler with ``42 + t``; we fold ``t`` into a
  JAX PRNG key — statistically equivalent, not bit-equal, documented);
- logistic gradient: mult = 1/(1+exp(-w.x)) - y, grad = mult * x;
- hinge gradient: y' = 2y-1, grad = -y'x when y'(w.x) < 1;
- both *WithSGD classes use SquaredL2Updater (w scaled by
  (1 - step_t*regParam) before the gradient step); the static
  ``train`` helpers pass regParam 0.0 (logreg) or the user value
  (svm), while the default constructors use 0.01;
- zero initial weights, no intercept (the reference never calls
  setIntercept, and MLlib's default is off);
- an iteration whose sample is empty leaves weights unchanged;
- MLlib's convergence early stop (GradientDescent default
  ``convergenceTol = 0.001``): once two updates have happened, stop
  when ``norm(w_prev - w_cur) < tol * max(norm(w_cur), 1)``. Inside
  the scan this is a carried ``converged`` flag that freezes the
  weights — fixed trip count, same result, XLA-friendly.

``models/mllib_oracle.py`` is the float64 host oracle for the
deterministic full-batch path; tests assert this engine agrees with
it on the reference fixture.

The whole loop is a ``lax.scan`` inside one jit — no per-iteration
host round trips (the reference pays a driver->executor treeAggregate
round trip per iteration). When inputs are sharded over a mesh's data
axis, XLA turns the gradient reductions into ICI all-reduces
automatically; see ``parallel/``.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SGDConfig:
    num_iterations: int = 100
    step_size: float = 1.0
    mini_batch_fraction: float = 1.0
    reg_param: float = 0.0  # SquaredL2Updater when > 0 path used (svm)
    loss: str = "logistic"  # "logistic" | "hinge"
    seed: int = 42
    # MLlib GradientDescent default; 0.0 disables the early stop
    convergence_tol: float = 0.001
    # cost-sensitive class weights (the seizure workload,
    # docs/workloads.md): each sample's gradient contribution scales
    # by its class's weight — positives by ``weight_pos`` (the
    # false-negative cost), negatives by ``weight_neg`` (the
    # false-positive cost). Both 1.0 (the default) takes a code path
    # with the IDENTICAL XLA program as before the knobs existed
    # (``weighted`` is a static argument), so P300 trajectories are
    # bit-unchanged.
    weight_pos: float = 1.0
    weight_neg: float = 1.0

    @property
    def weighted(self) -> bool:
        return self.weight_pos != 1.0 or self.weight_neg != 1.0


def _make_scan_step(
    x, y, ones, step_size, mini_batch_fraction, reg_param, seed,
    convergence_tol, loss, full_batch, weighted=False,
    weight_pos=1.0, weight_neg=1.0,
):
    """The per-iteration MLlib-SGD scan body, shared by the monolithic
    engine (:func:`_run_sgd`) and the chunked resumable engine
    (:func:`_run_sgd_chunk`) so the two can never drift.

    ``weighted`` is STATIC: False builds the exact pre-cost-knob
    program (bit-identical P300 trajectories); True scales each
    sample's gradient by its class weight (``weight_pos``/
    ``weight_neg`` ride as traced scalars, so a cost sweep never
    recompiles). The gradient average stays over the *sampled count*
    — MLlib's normalization — not the weight sum, so weights shift
    the decision boundary without rescaling the effective step size.
    """
    n = x.shape[0]
    if weighted:
        class_w = y * weight_pos + (1.0 - y) * weight_neg

    def gradient_sum(w, mask):
        margin = x @ w  # (n,)
        if loss == "logistic":
            mult = jax.nn.sigmoid(margin) - y
        else:  # hinge
            y_signed = 2.0 * y - 1.0
            active = (y_signed * margin) < 1.0
            mult = jnp.where(active, -y_signed, 0.0)
        if weighted:
            mult = mult * class_w
        weighted_mult = mult * mask
        return x.T @ weighted_mult  # (d,) — lowers to MXU matmul + all-reduce

    def step(carry, t):
        # t is 1-based iteration index
        w, converged, n_updates = carry
        if full_batch:
            mask = ones
        else:
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            mask = ones * (
                jax.random.uniform(key, (n,), dtype=x.dtype)
                < mini_batch_fraction
            ).astype(x.dtype)
        count = mask.sum()
        g = gradient_sum(w, mask)
        step_t = step_size / jnp.sqrt(t.astype(x.dtype))
        scale = jnp.where(count > 0, 1.0 / jnp.maximum(count, 1.0), 0.0)
        decay = jnp.where(count > 0, 1.0 - step_t * reg_param, 1.0)
        w_cand = w * decay - step_t * scale * g
        updated = count > 0
        # MLlib isConverged: consecutive iterates, only once a previous
        # update exists (GradientDescent.runMiniBatchSGD)
        diff = jnp.linalg.norm(w - w_cand)
        bound = convergence_tol * jnp.maximum(jnp.linalg.norm(w_cand), 1.0)
        hit = updated & (n_updates >= 1) & (diff < bound)
        w_new = jnp.where(converged, w, w_cand)
        converged_new = converged | (~converged & hit)
        n_updates_new = n_updates + jnp.where(
            updated & ~converged, 1, 0
        ).astype(n_updates.dtype)
        return (w_new, converged_new, n_updates_new), None

    return step


@partial(
    jax.jit,
    static_argnames=("num_iterations", "loss", "full_batch", "weighted"),
)
def _run_sgd(
    features: jnp.ndarray,
    labels: jnp.ndarray,
    step_size: float,
    mini_batch_fraction: float,
    reg_param: float,
    seed,
    convergence_tol: float,
    num_iterations: int,
    loss: str,
    full_batch: bool,
    sample_mask: jnp.ndarray | None = None,
    weighted: bool = False,
    weight_pos=1.0,
    weight_neg=1.0,
):
    x = features
    y = labels
    ones = jnp.ones_like(y) if sample_mask is None else sample_mask
    step = _make_scan_step(
        x, y, ones, step_size, mini_batch_fraction, reg_param, seed,
        convergence_tol, loss, full_batch, weighted=weighted,
        weight_pos=weight_pos, weight_neg=weight_neg,
    )
    w0 = jnp.zeros((x.shape[1],), dtype=x.dtype)
    carry0 = (w0, jnp.asarray(False), jnp.asarray(0, jnp.int32))
    (w_final, _, _), _ = jax.lax.scan(
        step, carry0, jnp.arange(1, num_iterations + 1)
    )
    return w_final


@partial(
    jax.jit,
    static_argnames=("n_iterations", "loss", "full_batch", "weighted"),
)
def _run_sgd_chunk(
    carry,
    t_start,
    features: jnp.ndarray,
    labels: jnp.ndarray,
    step_size: float,
    mini_batch_fraction: float,
    reg_param: float,
    seed,
    convergence_tol: float,
    n_iterations: int,
    loss: str,
    full_batch: bool,
    sample_mask: jnp.ndarray | None = None,
    weighted: bool = False,
    weight_pos=1.0,
    weight_neg=1.0,
):
    """Iterations ``t_start+1 .. t_start+n_iterations`` of the same
    scan :func:`_run_sgd` runs monolithically, resuming from ``carry``
    = ``(w, converged, n_updates)``. Iteration indices are absolute,
    so the per-iteration step sizes and Bernoulli sample keys match
    the monolithic engine exactly — a chunked run replays the same
    trajectory, which is what makes mid-train checkpoint/restore
    (models.linear fit_elastic) transparent to the result."""
    x = features
    y = labels
    ones = jnp.ones_like(y) if sample_mask is None else sample_mask
    step = _make_scan_step(
        x, y, ones, step_size, mini_batch_fraction, reg_param, seed,
        convergence_tol, loss, full_batch, weighted=weighted,
        weight_pos=weight_pos, weight_neg=weight_neg,
    )
    carry, _ = jax.lax.scan(
        step, carry, t_start + jnp.arange(1, n_iterations + 1)
    )
    return carry


def partial_fit_carry(n_features: int, weights=None):
    """A fresh ``(w, converged, n_updates)`` chunk carry for the
    streaming partial-fit surface: zero weights by default, or a warm
    start from an existing float32 weight vector (the serving
    lifecycle stages its candidate from the live model's weights)."""
    w = (
        jnp.zeros((int(n_features),), jnp.float32)
        if weights is None
        else jnp.asarray(weights, jnp.float32)
    )
    return (w, jnp.asarray(False), jnp.asarray(0, jnp.int32))


def partial_fit_linear(
    carry,
    t_start: int,
    features,
    labels,
    config: SGDConfig,
    n_iterations: int,
    sample_mask=None,
):
    """One streaming partial-fit chunk: iterations ``t_start+1 ..
    t_start+n_iterations`` of the MLlib-SGD scan over the CURRENT
    (bounded) feedback matrix, resuming from ``carry``.

    This is the serving lifecycle's training seam (serve/lifecycle.py)
    over :func:`_run_sgd_chunk`: absolute iteration indices keep the
    per-iteration step sizes and Bernoulli keys on the one true
    trajectory, so a SIGKILL'd adapter that restores its checkpointed
    carry and replays the remaining chunks produces byte-identical
    weights. ``features`` has a STATIC row capacity with
    ``sample_mask`` marking the live rows (the population engine's
    inert-member seam), so a growing feedback buffer retriggers zero
    recompiles; ``t_start`` rides traced for the same reason. The
    ``sgd_invocation`` kwargs discipline applies: unweighted configs
    omit the weight kwargs, building the byte-identical pre-knob
    program.

    Returns the new ``(w, converged, n_updates)`` carry.
    """
    weight_kwargs = (
        dict(
            weighted=True,
            weight_pos=float(config.weight_pos),
            weight_neg=float(config.weight_neg),
        )
        if config.weighted
        else {}
    )
    return _run_sgd_chunk(
        carry,
        jnp.asarray(t_start, jnp.int32),
        jnp.asarray(features, jnp.float32),
        jnp.asarray(labels, jnp.float32),
        float(config.step_size),
        float(config.mini_batch_fraction),
        float(config.reg_param),
        int(config.seed),
        float(config.convergence_tol),
        n_iterations=int(n_iterations),
        loss=config.loss,
        full_batch=config.mini_batch_fraction >= 1.0,
        sample_mask=(
            None if sample_mask is None
            else jnp.asarray(sample_mask, jnp.float32)
        ),
        **weight_kwargs,
    )


def sgd_invocation(x_arr, y_arr, config: SGDConfig, sample_mask=None):
    """(jitted, args, kwargs) for the engine exactly as
    :func:`train_linear` invokes it — the single source of the
    ``_run_sgd`` call contract, so AOT inspectors (the driver dryrun's
    collective-structure check) lower the same program production
    runs rather than a hand-copied approximation."""
    args = (
        x_arr,
        y_arr,
        float(config.step_size),
        float(config.mini_batch_fraction),
        float(config.reg_param),
        int(config.seed),
        float(config.convergence_tol),
    )
    kwargs = dict(
        num_iterations=int(config.num_iterations),
        loss=config.loss,
        full_batch=config.mini_batch_fraction >= 1.0,
        sample_mask=sample_mask,
    )
    if config.weighted:
        # unweighted calls omit these kwargs (Python binds the same
        # defaults either way): with the static ``weighted=False`` the
        # scan body contains NO weight arithmetic, so unweighted
        # trajectories are bit-identical to the pre-knob engine
        # (pinned in tests/test_seizure_pipeline.py)
        kwargs.update(
            weighted=True,
            weight_pos=float(config.weight_pos),
            weight_neg=float(config.weight_neg),
        )
    return _run_sgd, args, kwargs


def train_linear(
    features: np.ndarray,
    labels: np.ndarray,
    config: SGDConfig,
    mesh=None,
) -> np.ndarray:
    """Train a linear model; returns (d,) float32 weights.

    With ``mesh``, the batch is sharded over the mesh's data axis and
    the gradient matvec's contraction over samples becomes an ICI
    all-reduce inserted by XLA — the TPU equivalent of MLlib's
    per-iteration ``treeAggregate`` over executors, minus the
    per-iteration driver round trip.
    """
    if mesh is not None:
        from ..parallel import mesh as pmesh

        x_arr, y_arr, mask = pmesh.shard_batch_with_mask(mesh, features, labels)
    else:
        x_arr = jnp.asarray(features, dtype=jnp.float32)
        y_arr = jnp.asarray(labels, dtype=jnp.float32)
        mask = None
    fn, args, kwargs = sgd_invocation(x_arr, y_arr, config, sample_mask=mask)
    return np.asarray(fn(*args, **kwargs))


def train_linear_elastic(
    features: np.ndarray,
    labels: np.ndarray,
    config: SGDConfig,
    manager,
    chunk_iters: int = 10,
    save_every: int = 1,
    max_restarts: int = 3,
    sentinel=None,
    probe_on_failure: bool = True,
    mesh=None,
) -> np.ndarray:
    """:func:`train_linear` with mid-train checkpoint/restore.

    The iteration scan runs in ``chunk_iters``-sized chunks through
    ``obs.failure.elastic_train``: every chunk's carry ``(w,
    converged, n_updates)`` checkpoints under ``manager``, so a
    transient failure (device loss, injected ``device.step`` chaos
    fault) restores the latest carry and replays only the
    un-checkpointed iterations — instead of restarting the whole SGD
    run from zero weights. Absolute iteration indices keep the
    per-iteration step sizes and sample keys identical to the
    monolithic engine.

    Returns (d,) float32 weights, like :func:`train_linear`.
    """
    from ..obs import chaos, failure

    if mesh is not None:
        from ..parallel import mesh as pmesh

        x_arr, y_arr, sample_mask = pmesh.shard_batch_with_mask(
            mesh, features, labels
        )
    else:
        x_arr = jnp.asarray(features, dtype=jnp.float32)
        y_arr = jnp.asarray(labels, dtype=jnp.float32)
        sample_mask = None
    total = int(config.num_iterations)
    full_batch = config.mini_batch_fraction >= 1.0
    chunks = [
        (t0, min(int(chunk_iters), total - t0))
        for t0 in range(0, total, int(chunk_iters))
    ]
    d = x_arr.shape[1]

    def init_state():
        return {
            "w": jnp.zeros((d,), x_arr.dtype),
            "converged": jnp.asarray(False),
            "n_updates": jnp.asarray(0, jnp.int32),
        }

    # the sgd_invocation discipline: weight kwargs ride only on
    # weighted configs, so the unweighted elastic call reads exactly
    # like the unweighted monolithic one
    weight_kwargs = (
        dict(
            weighted=True,
            weight_pos=float(config.weight_pos),
            weight_neg=float(config.weight_neg),
        )
        if config.weighted
        else {}
    )

    def chunk_step(state, t0, n):
        from ..obs import events

        # telemetry: one event per elastic chunk — a crash report
        # shows exactly how far training got before the failure
        events.event("train.sgd_chunk", t0=int(t0), iters=int(n))
        # host-level chaos injection point: a chunk is one "device
        # step" of the elastic driver
        chaos.maybe_fire("device.step")
        w, converged, n_updates = _run_sgd_chunk(
            (state["w"], state["converged"], state["n_updates"]),
            t0,
            x_arr,
            y_arr,
            float(config.step_size),
            float(config.mini_batch_fraction),
            float(config.reg_param),
            int(config.seed),
            float(config.convergence_tol),
            n_iterations=int(n),
            loss=config.loss,
            full_batch=full_batch,
            sample_mask=sample_mask,
            **weight_kwargs,
        )
        new = {"w": w, "converged": converged, "n_updates": n_updates}
        # the weight norm is the sentinel's loss stream: divergence
        # (non-finite weights) surfaces as a non-finite "loss"
        return new, jnp.linalg.norm(w)

    state, _, _ = failure.elastic_train(
        manager,
        init_state,
        chunk_step,
        lambda: list(chunks),
        max_restarts=max_restarts,
        save_every=save_every,
        sentinel=sentinel,
        probe_on_failure=probe_on_failure,
    )
    return np.asarray(state["w"])


@jax.jit
def predict_margin(features: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    return features @ weights
