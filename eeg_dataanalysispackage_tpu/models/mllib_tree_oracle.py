"""Exact float64 host emulation of Spark MLlib 1.6.2 decision trees.

Companion to ``models/mllib_oracle.py`` (which plays this role for
``GradientDescent``): a plain-NumPy re-enactment of what the
reference's JVM computes when ``DecisionTreeClassifier.java:127`` runs
``new DecisionTree(strategy).run(rdd)`` and
``RandomForestClassifier.java:129`` runs
``new RandomForest(strategy, numTrees, featureSubsetStrategy, 12345)
.run(rdd)`` — every float64 operation in the order MLlib 1.6.2's
``tree.RandomForest``/``tree.DecisionTree`` perform it.

What is emulated exactly (and why it is *deterministic* for DT):

- **Split sketch** (``DecisionTree.findSplitsForContinuousFeature``):
  thresholds are *observed feature values* chosen by the
  count-stride walk over sorted distinct values — NOT interpolated
  quantiles. The sketch runs on a sample only when
  ``numExamples > max(maxBins^2, 10000)``; the reference's corpora are
  far below that, so the sampler's ``fraction`` is 1.0 and *no RNG
  affects the sketch* (``DecisionTree.findSplitsBins``:
  ``requiredSamples = max(metadata.maxBins * metadata.maxBins,
  10000)``; a ``BernoulliSampler`` at fraction 1.0 keeps every row).
- **Bin semantics** (``TreePoint.findBin``): bin ``b`` covers
  ``(split(b-1), split(b)]`` — a value *equal* to a threshold goes
  left. NumPy equivalent: ``searchsorted(thresholds, v, 'left')``.
- **maxPossibleBins** = ``min(maxBins, numExamples)``
  (``DecisionTreeMetadata.buildMetadata``), so a 7-row training set
  has at most 6 candidate splits per feature regardless of
  ``config_max_bins``.
- **Gain semantics** (``InformationGainStats`` via
  ``calculateGainForSplit``): a split is *invalid* when either child's
  **Long-truncated** weighted count is below ``minInstancesPerNode``
  or when ``gain < minInfoGain`` (default 0.0 — an exactly-zero gain
  is a *valid* split, but the node still becomes a leaf because
  ``findBestSplits`` marks ``isLeaf = stats.gain <= 0``);
  ``gain = impurity - leftWeight*leftImpurity -
  rightWeight*rightImpurity`` with the weights formed from the Long
  counts — this exact association order is mirrored so near-tie
  argmaxes bit-match.
- **Tie-break**: ``maxBy(_._2.gain)`` keeps the *first* maximum, with
  features iterated in subset order and splits in threshold order —
  NumPy's first-max ``argmax`` over the same iteration order.
- **Leaf rules**: a node is a leaf when its best gain ``<= 0`` or its
  heap level equals ``maxDepth``; a *child* is born a leaf when the
  next level is ``maxDepth`` or its impurity is exactly 0.0 — such
  children are never enqueued (``DecisionTree.findBestSplits``).
- **Prediction**: leaf predicts the first-max class of its weighted
  counts; model prediction walks raw (un-binned) features with
  ``value <= threshold`` going left (``Node.predict``); the forest
  takes an unweighted majority vote (``TreeEnsembleModel
  .predictByVoting``, all ``treeWeights`` 1.0).

For the forest, MLlib's randomness is reproduced at the generator
level (seed 12345, ``RandomForestClassifier.java:104``):

- **Bootstrap**: Poisson(subsamplingRate = 1.0) weights per
  (instance, tree) from commons-math 3 ``PoissonDistribution`` backed
  by a ``Well19937c`` generator reseeded
  ``seed + partitionIndex + 1`` (``BaggedPoint
  .convertToBaggedRDDSamplingWithReplacement``). The oracle pins the
  single-partition layout (partitionIndex 0 → Well19937c seed
  ``12346``); on a real cluster the weights — and therefore the whole
  model — depend on how Spark happened to partition the RDD (see
  *Environmental dependences* below).
- **Per-node feature subsets**: ``numFeaturesPerNode`` =
  ``ceil(sqrt(numFeatures))`` for classification under ``auto``
  (→ "sqrt"; ``DecisionTreeMetadata.buildMetadata``), drawn by
  reservoir sampling over ``0 until numFeatures``
  (``SamplingUtils.reservoirSampleAndCount``) with a Spark
  ``XORShiftRandom`` seeded from ``new scala.util.Random(seed)
  .nextLong()`` — one draw per queued node, consumed in FIFO queue
  order (``RandomForest.selectNodesToSplit``). The reservoir is left
  in draw order (NOT sorted); feature iteration order — and hence
  gain tie-breaks — follow it.

Environmental dependences of the JVM (why bit-exact RF emulation is
*impossible in principle* and what the oracle pins instead):

1. ``parallelize(...)`` partition count equals the cluster's default
   parallelism (local[*] → host core count), and each partition
   reseeds its own Poisson stream — the reference's RF model is a
   function of the submitting machine's core count. Oracle: 1
   partition.
2. Child nodes are re-enqueued by iterating a scala ``Map`` keyed by
   tree index (``nodesForGroup``); for >4 trees its iteration order
   follows scala's hash-trie internals, which shifts which
   ``rng.nextLong()`` seeds which node's reservoir. Oracle: ascending
   tree index (exact for ≤1 tree; canonical otherwise).
3. ``maxMemoryInMB`` (default 256) can split a level into several
   groups on huge bin counts, interleaving draws. Oracle: unbounded
   group (correct for every corpus this package targets).

The DT path has none of these (numTrees=1 → ``featureSubsetStrategy
"all"`` → no subset draws; bootstrap replaced by weight-1.0
``convertToBaggedRDDWithoutSampling``; seed 0 unused), so
``oracle_decision_tree`` is an *exact, RNG-free* float64 re-enactment
for every corpus small enough that the sketch fraction is 1.0.

The JVM RNG tower is re-implemented bit-faithfully from the published
algorithms (java.util.Random LCG; Spark ``XORShiftRandom`` = scala
``MurmurHash3.bytesHash`` seed-hash + 21/35/4 xorshift; commons-math
``Well19937c`` + the multiplicative Knuth Poisson sampler), with
regression pins in ``tests/test_mllib_tree_parity.py``.

No JVM runs in this environment, so fixture values pinned from this
oracle are the package's reproducible contract for MLlib-tree
behavior — same posture as ``models/mllib_oracle.py``'s SGD pins.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


def _i32(x: int) -> int:
    x &= _M32
    return x - (1 << 32) if x >= (1 << 31) else x


def _i64(x: int) -> int:
    x &= _M64
    return x - (1 << 64) if x >= (1 << 63) else x


# --------------------------------------------------------------------------
# java.util.Random (the LCG behind scala.util.Random)
# --------------------------------------------------------------------------


class JavaRandom:
    """java.util.Random: 48-bit LCG, the engine behind
    ``new scala.util.Random(seed)`` in ``RandomForest.run``."""

    _MULT = 0x5DEECE66D
    _ADD = 0xB
    _MASK = (1 << 48) - 1

    def __init__(self, seed: int) -> None:
        self.set_seed(seed)

    def set_seed(self, seed: int) -> None:
        self._state = (seed ^ self._MULT) & self._MASK

    def next(self, bits: int) -> int:
        self._state = (self._state * self._MULT + self._ADD) & self._MASK
        return _i32(self._state >> (48 - bits))

    def next_long(self) -> int:
        hi = self.next(32)
        lo = self.next(32)
        return _i64((hi << 32) + lo)


# --------------------------------------------------------------------------
# scala.util.hashing.MurmurHash3.bytesHash + Spark's XORShiftRandom
# --------------------------------------------------------------------------


def _rotl32(x: int, r: int) -> int:
    x &= _M32
    return ((x << r) | (x >> (32 - r))) & _M32


def scala_murmur3_bytes(data: bytes, seed: int) -> int:
    """scala 2.10 ``MurmurHash3.bytesHash`` (murmur3_x86_32 body with
    scala's tail/finalization), returning a signed Int."""
    h = seed & _M32
    n = len(data)
    i = 0
    remaining = n
    while remaining >= 4:
        k = (
            data[i]
            | (data[i + 1] << 8)
            | (data[i + 2] << 16)
            | (data[i + 3] << 24)
        )
        k = (k * 0xCC9E2D51) & _M32
        k = _rotl32(k, 15)
        k = (k * 0x1B873593) & _M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
        i += 4
        remaining -= 4
    k = 0
    if remaining == 3:
        k ^= data[i + 2] << 16
    if remaining >= 2:
        k ^= data[i + 1] << 8
    if remaining >= 1:
        k ^= data[i]
        k = (k * 0xCC9E2D51) & _M32
        k = _rotl32(k, 15)
        k = (k * 0x1B873593) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return _i32(h)


_SCALA_ARRAY_SEED = 0x3C074A61  # MurmurHash3.arraySeed


class XORShiftRandom:
    """Spark's ``org.apache.spark.util.random.XORShiftRandom``: a
    java.util.Random subclass whose ``next(bits)`` is a 21/35/4
    xorshift over a MurmurHash3-whitened seed.  The seed hash mirrors
    Spark 1.6's quirk of hashing a ``ByteBuffer.allocate(Long.SIZE)``
    buffer — ``Long.SIZE`` is 64 *bits*, so the hashed message is the
    8 seed bytes (big-endian) followed by 56 zeros."""

    def __init__(self, init: int) -> None:
        self._seed = self.hash_seed(init)

    @staticmethod
    def hash_seed(seed: int) -> int:
        data = (seed & _M64).to_bytes(8, "big") + b"\x00" * 56
        low = scala_murmur3_bytes(data, _SCALA_ARRAY_SEED)
        high = scala_murmur3_bytes(data, low)
        return _i64((high << 32) | (low & _M32))

    def next(self, bits: int) -> int:
        s = self._seed & _M64
        s ^= (s << 21) & _M64
        s ^= s >> 35
        s ^= (s << 4) & _M64
        self._seed = s
        return _i32(s & ((1 << bits) - 1))

    def next_double(self) -> float:
        # java.util.Random.nextDouble over the overridden next()
        return ((self.next(26) << 27) + self.next(27)) * (2.0 ** -53)


# --------------------------------------------------------------------------
# commons-math3 Well19937c + PoissonDistribution sampler
# --------------------------------------------------------------------------


class Well19937c:
    """commons-math3 ``Well19937c`` (the default generator inside
    ``PoissonDistribution``): 624-word WELL lattice, parameters
    (m1, m2, m3) = (70, 179, 449), Matsumoto–Kurita tempering."""

    _R = 624
    _M1 = 70
    _M2 = 179
    _M3 = 449

    def __init__(self, seed: Optional[int] = None) -> None:
        self.v = [0] * self._R
        self.index = 0
        if seed is not None:
            self.set_seed_long(seed)

    def set_seed_long(self, seed: int) -> None:
        s = seed & _M64
        self.set_seed_ints([_i32(s >> 32), _i32(s & _M32)])

    def set_seed_ints(self, seed: Sequence[int]) -> None:
        # AbstractWell.setSeed(int[]): copy, then MT-style spread
        v = [0] * self._R
        for i, x in enumerate(list(seed)[: self._R]):
            v[i] = _i32(x)
        for i in range(len(seed), self._R):
            l = v[i - len(seed)]  # sign-extended int -> long
            v[i] = _i32((1812433253 * (l ^ (l >> 30)) + i) & _M32)
        self.v = v
        self.index = 0

    def next(self, bits: int) -> int:
        R, v = self._R, self.v
        idx = self.index
        i_rm1 = (idx + R - 1) % R
        i_rm2 = (idx + R - 2) % R
        v0 = v[idx] & _M32
        vm1 = v[(idx + self._M1) % R] & _M32
        vm2 = v[(idx + self._M2) % R] & _M32
        vm3 = v[(idx + self._M3) % R] & _M32
        z0 = ((0x80000000 & v[i_rm1]) ^ (0x7FFFFFFF & v[i_rm2])) & _M32
        z1 = ((v0 ^ ((v0 << 25) & _M32)) ^ (vm1 ^ (vm1 >> 27))) & _M32
        z2 = ((vm2 >> 9) ^ (vm3 ^ (vm3 >> 1))) & _M32
        z3 = (z1 ^ z2) & _M32
        z4 = (
            z0
            ^ (z1 ^ ((z1 << 9) & _M32))
            ^ (z2 ^ ((z2 << 21) & _M32))
            ^ (z3 ^ (z3 >> 21))
        ) & _M32
        v[idx] = _i32(z3)
        v[i_rm1] = _i32(z4)
        v[i_rm2] = _i32(v[i_rm2] & 0x80000000)
        self.index = i_rm1
        # Matsumoto-Kurita tempering (the "c" in Well19937c)
        z4 = (z4 ^ ((z4 << 7) & 0xE46E1700)) & _M32
        z4 = (z4 ^ ((z4 << 15) & 0x9B868000)) & _M32
        return z4 >> (32 - bits)

    def next_double(self) -> float:
        # BitsStreamGenerator.nextDouble: 26+26 bits * 2^-52
        high = self.next(26) << 26
        low = self.next(26)
        return (high | low) * (2.0 ** -52)


def poisson_sample(rng: Well19937c, mean: float = 1.0) -> int:
    """commons-math3 ``PoissonDistribution.sample`` for ``mean < 40``:
    Knuth's multiplicative method over ``rng.next_double()``."""
    p = math.exp(-mean)
    n = 0
    r = 1.0
    while n < 1000 * mean:
        r *= rng.next_double()
        if r >= p:
            n += 1
        else:
            return n
    return n


def reservoir_sample_range(n: int, k: int, seed: int) -> List[int]:
    """``SamplingUtils.reservoirSampleAndCount(Range(0, n).iterator,
    k, seed)``: first-k fill, then each later item ``i`` replaces slot
    ``(nextDouble() * itemsSeen).toLong`` when that lands below ``k``.
    The result is left in reservoir order (NOT sorted) — feature
    iteration order, and hence gain tie-breaks, follow it."""
    if n <= k:
        return list(range(n))
    reservoir = list(range(k))
    rand = XORShiftRandom(seed)
    seen = k
    for item in range(k, n):
        seen += 1
        replacement = int(rand.next_double() * seen)
        if replacement < k:
            reservoir[replacement] = item
    return reservoir


# --------------------------------------------------------------------------
# Split sketch (DecisionTree.findSplitsForContinuousFeature)
# --------------------------------------------------------------------------


def find_splits_for_continuous_feature(
    samples: np.ndarray, num_splits: int
) -> np.ndarray:
    """Candidate thresholds for one continuous feature, exactly as
    MLlib 1.6.2 computes them: if there are at most ``num_splits``
    distinct values, every distinct value is a threshold; otherwise a
    stride walk over the sorted (value, count) sequence emits the
    previous value whenever adding the current count would move the
    cumulative count further from the running target."""
    samples = np.asarray(samples, dtype=np.float64)
    values, counts = np.unique(samples, return_counts=True)
    if len(values) <= num_splits:
        return values.astype(np.float64, copy=True)
    stride = len(samples) / (num_splits + 1)  # Double division
    out: List[float] = []
    target = stride
    current = int(counts[0])
    for index in range(1, len(values)):
        previous = current
        current += int(counts[index])
        if abs(previous - target) < abs(current - target):
            out.append(float(values[index - 1]))
            target += stride
    return np.array(out, dtype=np.float64)


def find_splits_bins(
    features: np.ndarray, max_bins: int
) -> List[np.ndarray]:
    """Per-feature threshold arrays (ragged), MLlib
    ``findSplitsBins`` semantics: ``maxPossibleBins = min(maxBins,
    numExamples)``; the sketch runs over *all* rows because every
    corpus this package targets satisfies ``numExamples <=
    max(maxPossibleBins^2, 10000)`` (sampling fraction 1.0 — see
    module docstring)."""
    features = np.asarray(features, dtype=np.float64)
    n, d = features.shape
    max_possible_bins = min(max_bins, n)
    num_splits = max_possible_bins - 1
    required = max(max_possible_bins * max_possible_bins, 10000)
    if n > required:  # pragma: no cover - beyond targeted corpus sizes
        raise NotImplementedError(
            "corpus large enough to trigger MLlib's sampled sketch "
            f"({n} > {required}); the sampled path is "
            "partition-layout-dependent on the JVM and is not emulated"
        )
    out = []
    for j in range(d):
        th = find_splits_for_continuous_feature(features[:, j], num_splits)
        if len(th) == 0:
            # only reachable when num_splits == 0 (a 1-row corpus or
            # max_bins <= 1): a constant feature still yields itself
            # as a threshold via the <=num_splits branch above
            raise ValueError(
                f"no candidate splits for feature {j} (num_splits="
                f"{num_splits}); MLlib asserts splits.length > 0 and "
                "would abort too"
            )
        out.append(th)
    return out


def bin_features_mllib(
    features: np.ndarray, thresholds: List[np.ndarray]
) -> np.ndarray:
    """``TreePoint.findBin``: bin ``b`` covers ``(split(b-1),
    split(b)]`` — equality goes LEFT, i.e. ``searchsorted(...,
    'left')`` (the production path's historical ``'right'`` convention
    was aligned to this; see ``trees.bin_features``)."""
    features = np.asarray(features, dtype=np.float64)
    n, d = features.shape
    binned = np.empty((n, d), dtype=np.int32)
    for j in range(d):
        binned[:, j] = np.searchsorted(thresholds[j], features[:, j], side="left")
    return binned


# --------------------------------------------------------------------------
# Impurity / gain (float64, MLlib association order)
# --------------------------------------------------------------------------

_INVALID_GAIN = -np.finfo(np.float64).max  # Double.MinValue


def _calculate(counts: np.ndarray, impurity: str) -> float:
    """Gini.calculate / Entropy.calculate on weighted class counts."""
    total = float(counts.sum())
    if total == 0.0:
        return 0.0
    if impurity == "entropy":
        acc = 0.0
        for c in counts:
            if c != 0.0:
                freq = c / total
                acc -= freq * (math.log(freq) / math.log(2.0))
        return acc
    acc = 0.0
    for c in counts:
        freq = c / total
        acc += freq * freq
    return 1.0 - acc


def _predict_from(counts: np.ndarray) -> float:
    """ImpurityCalculator.predict: first-max class index."""
    return float(int(np.argmax(counts)))


@dataclass
class GainStats:
    gain: float
    left_counts: np.ndarray
    right_counts: np.ndarray
    left_impurity: float
    right_impurity: float


def _gain_for_split(
    left_counts: np.ndarray,
    right_counts: np.ndarray,
    node_impurity: float,
    impurity: str,
    min_instances: int,
    min_info_gain: float = 0.0,
) -> GainStats:
    """``calculateGainForSplit``: Long-truncated counts gate
    minInstances; weights are formed from those Longs; the gain is
    accumulated in MLlib's exact association order."""
    left_count = int(float(left_counts.sum()))  # stats.sum.toLong
    right_count = int(float(right_counts.sum()))
    if left_count < min_instances or right_count < min_instances:
        return GainStats(_INVALID_GAIN, left_counts, right_counts, 0.0, 0.0)
    total = left_count + right_count
    left_imp = _calculate(left_counts, impurity)
    right_imp = _calculate(right_counts, impurity)
    left_weight = left_count / float(total)
    right_weight = right_count / float(total)
    gain = node_impurity - left_weight * left_imp - right_weight * right_imp
    if gain < min_info_gain:
        return GainStats(_INVALID_GAIN, left_counts, right_counts, 0.0, 0.0)
    return GainStats(gain, left_counts, right_counts, left_imp, right_imp)


# --------------------------------------------------------------------------
# Tree growth (RandomForest.run / DecisionTree.findBestSplits)
# --------------------------------------------------------------------------


@dataclass
class OracleNode:
    """One node of the emulated tree, heap-indexed like MLlib's
    ``Node`` (root id 1; children ``2i``/``2i+1``; level =
    ``floor(log2(id))``)."""

    id: int
    predict: float = 0.0
    impurity: float = 0.0
    is_leaf: bool = True
    split_feature: int = -1
    split_threshold: float = 0.0
    left: Optional["OracleNode"] = None
    right: Optional["OracleNode"] = None
    # growth-time state (sample indices reaching this node)
    idx: Optional[np.ndarray] = field(default=None, repr=False)

    @property
    def level(self) -> int:
        return self.id.bit_length() - 1  # Node.indexToLevel

    def predict_row(self, row: np.ndarray) -> float:
        node = self
        while not node.is_leaf and node.left is not None:
            if row[node.split_feature] <= node.split_threshold:
                node = node.left
            else:
                node = node.right
        return node.predict


def _class_counts(
    labels: np.ndarray, idx: np.ndarray, weights: np.ndarray, n_classes: int
) -> np.ndarray:
    counts = np.zeros(n_classes, dtype=np.float64)
    for c in range(n_classes):
        counts[c] = float(weights[idx[labels[idx] == c]].sum())
    return counts


def _best_split_for_node(
    node: OracleNode,
    binned: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    thresholds: List[np.ndarray],
    feature_subset: Optional[List[int]],
    impurity: str,
    min_instances: int,
    n_classes: int,
) -> Tuple[int, int, GainStats, np.ndarray, float]:
    """binsToBestSplit over the node's samples: first-max over
    features in subset order, splits in threshold order.  Returns
    (feature, split_idx, stats, total_counts, node_impurity)."""
    idx = node.idx
    assert idx is not None
    features_iter = (
        feature_subset if feature_subset is not None else range(binned.shape[1])
    )
    total_counts = _class_counts(labels, idx, weights, n_classes)
    node_impurity = _calculate(total_counts, impurity)
    best: Tuple[int, int, GainStats] = (-1, -1, GainStats(
        _INVALID_GAIN, total_counts, total_counts, 0.0, 0.0
    ))
    best_gain = _INVALID_GAIN
    for f in features_iter:
        n_splits = len(thresholds[f])
        if n_splits == 0:
            continue
        # per-(bin, class) weighted histogram for this feature
        hist = np.zeros((n_splits + 1, n_classes), dtype=np.float64)
        for c in range(n_classes):
            sel = idx[labels[idx] == c]
            np.add.at(hist[:, c], binned[sel, f], weights[sel])
        cum = hist.cumsum(axis=0)
        for s in range(n_splits):
            left_counts = cum[s].copy()
            right_counts = total_counts - left_counts
            stats = _gain_for_split(
                left_counts,
                right_counts,
                node_impurity,
                impurity,
                min_instances,
            )
            if stats.gain > best_gain:  # strict: first max wins
                best_gain = stats.gain
                best = (f, s, stats)
    return best[0], best[1], best[2], total_counts, node_impurity


def _grow_forest_oracle(
    features: np.ndarray,
    labels: np.ndarray,
    bag_weights: np.ndarray,  # (T, n) float64 instance weights
    thresholds: List[np.ndarray],
    *,
    impurity: str,
    max_depth: int,
    min_instances: int,
    num_features_per_node: int,
    node_rng: Optional[JavaRandom],
    n_classes: int = 2,
) -> List[OracleNode]:
    """The FIFO node-queue loop of ``RandomForest.run``: groups are
    whole queue snapshots (maxMemoryInMB unbounded — module
    docstring #3); per-node feature subsets are drawn in queue order
    at selection time; children are re-enqueued per tree in ascending
    tree index (exact for a single tree; canonical otherwise)."""
    binned = bin_features_mllib(features, thresholds)
    n, d = binned.shape
    T = bag_weights.shape[0]
    subsampling = num_features_per_node < d

    roots = [OracleNode(id=1, idx=np.arange(n)) for _ in range(T)]
    queue: deque = deque((t, roots[t]) for t in range(T))

    while queue:
        group = list(queue)
        queue.clear()
        # selectNodesToSplit: one nextLong per queued node, queue order
        subsets: Dict[int, Optional[List[int]]] = {}
        for gi, (t, node) in enumerate(group):
            if subsampling:
                assert node_rng is not None
                subsets[gi] = reservoir_sample_range(
                    d, num_features_per_node, node_rng.next_long()
                )
            else:
                subsets[gi] = None
        # findBestSplits application + child enqueue, canonical order:
        # ascending tree index, nodes within a tree in queue order
        order = sorted(range(len(group)), key=lambda gi: (group[gi][0], gi))
        for gi in order:
            t, node = group[gi]
            f, s, stats, total_counts, node_imp = _best_split_for_node(
                node,
                binned,
                labels,
                bag_weights[t],
                thresholds,
                subsets[gi],
                impurity,
                min_instances,
                n_classes,
            )
            node.predict = _predict_from(total_counts)
            node.impurity = node_imp
            is_leaf = stats.gain <= 0.0 or node.level == max_depth
            node.is_leaf = is_leaf
            if is_leaf:
                node.idx = None
                continue
            node.split_feature = f
            node.split_threshold = float(thresholds[f][s])
            idx = node.idx
            assert idx is not None
            go_left = binned[idx, f] <= s
            child_level_is_max = node.level + 1 == max_depth
            left = OracleNode(
                id=2 * node.id,
                predict=_predict_from(stats.left_counts),
                impurity=stats.left_impurity,
                is_leaf=child_level_is_max or stats.left_impurity == 0.0,
                idx=idx[go_left],
            )
            right = OracleNode(
                id=2 * node.id + 1,
                predict=_predict_from(stats.right_counts),
                impurity=stats.right_impurity,
                is_leaf=child_level_is_max or stats.right_impurity == 0.0,
                idx=idx[~go_left],
            )
            node.left, node.right = left, right
            node.idx = None
            if not left.is_leaf:
                queue.append((t, left))
            if not right.is_leaf:
                queue.append((t, right))
    return roots


# --------------------------------------------------------------------------
# Public entry points
# --------------------------------------------------------------------------


def oracle_decision_tree(
    features: np.ndarray,
    labels: np.ndarray,
    *,
    max_bins: int = 32,
    impurity: str = "gini",
    max_depth: int = 5,
    min_instances: int = 1,
) -> OracleNode:
    """``new DecisionTree(strategy).run(rdd)``: numTrees=1,
    featureSubsetStrategy "all", weight-1 bagging — fully
    deterministic (no RNG is consumed; see module docstring)."""
    features = np.asarray(features, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64).astype(np.int64)
    thresholds = find_splits_bins(features, max_bins)
    bag = np.ones((1, len(y)), dtype=np.float64)
    roots = _grow_forest_oracle(
        features,
        y,
        bag,
        thresholds,
        impurity=impurity,
        max_depth=max_depth,
        min_instances=min_instances,
        num_features_per_node=features.shape[1],
        node_rng=None,
    )
    return roots[0]


def num_features_per_node(
    strategy: str, num_features: int, num_trees: int
) -> int:
    """``DecisionTreeMetadata.buildMetadata`` featureSubsetStrategy
    resolution for classification."""
    if strategy == "auto":
        strategy = "all" if num_trees == 1 else "sqrt"
    if strategy == "all":
        return num_features
    if strategy == "sqrt":
        return int(math.ceil(math.sqrt(num_features)))
    if strategy == "log2":
        return max(1, int(math.ceil(math.log(num_features) / math.log(2))))
    if strategy == "onethird":
        return int(math.ceil(num_features / 3.0))
    raise ValueError(f"unknown featureSubsetStrategy: {strategy!r}")


def poisson_bag_weights(
    n: int, num_trees: int, seed: int, subsample: float = 1.0
) -> np.ndarray:
    """``BaggedPoint.convertToBaggedRDDSamplingWithReplacement`` on a
    single partition: one Well19937c reseeded ``seed + 0 + 1``, then
    per instance (RDD order) ``num_trees`` Poisson draws."""
    rng = Well19937c(seed + 1)
    w = np.empty((num_trees, n), dtype=np.float64)
    for i in range(n):
        for t in range(num_trees):
            w[t, i] = float(poisson_sample(rng, subsample))
    return w


def oracle_random_forest(
    features: np.ndarray,
    labels: np.ndarray,
    *,
    num_trees: int = 100,
    feature_subset_strategy: str = "auto",
    max_bins: int = 32,
    impurity: str = "gini",
    max_depth: int = 5,
    min_instances: int = 1,
    seed: int = 12345,
) -> List[OracleNode]:
    """``new RandomForest(strategy, numTrees, featureSubsetStrategy,
    seed).run(rdd)`` under the canonical single-partition,
    ascending-tree-order layout (module docstring: *Environmental
    dependences*)."""
    features = np.asarray(features, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64).astype(np.int64)
    n, d = features.shape
    thresholds = find_splits_bins(features, max_bins)
    if num_trees > 1:
        bag = poisson_bag_weights(n, num_trees, seed)
    else:
        bag = np.ones((1, n), dtype=np.float64)
    return _grow_forest_oracle(
        features,
        y,
        bag,
        thresholds,
        impurity=impurity,
        max_depth=max_depth,
        min_instances=min_instances,
        num_features_per_node=num_features_per_node(
            feature_subset_strategy, d, num_trees
        ),
        node_rng=JavaRandom(seed),
    )


def predict_tree(root: OracleNode, features: np.ndarray) -> np.ndarray:
    """``DecisionTreeModel.predict``: raw-feature threshold walk."""
    features = np.asarray(features, dtype=np.float64)
    return np.array(
        [root.predict_row(features[i]) for i in range(features.shape[0])],
        dtype=np.float64,
    )


def predict_forest(roots: List[OracleNode], features: np.ndarray) -> np.ndarray:
    """``TreeEnsembleModel.predictByVoting`` with unit tree weights:
    unweighted majority vote; a 50/50 tie resolves to the class first
    reaching the maximum in tree order (scala's mutable-map maxBy on
    a 2-entry map keeps the first maximal entry in insertion order,
    i.e. the class the earliest tree voted for)."""
    features = np.asarray(features, dtype=np.float64)
    votes = np.stack([predict_tree(r, features) for r in roots])  # (T, n)
    n = features.shape[0]
    out = np.empty(n, dtype=np.float64)
    for i in range(n):
        tally: Dict[float, float] = {}
        for t in range(votes.shape[0]):
            v = votes[t, i]
            tally[v] = tally.get(v, 0.0) + 1.0
        best_v, best_c = None, -1.0
        for v, c in tally.items():  # insertion order = first-vote order
            if c > best_c:
                best_v, best_c = v, c
        out[i] = best_v
    return out


def tree_depth(root: OracleNode) -> int:
    if root.is_leaf or root.left is None:
        return 0
    return 1 + max(tree_depth(root.left), tree_depth(root.right))


def tree_node_count(root: OracleNode) -> int:
    if root.is_leaf or root.left is None:
        return 1
    return 1 + tree_node_count(root.left) + tree_node_count(root.right)
