"""On-device (XLA) histogram tree growth: the whole forest at once.

The host path (``models/trees.py``) grows trees level-by-level with
numpy bincounts — already MLlib's aggregation shape
(per-(node, feature, bin, class) histograms, SURVEY.md section 2.2
"Spark MLlib -> histogram-based DT/RF built from batched jnp
reductions"). This module is the same algorithm as one jitted XLA
program:

- nodes live in a **heap layout** (node ``i`` -> children ``2i+1``,
  ``2i+2``), so a tree of depth D is a set of fixed-shape arrays of
  length ``2^(D+1)-1`` — no dynamic allocation, no Python recursion;
- each level is ONE batched scatter-add building every node's
  (feature, bin, class) histogram simultaneously, followed by a
  vectorized gain argmax — compiler-friendly control flow only;
- the forest dimension is ``vmap``: all of a random forest's trees
  (each with its own bootstrap sample and per-node feature masks) grow
  in the same XLA program, histograms batched as (T, nodes, d, bins,
  classes). MLlib ships tree-at-a-time jobs; here tree-parallelism is
  a batch axis.

Split semantics match the host grower exactly (same gain formula, same
validity rules, same first-max tie-break over the same (feature, bin)
layout); the only intended divergence is RNG plumbing: host RF draws
feature subsets lazily per *splittable* node, the device path pre-draws
a mask per heap slot (``draw_feature_masks``), so host and device
forests are each deterministic but not bit-identical to each other.
Single trees with no feature subsetting agree exactly (pinned by
tests/test_trees_device.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-12


def n_heap_nodes(max_depth: int) -> int:
    return 2 ** (max_depth + 1) - 1


def _impurity(counts: jnp.ndarray, kind: str) -> jnp.ndarray:
    """counts (..., 2) -> impurity (...). f32 throughout."""
    total = counts.sum(axis=-1, keepdims=True)
    p = counts / jnp.maximum(total, _EPS)
    if kind == "entropy":
        # log(x)/log(2), matching the host grower and MLlib's
        # Entropy.log2 so near-tie argmaxes track the same formulation
        return -(
            p * (jnp.log(jnp.maximum(p, _EPS)) / math.log(2.0))
        ).sum(axis=-1)
    return 1.0 - (p * p).sum(axis=-1)


#: deepest tree the device backend accepts: heap storage is 2^(D+1)-1
#: slots, so beyond this the dense layout loses to the host grower's
#: active-frontier representation (MLlib allows maxDepth up to 30).
MAX_DEVICE_DEPTH = 12


def _check_device_depth(max_depth: int) -> None:
    if max_depth > MAX_DEVICE_DEPTH:
        raise ValueError(
            f"device tree backend supports max_depth <= {MAX_DEVICE_DEPTH} "
            f"(heap storage is 2^(depth+1)-1 slots); got {max_depth} — "
            "use backend='host' for deeper trees"
        )


def draw_feature_masks(
    n_trees: int,
    n_nodes: int,
    d: int,
    subset: Optional[int],
    seed: int = 12345,
) -> np.ndarray:
    """(T, n_nodes, d) bool — per-heap-slot feature availability.

    ``n_nodes`` only needs to cover *internal* slots
    (``n_heap_nodes(max_depth - 1)``): the deepest level never splits.
    ``subset=None`` (or >= d) means all features everywhere. The draw
    is host-side numpy (seeded like the reference's fixed RF seed,
    RandomForestClassifier.java:104) because it is setup, not compute;
    a vectorized argsort draw keeps it O(T·n_nodes·d log d) with no
    Python-level per-node loop.
    """
    if subset is None or subset >= d:
        return np.ones((n_trees, n_nodes, d), dtype=bool)
    rng = np.random.RandomState(seed)
    order = rng.rand(n_trees, n_nodes, d).argsort(axis=-1)
    return order < subset


def _grow_heap_tree(
    binned: jnp.ndarray,  # (n, d) int32 in [0, max_bins)
    channel_w: jnp.ndarray,  # (n, 2) f32 per-sample channel weights
    *,
    max_bins: int,
    max_depth: int,
    node_pred_fn,
    split_fn,
) -> Dict[str, jnp.ndarray]:
    """Shared level-by-level heap growth (the frontier mechanics both
    the classification and regression growers run).

    Per level, every node's two-channel (feature, bin) histogram is
    ONE matmul of the node/channel one-hot against the per-sample bin
    one-hot — TPU scatters are sort-based and an order of magnitude
    slower than this formulation (sums are exact in f32 below 2^24
    weight magnitude per node).

    ``node_pred_fn(tot) -> (L,)`` maps per-node channel totals
    ``tot (2, L)`` to predictions. ``split_fn(hist2, tot, offset, L)
    -> (flat_score (L, d*(B-1)) with -inf at invalid, accept_fn)``
    scores candidate splits; ``accept_fn(best_score) -> (L,) bool``
    applies the grower's acceptance rule (the shared loop adds only
    finiteness). First-max argmax over the (feature, bin) flat layout
    is the host growers' tie-break.
    """
    n, d = binned.shape
    B = max_bins
    n_nodes = n_heap_nodes(max_depth)

    feature = jnp.full((n_nodes,), -1, jnp.int32)
    thresh = jnp.full((n_nodes,), -1, jnp.int32)
    pred = jnp.zeros((n_nodes,), jnp.float32)
    assign = jnp.zeros((n,), jnp.int32)  # every sample starts at the root

    oh_bins = (
        (binned[:, :, None] == jnp.arange(B, dtype=jnp.int32)[None, None, :])
        .astype(jnp.float32)
        .reshape(n, d * B)
    )

    for level in range(max_depth + 1):
        offset = 2**level - 1
        L = 2**level
        local = assign - offset
        live = (local >= 0) & (local < L)  # at this level & not a leaf

        # dead samples map to the out-of-range index -1 -> zero rows
        oh = jax.nn.one_hot(
            jnp.where(live, local, -1), L, dtype=jnp.float32
        )
        A = jnp.concatenate(
            [oh * channel_w[:, 0][:, None], oh * channel_w[:, 1][:, None]],
            axis=1,
        )  # (n, 2L)
        hist2 = jax.lax.dot_general(
            A,
            oh_bins,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(2, L, d, B)

        tot = hist2.sum(axis=3)[:, :, 0]  # (2, L) — identical per feature
        pred = jax.lax.dynamic_update_slice(
            pred, node_pred_fn(tot), (offset,)
        )

        if level == max_depth:
            break  # deepest level: predictions only, no further splits

        flat_score, accept_fn = split_fn(hist2, tot, offset, L)
        best = jnp.argmax(flat_score, axis=1).astype(jnp.int32)  # first max
        best_score = jnp.take_along_axis(flat_score, best[:, None], axis=1)[
            :, 0
        ]
        bf = best // (B - 1)
        bb = best % (B - 1)

        splittable = jnp.isfinite(best_score) & accept_fn(best_score)
        feature = jax.lax.dynamic_update_slice(
            feature, jnp.where(splittable, bf, -1), (offset,)
        )
        thresh = jax.lax.dynamic_update_slice(
            thresh, jnp.where(splittable, bb, -1), (offset,)
        )

        # route live samples at split nodes to their heap children
        node_split = jnp.where(
            live, jnp.take(splittable, jnp.clip(local, 0, L - 1)), False
        )
        feat_of_sample = jnp.take(bf, jnp.clip(local, 0, L - 1))
        thr_of_sample = jnp.take(bb, jnp.clip(local, 0, L - 1))
        sample_bin = jnp.take_along_axis(
            binned, feat_of_sample[:, None].astype(jnp.int32), axis=1
        )[:, 0]
        go_right = (sample_bin > thr_of_sample).astype(jnp.int32)
        assign = jnp.where(node_split, 2 * assign + 1 + go_right, assign)

    return {"feature": feature, "threshold_bin": thresh, "prediction": pred}


def _grow_one(
    binned: jnp.ndarray,  # (n, d) int32 in [0, max_bins)
    labels: jnp.ndarray,  # (n,) int32 in {0, 1}
    feature_mask: jnp.ndarray,  # (internal nodes, d) bool
    *,
    max_bins: int,
    impurity: str,
    max_depth: int,
    min_instances: int,
) -> Dict[str, jnp.ndarray]:
    """Single classification tree (gini/entropy); vmapped over the
    forest axis by the caller. Channels are the class indicators, so
    the shared histogram is the per-(node, feature, bin, class) count
    table (MLlib's aggregation shape)."""
    B = max_bins
    d = binned.shape[1]
    y = labels.astype(jnp.int32)
    channel_w = jnp.stack(
        [(y == 0), (y == 1)], axis=1
    ).astype(jnp.float32)

    def node_pred_fn(tot):
        m = tot[0] + tot[1]
        pos = tot[1]
        return jnp.where(pos * 2 > m, 1.0, 0.0)

    def split_fn(hist2, tot, offset, L):
        hist = jnp.moveaxis(hist2, 0, -1)  # (L, d, B, 2)
        node_counts = jnp.stack([tot[0], tot[1]], axis=1)  # (L, 2)
        m = node_counts.sum(-1)
        pos = node_counts[:, 1]
        cum = jnp.cumsum(hist, axis=2)  # (L, d, B, 2)
        left = cum[:, :, :-1, :]  # split "bin <= b", b in [0, B-2]
        right = cum[:, :, -1:, :] - left
        nl = left.sum(-1)
        nr = right.sum(-1)
        valid = (nl >= min_instances) & (nr >= min_instances)
        valid &= feature_mask[offset : offset + L][:, :, None]
        parent_imp = _impurity(node_counts, impurity)  # (L,)
        # MLlib association order: impurity - lw*lImp - rw*rImp
        # (InformationGainStats.calculateGainForSplit), mirrored by the
        # host grower and models/mllib_tree_oracle.py so near-tie
        # argmaxes agree across all three
        mm = jnp.maximum(m, _EPS)[:, None, None]
        gain = (
            parent_imp[:, None, None]
            - (nl / mm) * _impurity(left, impurity)
            - (nr / mm) * _impurity(right, impurity)
        )
        gain = jnp.where(valid, gain, -jnp.inf)

        def accept(best_gain):
            return (
                (m >= 2 * min_instances)
                & (pos > 0)
                & (pos < m)
                & (best_gain > 0)
            )

        return gain.reshape(L, d * (B - 1)), accept

    return _grow_heap_tree(
        binned,
        channel_w,
        max_bins=max_bins,
        max_depth=max_depth,
        node_pred_fn=node_pred_fn,
        split_fn=split_fn,
    )


@partial(
    jax.jit,
    static_argnames=(
        "max_bins",
        "impurity",
        "max_depth",
        "min_instances",
        "tree_chunk",
    ),
)
def grow_forest(
    binned: jnp.ndarray,  # (n, d) int32 — the base (un-bootstrapped) data
    labels: jnp.ndarray,  # (n,) int32
    bootstrap: jnp.ndarray,  # (T, n) int32 sample indices per tree
    feature_masks: jnp.ndarray,  # (T, internal nodes, d) bool
    *,
    max_bins: int,
    impurity: str,
    max_depth: int,
    min_instances: int,
    tree_chunk: int = 8,
) -> Dict[str, jnp.ndarray]:
    """Grow T trees simultaneously.

    Trees are vmapped in chunks of ``tree_chunk`` (``lax.map`` over
    chunks). The dataset is stored once; each chunk gathers its own
    bootstrap view, so peak memory is the chunk's (n, d*max_bins) bin
    one-hots — ``tree_chunk * n * d * max_bins * 4`` bytes — never a
    dense (T, n, d) replica of the training set."""
    _check_device_depth(max_depth)

    def grow(args):
        boot, fm = args
        return _grow_one(
            jnp.take(binned, boot, axis=0),
            jnp.take(labels, boot),
            fm,
            max_bins=max_bins,
            impurity=impurity,
            max_depth=max_depth,
            min_instances=min_instances,
        )

    return jax.lax.map(
        grow,
        (bootstrap, feature_masks),
        batch_size=min(tree_chunk, bootstrap.shape[0]),
    )


def grow_forest_sharded(
    binned: np.ndarray,  # (n, d) int32 — the base (un-bootstrapped) data
    labels: np.ndarray,  # (n,) int32
    bootstrap: np.ndarray,  # (T, n) int32 sample indices per tree
    feature_masks: np.ndarray,  # (T, internal nodes, d) bool
    *,
    mesh,
    max_bins: int,
    impurity: str,
    max_depth: int,
    min_instances: int,
) -> Dict[str, jnp.ndarray]:
    """Tree-parallel forest growth over a device mesh.

    The forest axis is the natural parallel dimension (MLlib grows
    trees as independent jobs, RandomForest.scala via
    ``RandomForestClassifier.java:104``); here each device grows
    ``T / n_devices`` trees of the same vmapped program: bootstrap
    indices and feature masks are sharded over the mesh's first axis,
    the (n, d) dataset and labels are replicated, and XLA runs the
    per-tree histogram growth with zero cross-device traffic until the
    caller gathers the heap arrays. ``T`` is padded up to a multiple
    of the mesh size with repeat trees, then trimmed, so any
    ``config_num_trees`` works on any mesh.
    """
    _check_device_depth(max_depth)
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    n_dev = mesh.devices.shape[0]
    T = bootstrap.shape[0]
    pad = (-T) % n_dev
    if pad:
        bootstrap = np.concatenate([bootstrap, bootstrap[:pad]], axis=0)
        feature_masks = np.concatenate(
            [feature_masks, feature_masks[:pad]], axis=0
        )
    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    forest = _grow_all_vmapped(
        jax.device_put(jnp.asarray(binned, jnp.int32), repl),
        jax.device_put(jnp.asarray(labels, jnp.int32), repl),
        jax.device_put(jnp.asarray(bootstrap, jnp.int32), shard),
        jax.device_put(jnp.asarray(feature_masks), shard),
        max_bins=max_bins,
        impurity=impurity,
        max_depth=max_depth,
        min_instances=min_instances,
    )
    return {k: v[:T] for k, v in forest.items()}


@partial(
    jax.jit,
    static_argnames=("max_bins", "impurity", "max_depth", "min_instances"),
)
def _grow_all_vmapped(
    binned, labels, bootstrap, feature_masks, *, max_bins, impurity,
    max_depth, min_instances,
):
    def grow(boot_i, fm_i):
        return _grow_one(
            jnp.take(binned, boot_i, axis=0),
            jnp.take(labels, boot_i),
            fm_i,
            max_bins=max_bins,
            impurity=impurity,
            max_depth=max_depth,
            min_instances=min_instances,
        )

    return jax.vmap(grow)(bootstrap, feature_masks)


def _grow_one_reg(
    binned: jnp.ndarray,  # (n, d) int32 in [0, max_bins)
    residuals: jnp.ndarray,  # (n,) f32
    *,
    max_bins: int,
    max_depth: int,
    min_instances: int,
) -> Dict[str, jnp.ndarray]:
    """Variance-reduction regression tree in heap layout (the GBT
    grower — host twin: trees._grow_regression_tree).

    Same shared frontier loop as :func:`_grow_one`
    (:func:`_grow_heap_tree`), but the two channels are
    (count, sum of residuals) instead of class counts: the
    SSE-reduction argmax only needs ``sl^2/nl + sr^2/nr`` (the
    sum-of-squares terms cancel between parent and children). Split
    acceptance matches the host grower: best score must beat the
    parent's ``S^2/m`` by 1e-12.
    """
    B = max_bins
    d = binned.shape[1]
    r = residuals.astype(jnp.float32)
    channel_w = jnp.stack([jnp.ones_like(r), r], axis=1)

    def node_pred_fn(tot):
        return tot[1] / jnp.maximum(tot[0], _EPS)

    def split_fn(hist2, tot, offset, L):
        cnt, s1 = hist2[0], hist2[1]
        m, S = tot[0], tot[1]
        ccnt = jnp.cumsum(cnt, axis=2)
        cs1 = jnp.cumsum(s1, axis=2)
        nl = ccnt[:, :, :-1]  # (L, d, B-1)
        sl = cs1[:, :, :-1]
        nr = m[:, None, None] - nl
        sr = S[:, None, None] - sl
        score = sl * sl / jnp.maximum(nl, _EPS) + sr * sr / jnp.maximum(
            nr, _EPS
        )
        valid = (nl >= min_instances) & (nr >= min_instances)
        score = jnp.where(valid, score, -jnp.inf)
        parent_score = S * S / jnp.maximum(m, _EPS)

        def accept(best_score):
            return (m >= 2 * min_instances) & (
                best_score > parent_score + 1e-12
            )

        return score.reshape(L, d * (B - 1)), accept

    return _grow_heap_tree(
        binned,
        channel_w,
        max_bins=max_bins,
        max_depth=max_depth,
        node_pred_fn=node_pred_fn,
        split_fn=split_fn,
    )


def _predict_heap_tree(feature, thresh, pred, binned, max_depth):
    """(n,) leaf values for one heap tree (shared walk)."""
    node = jnp.zeros((binned.shape[0],), jnp.int32)
    for _ in range(max_depth):
        f = jnp.take(feature, node)
        is_leaf = f < 0
        sample_bin = jnp.take_along_axis(
            binned, jnp.maximum(f, 0)[:, None].astype(jnp.int32), axis=1
        )[:, 0]
        go_right = (sample_bin > jnp.take(thresh, node)).astype(jnp.int32)
        node = jnp.where(is_leaf, node, 2 * node + 1 + go_right)
    return jnp.take(pred, node)


@partial(
    jax.jit,
    static_argnames=(
        "rounds", "max_bins", "max_depth", "min_instances",
    ),
)
def boost_gbt(
    binned: jnp.ndarray,  # (n, d) int32
    labels: jnp.ndarray,  # (n,) f32 in {0, 1}
    *,
    rounds: int,
    learning_rate: float,
    max_bins: int,
    max_depth: int,
    min_instances: int,
) -> Dict[str, jnp.ndarray]:
    """The whole GBT boosting loop as ONE XLA program.

    ``lax.scan`` over rounds: residual = y - sigmoid(F), grow a
    regression tree (fixed-shape heap), F += lr * tree(x). MLlib runs
    each round as separate Spark jobs; here the 100-round loop is one
    compiled program with no host round trips. Returns stacked heap
    arrays (rounds, n_nodes).
    """
    _check_device_depth(max_depth)
    y = labels.astype(jnp.float32)

    def body(F, _):
        residual = y - jax.nn.sigmoid(F)
        tree = _grow_one_reg(
            binned,
            residual,
            max_bins=max_bins,
            max_depth=max_depth,
            min_instances=min_instances,
        )
        contrib = _predict_heap_tree(
            tree["feature"], tree["threshold_bin"], tree["prediction"],
            binned, max_depth,
        )
        return F + learning_rate * contrib, tree

    _, trees = jax.lax.scan(
        body, jnp.zeros_like(y), None, length=rounds
    )
    return trees


@partial(jax.jit, static_argnames=("max_depth",))
def predict_forest(
    forest: Dict[str, jnp.ndarray],
    binned: jnp.ndarray,  # (n, d) int32
    max_depth: int,
) -> jnp.ndarray:
    """(T trees, n samples) heap walk -> (n,) mean vote in [0, 1]."""
    votes = jax.vmap(
        lambda f, t, p: _predict_heap_tree(f, t, p, binned, max_depth)
    )(forest["feature"], forest["threshold_bin"], forest["prediction"])
    return votes.mean(axis=0)


@partial(jax.jit, static_argnames=("max_iters",))
def predict_linked_forest(
    feature: jnp.ndarray,  # (T, n_nodes) int32, -1 = leaf
    thresh: jnp.ndarray,  # (T, n_nodes) int32
    left: jnp.ndarray,  # (T, n_nodes) int32
    right: jnp.ndarray,  # (T, n_nodes) int32
    pred: jnp.ndarray,  # (T, n_nodes) f32
    binned: jnp.ndarray,  # (n, d) int32
    max_iters: int = 64,
) -> jnp.ndarray:
    """(T, n) leaf values for explicit-link trees (the host storage
    format, `trees._Tree.to_arrays`) — device inference for forests
    of ANY origin, including host-grown/loaded ones where the heap
    walk of :func:`predict_forest` does not apply. ``max_iters``
    bounds the walk like the host `_predict_tree`'s depth bound."""
    n = binned.shape[0]

    def one(f, t, l, r, p):
        def body(node, _):
            fo = jnp.take(f, node)
            is_leaf = fo < 0
            sample_bin = jnp.take_along_axis(
                binned, jnp.maximum(fo, 0)[:, None].astype(jnp.int32),
                axis=1,
            )[:, 0]
            go_left = sample_bin <= jnp.take(t, node)
            nxt = jnp.where(
                go_left, jnp.take(l, node), jnp.take(r, node)
            )
            return jnp.where(is_leaf, node, nxt), None

        node, _ = jax.lax.scan(
            body, jnp.zeros((n,), jnp.int32), None, length=max_iters
        )
        return jnp.take(p, node)

    return jax.vmap(one)(feature, thresh, left, right, pred)


@partial(jax.jit, static_argnames=("max_iters", "row_chunk"))
def predict_linked_forest_chunked(
    feature, thresh, left, right, pred, binned,
    max_iters: int = 64, row_chunk: int = 8192,
):
    """:func:`predict_linked_forest` with the row axis processed in
    ``row_chunk`` blocks via ``lax.map`` — same result, bounded
    working set. Built as the fallback measurement for the r4 chip
    observation that the full-size program faulted the TPU worker
    process (tools/sweep_results/r4/rf_predict.err): if the chunked
    form runs where the monolith faults, the fault is size-dependent,
    not a construct problem. Requires ``n % row_chunk == 0`` (bench
    sizes are powers of two; pad otherwise)."""
    n = binned.shape[0]
    if n % row_chunk:
        raise ValueError(
            f"n {n} must be a multiple of row_chunk {row_chunk}"
        )
    blocks = binned.reshape(n // row_chunk, row_chunk, binned.shape[1])
    votes = jax.lax.map(
        lambda b: predict_linked_forest(
            feature, thresh, left, right, pred, b, max_iters=max_iters
        ),
        blocks,
    )  # (n_blocks, T, row_chunk)
    return jnp.moveaxis(votes, 0, 1).reshape(feature.shape[0], n)


def host_trees_to_device(trees: list):
    """Pad a list of host-format tree dicts to one (T, n_nodes) array
    set for :func:`predict_linked_forest` (padding nodes are leaves
    predicting 0 and are unreachable from the root)."""
    n_nodes = max(t["feature"].shape[0] for t in trees)

    def pad(key, fill, dtype):
        out = np.full((len(trees), n_nodes), fill, dtype)
        for i, t in enumerate(trees):
            arr = np.asarray(t[key])
            out[i, : arr.shape[0]] = arr
        return jnp.asarray(out)

    return (
        pad("feature", -1, np.int32),
        pad("threshold_bin", -1, np.int32),
        pad("left", -1, np.int32),
        pad("right", -1, np.int32),
        pad("prediction", 0.0, np.float32),
    )


def heap_to_host_arrays(forest: Dict[str, jnp.ndarray]) -> list:
    """Device heap forest -> the host path's per-tree array dicts
    (explicit left/right links), so persistence and the host
    ``_predict_tree`` work unchanged on device-grown trees."""
    out = []
    feature = np.asarray(forest["feature"])
    thresh = np.asarray(forest["threshold_bin"])
    pred = np.asarray(forest["prediction"], dtype=np.float64)
    n_nodes = feature.shape[1]
    for t in range(feature.shape[0]):
        split = feature[t] >= 0
        idx = np.arange(n_nodes)
        left = np.where(split, 2 * idx + 1, -1).astype(np.int32)
        right = np.where(split, 2 * idx + 2, -1).astype(np.int32)
        out.append(
            {
                "feature": feature[t],
                "threshold_bin": thresh[t],
                "left": left,
                "right": right,
                "prediction": pred[t],
            }
        )
    return out
