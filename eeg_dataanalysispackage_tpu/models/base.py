"""Classifier plugin boundary (reference: Classification/IClassifier.java).

Same public seam as the reference — ``set_feature_extraction``,
``train``, ``test``, ``save``, ``load``, ``set_config`` with opaque
``config_*`` string maps (IClassifier.java:43-85) — but stateless-by-
construction: model parameters are explicit pytrees threaded through
pure jitted functions, never mutable static fields (the reference's
classifiers share state through ``static fe``/``model`` fields, e.g.
LogisticRegressionClassifier.java:50-51, making one instance per JVM
the only safe configuration; SURVEY.md section 5 'race detection').
"""

from __future__ import annotations

import abc
import logging
from typing import Dict, Optional, Sequence

import numpy as np

from . import stats
from ..features import base as features_base

logger = logging.getLogger(__name__)


class Classifier(abc.ABC):
    """Batched classifier over extracted features."""

    # True for classifiers whose reference counterpart builds stats
    # from MulticlassMetrics' confusion matrix only (MLlib paths),
    # leaving MSE/class sums at 0; False for the incremental NN path.
    confusion_only_stats: bool = True

    def __init__(self) -> None:
        self.fe: Optional[features_base.FeatureExtraction] = None
        self.config: Dict[str, str] = {}

    # -- reference surface --------------------------------------------

    def set_feature_extraction(self, fe: features_base.FeatureExtraction) -> None:
        self.fe = fe

    def set_config(self, config: Dict[str, str]) -> None:
        self.config = dict(config)

    def train(
        self,
        epochs: Sequence[np.ndarray] | np.ndarray,
        targets: Sequence[float] | np.ndarray,
        fe: features_base.FeatureExtraction,
    ) -> None:
        from ..obs import events

        self.fe = fe
        with events.span(
            "model.extract", classifier=type(self).__name__
        ):
            features = self._extract(epochs)
        labels = np.asarray(targets, dtype=np.float64)
        with events.span(
            "model.fit", classifier=type(self).__name__,
            rows=int(labels.shape[0]),
        ):
            self.fit(features, labels)

    def train_elastic(
        self,
        epochs: Sequence[np.ndarray] | np.ndarray,
        targets: Sequence[float] | np.ndarray,
        fe: features_base.FeatureExtraction,
        manager,
        **elastic_kwargs,
    ) -> None:
        """:meth:`train` routed through :meth:`fit_elastic` — the host
        epoch path's entry to checkpointed, restartable training."""
        self.fe = fe
        features = self._extract(epochs)
        labels = np.asarray(targets, dtype=np.float64)
        self.fit_elastic(features, labels, manager, **elastic_kwargs)

    def test(
        self,
        epochs: Sequence[np.ndarray] | np.ndarray,
        targets: Sequence[float] | np.ndarray,
    ) -> stats.ClassificationStatistics:
        return self.test_features(self._extract(epochs), targets)

    def test_features(
        self,
        features: np.ndarray,
        targets: Sequence[float] | np.ndarray,
    ) -> stats.ClassificationStatistics:
        """Evaluate on already-extracted feature rows.

        The single place statistics are built from predictions — used
        by :meth:`test` and by the pipeline's fused device path, where
        features come straight off the accelerator.
        """
        from ..obs import events

        labels = np.asarray(targets, dtype=np.float64)
        with events.span(
            "model.test", classifier=type(self).__name__,
            rows=int(labels.shape[0]),
        ):
            predictions = self.predict(features)
            return stats.ClassificationStatistics.from_arrays(
                predictions, labels,
                confusion_only=self.confusion_only_stats,
            )

    # -- batched core (the TPU-native surface) -------------------------

    @abc.abstractmethod
    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        """(n, d) features + (n,) {0,1} labels -> trained state."""

    def fit_elastic(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        manager,
        save_every: int = 1,
        max_restarts: int = 3,
        sentinel=None,
        chunk_iters: int = 10,
        probe_on_failure: bool = True,
    ) -> None:
        """:meth:`fit` with mid-train checkpoint/restore when the
        classifier's training loop is steppable.

        The SGD/NN families override this to chunk their iteration
        scans through ``obs.failure.elastic_train`` (checkpoints under
        ``manager``, bounded restarts, divergence ``sentinel``). The
        default — classifiers whose training is a single opaque
        program (tree growers) — trains monolithically; there is no
        intermediate state to checkpoint.
        """
        del manager, save_every, max_restarts, sentinel, chunk_iters
        del probe_on_failure
        logger.info(
            "%s has no steppable training loop; elastic mode trains "
            "monolithically (no mid-train checkpoints)",
            type(self).__name__,
        )
        self.fit(features, labels)

    @abc.abstractmethod
    def predict(self, features: np.ndarray) -> np.ndarray:
        """(n, d) -> (n,) real-valued outputs (rounded by stats)."""

    @abc.abstractmethod
    def save(self, path: str) -> None: ...

    @abc.abstractmethod
    def load(self, path: str) -> None: ...

    # ------------------------------------------------------------------

    def _extract(self, epochs) -> np.ndarray:
        if self.fe is None:
            raise ValueError("feature extraction not set")
        arr = np.asarray(epochs, dtype=np.float64)
        if arr.ndim == 2:  # single epoch
            arr = arr[None]
        return np.asarray(self.fe.extract_batch(arr))
