"""Logistic-regression and SVM classifiers (MLlib-SGD semantics).

Parity surfaces of ``Classification/LogisticRegressionClassifier.java``
and ``Classification/SVMClassifier.java``: the same ``config_*`` keys
gate custom vs default hyperparameters exactly as the reference's
all-present checks do (LogisticRegressionClassifier.java:104-112,
SVMClassifier.java:95-109); prediction thresholds match MLlib's
strict comparisons (logreg: sigmoid(margin) > 0.5 i.e. margin > 0,
``LogisticRegressionModel.predictPoint``; svm: margin > 0,
``SVMModel.predictPoint`` — both predict 0.0 at exactly threshold).

Model persistence is a single ``.npz`` with weights + config instead
of MLlib's parquet+json directories.
"""

from __future__ import annotations

import io
import json

import numpy as np

from . import base, sgd


class _LinearClassifier(base.Classifier):
    loss: str = "logistic"
    # config keys that must ALL be present to use custom hyperparams
    required_keys: tuple = ()

    def __init__(self) -> None:
        super().__init__()
        self.weights: np.ndarray | None = None

    def _sgd_config(self) -> sgd.SGDConfig:
        raise NotImplementedError

    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        self.weights = sgd.train_linear(features, labels, self._sgd_config())

    def predict(self, features: np.ndarray) -> np.ndarray:
        if self.weights is None:
            raise ValueError("model not trained or loaded")
        margin = np.asarray(
            sgd.predict_margin(
                np.asarray(features, dtype=np.float32), self.weights
            )
        )
        return (margin > 0.0).astype(np.float64)

    def save(self, path: str) -> None:
        # serialize to bytes, then hand off to the pluggable
        # filesystem (local path or remote URI — the HDFS-parity
        # flow); a stale directory at the raw target is deleted
        # first (LogisticRegressionClassifier.java:144-147)
        from ..io import modelfiles

        modelfiles.delete_local_dir_target(path)
        buf = io.BytesIO()
        np.savez(
            buf,
            weights=self.weights,
            config=json.dumps(self.config),
            kind=self.__class__.__name__,
        )
        fname = path if path.endswith(".npz") else path + ".npz"
        modelfiles.write_model_bytes(fname, buf.getvalue())

    def load(self, path: str) -> None:
        from ..io import modelfiles

        fname = path if path.endswith(".npz") else path + ".npz"
        data = np.load(
            io.BytesIO(modelfiles.read_model_bytes(fname)),
            allow_pickle=False,
        )
        kind = str(data["kind"])
        if kind != self.__class__.__name__:
            raise ValueError(
                f"model at {path} was saved by {kind}, "
                f"not {self.__class__.__name__}"
            )
        self.weights = data["weights"]
        self.config = json.loads(str(data["config"]))


class LogisticRegressionClassifier(_LinearClassifier):
    loss = "logistic"
    required_keys = (
        "config_num_iterations",
        "config_step_size",
        "config_mini_batch_fraction",
    )

    def _sgd_config(self) -> sgd.SGDConfig:
        c = self.config
        if all(k in c for k in self.required_keys):
            # the static train(rdd, iters, step, frac) path constructs
            # LogisticRegressionWithSGD(step, iters, 0.0, frac): no reg
            return sgd.SGDConfig(
                num_iterations=int(c["config_num_iterations"]),
                step_size=float(c["config_step_size"]),
                mini_batch_fraction=float(c["config_mini_batch_fraction"]),
                reg_param=0.0,
                loss="logistic",
            )
        # the no-config path runs the default constructor
        # LogisticRegressionWithSGD(1.0, 100, 0.01, 1.0), whose updater
        # is SquaredL2Updater — L2 with regParam 0.01 applies
        return sgd.SGDConfig(
            num_iterations=100, step_size=1.0, mini_batch_fraction=1.0,
            reg_param=0.01, loss="logistic",
        )


class SVMClassifier(_LinearClassifier):
    loss = "hinge"
    required_keys = (
        "config_num_iterations",
        "config_step_size",
        "config_reg_param",
        "config_mini_batch_fraction",
    )

    def _sgd_config(self) -> sgd.SGDConfig:
        c = self.config
        if all(k in c for k in self.required_keys):
            return sgd.SGDConfig(
                num_iterations=int(c["config_num_iterations"]),
                step_size=float(c["config_step_size"]),
                mini_batch_fraction=float(c["config_mini_batch_fraction"]),
                reg_param=float(c["config_reg_param"]),
                loss="hinge",
            )
        # MLlib SVMWithSGD().run defaults
        return sgd.SGDConfig(
            num_iterations=100, step_size=1.0, mini_batch_fraction=1.0,
            reg_param=0.01, loss="hinge",
        )
