"""Logistic-regression and SVM classifiers (MLlib-SGD semantics).

Parity surfaces of ``Classification/LogisticRegressionClassifier.java``
and ``Classification/SVMClassifier.java``: the same ``config_*`` keys
gate custom vs default hyperparameters exactly as the reference's
all-present checks do (LogisticRegressionClassifier.java:104-112,
SVMClassifier.java:95-109); prediction thresholds match MLlib's
strict comparisons (logreg: sigmoid(margin) > 0.5 i.e. margin > 0,
``LogisticRegressionModel.predictPoint``; svm: margin > 0,
``SVMModel.predictPoint`` — both predict 0.0 at exactly threshold).

Model persistence is a single ``.npz`` with weights + config; MLlib's
parquet+json model *directories* (what an existing reference
deployment has on disk, LogisticRegressionClassifier.java:144-152)
load drop-in too — ``load()`` detects the directory layout and routes
through io/mllib_format.py, adopting the saved intercept and
threshold with MLlib's strict-greater predict semantics.
"""

from __future__ import annotations

import io
import json

import numpy as np

from . import base, sgd


class _LinearClassifier(base.Classifier):
    loss: str = "logistic"
    # config keys that must ALL be present to use custom hyperparams
    required_keys: tuple = ()

    def __init__(self) -> None:
        super().__init__()
        self.weights: np.ndarray | None = None
        # MLlib GLM predict state: margin = x.w + intercept, label =
        # margin > margin_threshold (strict). Natively-trained models
        # keep (0, 0) — MLlib's own defaults (prob 0.5 <=> margin 0) —
        # so behavior is unchanged; imports adopt the saved values.
        self.intercept: float = 0.0
        self.margin_threshold: float = 0.0

    # MLlib class tag this classifier accepts from a model directory
    _mllib_class: str | None = None
    # margin threshold from the saved threshold field: logreg stores a
    # probability (margin = logit(p)), svm a margin (identity)
    @staticmethod
    def _to_margin_threshold(saved: float) -> float:
        raise NotImplementedError

    def _sgd_config(self) -> sgd.SGDConfig:
        raise NotImplementedError

    def _class_weights(self) -> dict:
        """Cost-sensitive class weights from the opaque config
        (``config_weight_pos`` / ``config_weight_neg`` — what the
        pipeline's ``class_weight=`` / ``cost_fp=`` / ``cost_fn=``
        knobs resolve to; docs/workloads.md). Absent keys mean 1.0,
        which trains the exact pre-knob program."""
        return {
            "weight_pos": float(self.config.get("config_weight_pos", 1.0)),
            "weight_neg": float(self.config.get("config_weight_neg", 1.0)),
        }

    def fit(self, features: np.ndarray, labels: np.ndarray) -> None:
        self.weights = sgd.train_linear(features, labels, self._sgd_config())
        # training replaces any imported MLlib state: native MLlib-SGD
        # semantics are interceptless with the margin-0 threshold
        self.intercept = 0.0
        self.margin_threshold = 0.0

    def fit_elastic(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        manager,
        save_every: int = 1,
        max_restarts: int = 3,
        sentinel=None,
        chunk_iters: int = 10,
        probe_on_failure: bool = True,
    ) -> None:
        """MLlib-SGD training with mid-train checkpoint/restore: the
        iteration scan runs in chunks through
        ``obs.failure.elastic_train`` (sgd.train_linear_elastic), so a
        transient mid-train failure restores the latest chunk carry
        instead of restarting from zero weights. Absolute iteration
        indexing keeps the trajectory identical to :meth:`fit`."""
        self.weights = sgd.train_linear_elastic(
            features,
            np.asarray(labels, dtype=np.float64),
            self._sgd_config(),
            manager,
            chunk_iters=chunk_iters,
            save_every=save_every,
            max_restarts=max_restarts,
            sentinel=sentinel,
            probe_on_failure=probe_on_failure,
        )
        self.intercept = 0.0
        self.margin_threshold = 0.0

    def predict(self, features: np.ndarray) -> np.ndarray:
        if self.weights is None:
            raise ValueError("model not trained or loaded")
        if self.weights.dtype == np.float64:
            # imported MLlib weights stay f64 end-to-end so the import
            # predicts bit-identically to the JVM's double margins
            margin = (
                np.asarray(features, dtype=np.float64) @ self.weights
                + self.intercept
            )
        else:
            margin = (
                np.asarray(
                    sgd.predict_margin(
                        np.asarray(features, dtype=np.float32),
                        self.weights,
                    )
                )
                + self.intercept
            )
        return (margin > self.margin_threshold).astype(np.float64)

    def save(self, path: str) -> None:
        # serialize to bytes, then hand off to the pluggable
        # filesystem (local path or remote URI — the HDFS-parity
        # flow); a stale directory at the raw target is deleted
        # first (LogisticRegressionClassifier.java:144-147)
        from ..io import modelfiles

        if self.config.get("config_model_format") == "mllib":
            # query-level reverse migration: save_clf=true&
            # config_model_format=mllib writes the Spark-loadable
            # model directory instead of the native npz
            modelfiles.delete_local_dir_target(path)
            self.export_mllib_dir(path)
            return

        modelfiles.delete_local_dir_target(path)
        buf = io.BytesIO()
        np.savez(
            buf,
            weights=self.weights,
            config=json.dumps(self.config),
            kind=self.__class__.__name__,
            intercept=np.float64(self.intercept),
            margin_threshold=np.float64(self.margin_threshold),
        )
        fname = path if path.endswith(".npz") else path + ".npz"
        modelfiles.write_model_bytes(fname, buf.getvalue())

    def load(self, path: str) -> None:
        from ..io import mllib_format, modelfiles

        if mllib_format.is_model_dir(path):
            self._load_mllib_dir(path)
            return
        fname = path if path.endswith(".npz") else path + ".npz"
        data = np.load(
            io.BytesIO(modelfiles.read_model_bytes(fname)),
            allow_pickle=False,
        )
        kind = str(data["kind"])
        if kind != self.__class__.__name__:
            raise ValueError(
                f"model at {path} was saved by {kind}, "
                f"not {self.__class__.__name__}"
            )
        self.weights = data["weights"]
        self.config = json.loads(str(data["config"]))
        # absent in pre-interchange archives: those models were
        # trained natively, where both are structurally zero
        self.intercept = (
            float(data["intercept"]) if "intercept" in data.files else 0.0
        )
        self.margin_threshold = (
            float(data["margin_threshold"])
            if "margin_threshold" in data.files
            else 0.0
        )

    def export_mllib_dir(self, path: str) -> None:
        """Write this model as a Spark-1.6 MLlib model directory —
        the reverse migration (the artifact
        ``LogisticRegressionModel.load`` / ``SVMModel.load``
        consumes, LogisticRegressionClassifier.java:150-152).
        Weights widen f32 -> f64 exactly; the margin threshold maps
        back to the class's saved-threshold convention."""
        from ..io import mllib_format

        if self.weights is None:
            raise ValueError("model not trained or loaded")
        mllib_format.write_glm(
            path,
            self._mllib_class,
            np.asarray(self.weights, dtype=np.float64),
            intercept=self.intercept,
            threshold=self._from_margin_threshold(self.margin_threshold),
        )

    @staticmethod
    def _from_margin_threshold(margin: float) -> float:
        raise NotImplementedError

    def _load_mllib_dir(self, path: str) -> None:
        """Adopt a reference-deployment MLlib model directory
        (LogisticRegressionClassifier.java:150-152 loads the same
        artifact via ``LogisticRegressionModel.load``)."""
        from ..io import mllib_format

        m = mllib_format.read_glm(path)
        if m.model_class != self._mllib_class:
            raise ValueError(
                f"model dir at {path} holds {m.model_class}, but "
                f"{self.__class__.__name__} loads {self._mllib_class}"
            )
        if m.num_classes != 2:
            # multinomial logreg packs (numClasses-1) weight blocks;
            # the binary margin predict below would misread them
            raise NotImplementedError(
                f"multinomial MLlib model (numClasses="
                f"{m.num_classes}) is not supported; the reference "
                f"pipeline is binary"
            )
        self.weights = m.weights  # f64: routes predict to the f64 path
        self.intercept = m.intercept
        # a cleared threshold (MLlib clearThreshold, raw-score mode)
        # has no label semantics; the pipeline always classifies, so
        # refuse rather than guess
        if m.threshold is None:
            raise ValueError(
                "model dir was saved with a cleared threshold (raw "
                "scores); set one before exporting"
            )
        self.margin_threshold = self._to_margin_threshold(m.threshold)


class LogisticRegressionClassifier(_LinearClassifier):
    loss = "logistic"
    required_keys = (
        "config_num_iterations",
        "config_step_size",
        "config_mini_batch_fraction",
    )
    _mllib_class = (
        "org.apache.spark.mllib.classification.LogisticRegressionModel"
    )

    @staticmethod
    def _to_margin_threshold(saved: float) -> float:
        # LogisticRegressionModel stores a PROBABILITY threshold;
        # sigmoid(margin) > p  <=>  margin > logit(p). The legal
        # extremes map to the constant classifiers they mean in
        # MLlib: p=1 -> score>1 never (always 0), p=0 -> score>0
        # always (always 1) — not a ZeroDivisionError.
        if saved >= 1.0:
            return float("inf")
        if saved <= 0.0:
            return float("-inf")
        return float(np.log(saved / (1.0 - saved)))

    @staticmethod
    def _from_margin_threshold(margin: float) -> float:
        # inverse of _to_margin_threshold: sigmoid maps +/-inf to the
        # constant-classifier probabilities 1.0 / 0.0
        return float(1.0 / (1.0 + np.exp(-margin)))

    def _sgd_config(self) -> sgd.SGDConfig:
        c = self.config
        if all(k in c for k in self.required_keys):
            # the static train(rdd, iters, step, frac) path constructs
            # LogisticRegressionWithSGD(step, iters, 0.0, frac): no reg
            return sgd.SGDConfig(
                num_iterations=int(c["config_num_iterations"]),
                step_size=float(c["config_step_size"]),
                mini_batch_fraction=float(c["config_mini_batch_fraction"]),
                reg_param=0.0,
                loss="logistic",
                **self._class_weights(),
            )
        # the no-config path runs the default constructor
        # LogisticRegressionWithSGD(1.0, 100, 0.01, 1.0), whose updater
        # is SquaredL2Updater — L2 with regParam 0.01 applies
        return sgd.SGDConfig(
            num_iterations=100, step_size=1.0, mini_batch_fraction=1.0,
            reg_param=0.01, loss="logistic", **self._class_weights(),
        )


class SVMClassifier(_LinearClassifier):
    loss = "hinge"
    required_keys = (
        "config_num_iterations",
        "config_step_size",
        "config_reg_param",
        "config_mini_batch_fraction",
    )
    _mllib_class = "org.apache.spark.mllib.classification.SVMModel"

    @staticmethod
    def _to_margin_threshold(saved: float) -> float:
        # SVMModel's threshold IS a margin (SVMModel.predictPoint)
        return float(saved)

    @staticmethod
    def _from_margin_threshold(margin: float) -> float:
        return float(margin)

    def _sgd_config(self) -> sgd.SGDConfig:
        c = self.config
        if all(k in c for k in self.required_keys):
            return sgd.SGDConfig(
                num_iterations=int(c["config_num_iterations"]),
                step_size=float(c["config_step_size"]),
                mini_batch_fraction=float(c["config_mini_batch_fraction"]),
                reg_param=float(c["config_reg_param"]),
                loss="hinge",
                **self._class_weights(),
            )
        # MLlib SVMWithSGD().run defaults
        return sgd.SGDConfig(
            num_iterations=100, step_size=1.0, mini_batch_fraction=1.0,
            reg_param=0.01, loss="hinge", **self._class_weights(),
        )
