"""CLI entry point (reference: Main.java).

``python -m eeg_dataanalysispackage_tpu.pipeline.cli '<query string>'``
mirrors ``spark-submit --class cz.zcu.kiv.Main <jar> '<query string>'``
(Main.java:38-51, README "Deployment"): args[0] is the query string;
failures print a stack trace and exit non-zero (the reference swallows
them — we at least fail loudly).
"""

from __future__ import annotations

import logging
import sys

from . import builder
from .. import obs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # logfile path via LOGFILE_NAME, the -Dlogfile.name analogue
    obs.configure_logging(level=logging.INFO)
    log = logging.getLogger("eeg_dataanalysispackage_tpu")
    log.info("Hello from the TPU-native EEG analysis pipeline")
    log.info("Application started with arguments %s", argv)
    if not argv:
        log.error("usage: cli.py '<query string>' (e.g. "
                  "'info_file=...&fe=dwt-8&train_clf=logreg')")
        return 2
    try:
        statistics = builder.PipelineBuilder(argv[0]).execute()
    except Exception:
        import traceback

        traceback.print_exc()
        return 1
    print(statistics, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
